#!/usr/bin/env python
"""Swarm coordinator entrypoint (reference-parity name, BASELINE.json:5).

Bootstraps the swarm: initial DHT node + rendezvous address + liveness
registry + swarm-level metrics. Prints ``COORDINATOR_READY host:port`` once
listening.

    python coordinator.py --host 0.0.0.0 --port 9000 --metrics swarm.jsonl
"""

import argparse
import asyncio

from distributedvolunteercomputing_tpu.swarm.coordinator import run_coordinator_forever


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0, help="0 = pick a free port")
    ap.add_argument("--metrics", default=None, help="swarm-level metrics JSONL path")
    ap.add_argument("--advertise-host", default=None,
                    help="dialable address to publish when binding 0.0.0.0")
    ap.add_argument("--secret-file", default=None,
                    help="file holding the shared swarm secret; enables "
                         "HMAC frame authentication (all members must use "
                         "the same secret)")
    args = ap.parse_args()
    from distributedvolunteercomputing_tpu.swarm.transport import read_secret

    secret = read_secret(args.secret_file)
    try:
        asyncio.run(
            run_coordinator_forever(
                args.host, args.port, args.metrics, args.advertise_host, secret=secret
            )
        )
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
