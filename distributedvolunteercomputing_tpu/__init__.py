"""distributedvolunteercomputing_tpu — a TPU-native volunteer-computing training framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
``SrinivasBaskar1995/DistributedVolunteerComputing`` (see SURVEY.md — the
reference source mount was empty this round; parity targets come from the
driver metadata in BASELINE.json):

- per-volunteer ``train_step`` compiled with ``jax.jit``/``pjit`` (reference:
  per-worker CUDA train_step, BASELINE.json:5)
- ``GradientAverager`` with synchronous / gossip / butterfly / Byzantine-robust
  modes over a host-side DCN transport (reference: NCCL/gloo GradientAverager +
  gossip + butterfly + Byzantine aggregation, BASELINE.json:5)
- DHT peer discovery, heartbeat liveness, join/leave churn handling
  (reference: coordinator/DHT/heartbeat/join-leave, BASELINE.json:5)
- intra-slice collectives ride ICI via XLA (``jax.lax.psum`` under ``pjit``);
  inter-slice averaging rides DCN via the swarm transport.

Layer map (mirrors SURVEY.md §1):

    L6 entrypoints   coordinator.py / run_volunteer.py (repo root)
    L5 trainer       distributedvolunteercomputing_tpu.training
    L4 averaging     distributedvolunteercomputing_tpu.swarm.{averager,gossip,butterfly,byzantine}
    L3 membership    distributedvolunteercomputing_tpu.swarm.{dht,heartbeat,membership,coordinator}
    L2 transport     distributedvolunteercomputing_tpu.swarm.transport (+ native C++ core)
    L1 compute       distributedvolunteercomputing_tpu.{models,ops,parallel}
"""

from distributedvolunteercomputing_tpu.version import __version__

__all__ = ["__version__"]
