"""On-mesh swarm data path: the wire codec and the robust tile folds run on
the volunteer's local accelerator mesh instead of single-threaded host numpy.

PRs 2–3 made the NETWORK side of an averaging round 3–86× faster, which
left the chip-side data path — bf16↔f32 wire codec, PowerSGD power
iterations, and the per-tile robust folds in ``swarm/agg_stream.py`` — as
the round bottleneck: all of it ran as host-CPU numpy while the volunteer's
TPU slice sat idle between train steps. This module moves those ops onto
the slice:

- **bf16 pack/unpack** (``encode_bf16`` / ``decode_bf16`` /
  ``decode_axpy``): one fused XLA pass (bitcast + widen + axpy) instead of
  the host's decode-then-axpy two-pass, optionally lowered through a Pallas
  kernel on TPU backends (``_enc_kernel`` / ``_dec_axpy_kernel``).
- **window folds** (``aggregate``): coordinate-wise estimators (median,
  trimmed_mean) over an ``[n_peers, tile]`` window run as an UNROLLED
  Batcher sorting network over the peer axis — n is tiny (a round's group),
  so the network is ~n·log²n elementwise min/max passes that XLA fuses and
  parallelizes over the tile dim, where a host column sort is serial.
  Weighted mean folds as one fused multiply-sum.
- **mean accumulation** (``MeshMeanFolder``): the streaming leader's O(D)
  mean accumulator lives ON DEVICE as an ``[n_tiles, tile]`` buffer;
  arriving wire chunks stage as raw bytes and fold in batches via one
  scatter-add (fused bf16-decode + weighted add), overlapped with arrival.
- **PowerSGD** (``low_rank_iterate`` / ``lowrank_reconstruct``): the per-
  tensor ``QR(M·Q)`` / ``MᵀP`` power-iteration matmuls and the decoder's
  ``P·Qᵀ`` reconstruction.

Placement and decomposition policy (mirrors ``ops.robust._TILE_MODES``):

=================  ==========================================================
method             on-mesh path
=================  ==========================================================
mean               device (fused weighted multiply-sum / scatter-add folder)
median             device (sorting network over the peer axis)
trimmed_mean       device (sorting network; trim rows dropped from the sum)
krum / bulyan      host — selection needs float64 pairwise d² (accumulated
                   tile-wise on host by the streaming aggregator) and a
                   discrete argsort pick; shipping rows to device buys
                   nothing over the d²-precomputed host path
geometric_median   host — Weiszfeld's data-dependent early exit
centered_clip      host — data-dependent per-iteration clip radii
=================  ==========================================================

Sharding: every device op runs under ``shard_map`` over a 1-D **codec view**
of the volunteer's ``(dp, sp, pp, ep, tp)`` mesh — the flat f32/bf16 wire
buffers have no model axes, so the natural placement is an even split of the
element dim across ALL local chips (``NamedSharding(P("codec"))``); window
stacks split their tile dim the same way with the peer dim replicated. A
single-device mesh degenerates to plain jit with zero overhead, so one code
path serves the 8-chip slice and the laptop volunteer alike.

Backend selection happens ONCE per volunteer at startup (``configure`` /
``select_backend``): ``"mesh"`` when the default jax backend is TPU silicon
(``utils.jaxenv.tpu_backend``) or when forced via ``DVC_MESH_CODEC=1``;
``"host"`` otherwise (and always under ``DVC_MESH_CODEC=0``) — the host
path delegates straight to ``native``/``ops.robust`` numpy, so a
CPU-platform tier-1 run never pays a jit compile it didn't ask for.

Degraded-slice fallback (mesh-networks paper, PAPERS.md: slice-level
failures are a normal operating mode, not a crash): every device op runs
through ``_run``, and the FIRST failure — a chip dropping out of the local
mesh, a PJRT error, an injected chaos fault — permanently degrades this
codec to the host backend, replays the failed op on host, and surfaces the
reason in ``stats()``. Mid-round state is handled by the callers: the
stateless codec ops re-run losslessly; ``MeshMeanFolder`` pulls its last
good device accumulator back to host and keeps folding there, so a round
in flight COMMITS through a mesh shrink instead of dying with it.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

# Stage this many raw wire bytes before a MeshMeanFolder flush: big enough
# to amortize a device dispatch over many tiles, small enough that folding
# stays overlapped with arrival (a 64 MB contribution flushes ~4 times).
FOLDER_FLUSH_BYTES = 16 << 20


class MeshCodecError(RuntimeError):
    """An injected (chaos) or real device failure inside a mesh op."""


def _batcher_pairs(m: int) -> List[Tuple[int, int]]:
    """Batcher odd-even mergesort compare-exchange pairs for m rows
    (m a power of two) — the static sorting network the window estimators
    unroll over the peer axis."""
    pairs: List[Tuple[int, int]] = []

    def merge(lo: int, cnt: int, r: int) -> None:
        step = r * 2
        if step < cnt:
            merge(lo, cnt, step)
            merge(lo + r, cnt, step)
            for i in range(lo + r, lo + cnt - r, step):
                pairs.append((i, i + r))
        else:
            pairs.append((lo, lo + r))

    def sort(lo: int, cnt: int) -> None:
        if cnt > 1:
            half = cnt // 2
            sort(lo, half)
            sort(lo + half, cnt - half)
            merge(lo, cnt, 1)

    sort(0, m)
    return pairs


# ---------------------------------------------------------------------------
# Pallas kernels (TPU path for the hot bf16 pack/unpack + axpy fold)
# ---------------------------------------------------------------------------
#
# The jnp bodies below already fuse into single XLA passes; the Pallas
# versions exist for the TPU backend, where explicit (rows, 128)-lane
# blocking keeps the codec's VMEM footprint bounded and off the train
# step's working set. They are gated (``_pallas_mode``): compiled on TPU
# silicon, interpreted under DVC_MESH_PALLAS=interpret (CPU equivalence
# tests), and skipped otherwise — a Pallas failure falls back to the jnp
# body, never to the host.

_PALLAS_LANES = 128
_PALLAS_ROWS = 512  # block = (512, 128) f32 -> 256 KB VMEM per operand


def _enc_kernel(x_ref, o_ref):
    import jax

    o_ref[...] = jax.lax.bitcast_convert_type(
        x_ref[...].astype(_jnp().bfloat16), _jnp().uint16
    )


def _dec_axpy_kernel(b_ref, a_ref, w_ref, o_ref):
    o_ref[...] = a_ref[...] + w_ref[0, 0] * _bf16_widen(b_ref[...])


def _jnp():
    import jax.numpy as jnp

    return jnp


def _bf16_widen(bits):
    """THE fused bf16-bits -> f32 expression every device body shares
    (decode, decode+axpy, folder flush, window aggregate_bits) — one home,
    so the lowering can't drift between call sites."""
    import jax

    return jax.lax.bitcast_convert_type(bits, _jnp().bfloat16).astype(_jnp().float32)


class MeshCodec:
    """One volunteer's on-mesh codec + fold engine (or its host fallback).

    ``backend``: "auto" (mesh on TPU silicon / DVC_MESH_CODEC=1, host
    otherwise), "mesh" (force the device path — used by benches and
    equivalence tests on the CPU platform), or "host". ``mesh`` is the
    volunteer's training Mesh; its devices are re-viewed as the 1-D codec
    axis. ``None`` uses the default jax device only.
    """

    def __init__(
        self,
        mesh=None,
        backend: str = "auto",
        pallas: Optional[str] = None,
        collective: Optional[str] = None,
    ):
        if backend not in ("auto", "mesh", "host"):
            raise ValueError(f"unknown mesh-codec backend {backend!r}")
        self._lock = threading.Lock()
        self._mesh_arg = mesh
        self._codec_mesh = None  # built lazily on first device op
        self._ndev = 1
        self._jit_cache: Dict[tuple, Callable] = {}
        self.degraded = False
        self.degrade_reason = ""
        self._fail_injected = 0
        # Optional flight recorder (anything with .record(kind, **fields));
        # attached by the volunteer so a degrade event lands in the
        # telemetry plane's ring buffer beside the depositions and fences.
        self.recorder = None
        # gauges
        self.ops_mesh = 0
        self.ops_host = 0
        self.fallbacks = 0
        self.device_s = 0.0
        # Ring-lowering gauges, written by RingMeanFolder: the configured
        # lowering, the last lowering actually used, and how many flushes
        # were quietly re-lowered to xla by the VMEM estimate. Without
        # these a fleet pinned to xla by DVC_RING_VMEM_MB (or a mis-sized
        # estimate) is indistinguishable from one running the kernel.
        self.ring_lower: Optional[str] = None
        self.ring_lower_effective: Optional[str] = None
        self.ring_lower_fallback: Optional[str] = None
        self.ring_vmem_fallbacks = 0
        self._ring_vmem_warned = False
        self._pallas_mode = self._resolve_pallas(pallas)
        self._backend = self._resolve_backend(backend)
        self._collective = self._resolve_collective(collective)

    # -- selection ---------------------------------------------------------

    @staticmethod
    def _resolve_backend(backend: str) -> str:
        if backend != "auto":
            return backend
        env = os.environ.get("DVC_MESH_CODEC", "").strip().lower()
        if env in ("0", "host", "off"):
            return "host"
        if env in ("1", "mesh", "on"):
            return "mesh"
        try:
            from distributedvolunteercomputing_tpu.utils.jaxenv import tpu_backend

            return "mesh" if tpu_backend() else "host"
        except Exception as e:  # noqa: BLE001 — no usable jax == host codec
            log.debug("mesh codec auto-select failed (%s); using host", errstr(e))
            return "host"

    @staticmethod
    def _resolve_pallas(pallas: Optional[str]) -> str:
        """"compiled" | "interpret" | "off" — the bf16 kernel lowering."""
        if pallas is None:
            pallas = os.environ.get("DVC_MESH_PALLAS", "auto").strip().lower()
        if pallas in ("interpret", "0", "off", "1", "on"):
            return {"1": "compiled", "on": "compiled", "0": "off", "off": "off"}.get(
                pallas, "interpret"
            )
        try:
            from distributedvolunteercomputing_tpu.utils.jaxenv import tpu_backend

            return "compiled" if tpu_backend() else "off"
        except Exception:  # noqa: BLE001
            return "off"

    @staticmethod
    def _resolve_collective(collective: Optional[str]) -> str:
        """"ring" | "off" — the fused reduce pipeline (ops.mesh_collective).

        Explicit "ring"/"off" wins; otherwise DVC_MESH_COLLECTIVE, then
        auto: ring on TPU silicon (where the remote-DMA kernel compiles),
        off elsewhere — the CPU test/bench planes opt in explicitly so the
        PR 5 staged folder stays the default sharded path off-silicon."""
        if collective is None:
            collective = os.environ.get("DVC_MESH_COLLECTIVE", "auto").strip().lower()
        if collective in ("ring", "1", "on"):
            return "ring"
        if collective in ("off", "0", "none", "host"):
            return "off"
        if collective != "auto":
            raise ValueError(f"unknown mesh collective {collective!r}")
        try:
            from distributedvolunteercomputing_tpu.utils.jaxenv import tpu_backend

            return "ring" if tpu_backend() else "off"
        except Exception:  # noqa: BLE001 — no usable jax == no collective
            return "off"

    @property
    def backend(self) -> str:
        return "host" if self.degraded else self._backend

    @property
    def active(self) -> bool:
        """True when device ops are live (mesh backend, not degraded)."""
        return self._backend == "mesh" and not self.degraded

    def stats(self) -> dict:
        return {
            "backend": self.backend,
            "configured": self._backend,
            "devices": self._ndev if self._codec_mesh is not None else None,
            "pallas": self._pallas_mode,
            "collective": self._collective,
            "ops_mesh": int(self.ops_mesh),
            "ops_host": int(self.ops_host),
            "fallbacks": int(self.fallbacks),
            "device_s": round(self.device_s, 6),
            "degraded": bool(self.degraded),
            "degrade_reason": self.degrade_reason,
            "ring_lower": self.ring_lower,
            "ring_lower_effective": self.ring_lower_effective,
            "ring_lower_fallback": self.ring_lower_fallback,
            "ring_vmem_fallbacks": int(self.ring_vmem_fallbacks),
        }

    # -- failure handling --------------------------------------------------

    def inject_failure(self, n: int = 1) -> None:
        """Chaos hook: the next ``n`` device ops raise (a synthetic mesh
        shrink / chip loss), exercising the degrade-to-host path."""
        with self._lock:
            self._fail_injected += int(n)

    def _check_injected(self) -> None:
        with self._lock:
            if self._fail_injected > 0:
                self._fail_injected -= 1
                raise MeshCodecError("injected mesh failure (chaos)")

    def _degrade(self, e: BaseException) -> None:
        with self._lock:
            if self.degraded:
                return  # idempotent: late racers must not re-log/re-count
            self.degraded = True
            self.degrade_reason = errstr(e)
            self.fallbacks += 1
        log.warning(
            "mesh codec degraded to host backend: %s — this volunteer "
            "continues on the host data path", errstr(e),
        )
        if self.recorder is not None:
            # Flight recorder (swarm/telemetry.py): a slice loss mid-round
            # is front-page post-mortem material.
            try:
                self.recorder.record("codec_degraded", reason=errstr(e))
            except Exception:  # noqa: BLE001 — recording must not affect the fallback
                pass

    def _run(self, op: Callable, host: Callable):
        """Run ``op`` on device, falling back to ``host`` (and permanently
        degrading) on ANY failure. The stateless codec ops lose nothing in
        the fallback — the same inputs re-run on host."""
        if not self.active:
            self.ops_host += 1
            return host()
        t0 = time.perf_counter()
        try:
            self._check_injected()
            out = op()
            self.device_s += time.perf_counter() - t0
            self.ops_mesh += 1
            return out
        except Exception as e:  # noqa: BLE001 — chip loss must not kill the round
            self._degrade(e)
            self.ops_host += 1
            return host()

    # -- device plumbing ---------------------------------------------------

    def _ensure_mesh(self):
        """The 1-D codec Mesh (lazy: building it touches the backend)."""
        if self._codec_mesh is None:
            import jax
            from jax.sharding import Mesh

            if self._mesh_arg is not None:
                devices = np.asarray(self._mesh_arg.devices).reshape(-1)
            else:
                devices = np.asarray(jax.devices()[:1])
            self._codec_mesh = Mesh(devices, ("codec",))
            self._ndev = devices.size
        return self._codec_mesh

    def _sharding(self, spec):
        from jax.sharding import NamedSharding

        return NamedSharding(self._ensure_mesh(), spec)

    def _put_flat(self, arr: np.ndarray):
        """Pad a flat host array to an ndev multiple and place it split over
        the codec axis. Returns (device_array, original_size). On a
        single-device codec mesh the host array is handed to jit directly —
        XLA:CPU consumes aligned numpy zero-copy, and the explicit
        device_put would just be a memcpy."""
        import jax
        from jax.sharding import PartitionSpec as P

        self._ensure_mesh()
        n = arr.size
        pad = (-n) % self._ndev
        if pad:
            arr = np.pad(arr, (0, pad))
        if self._ndev == 1:
            return arr, n
        return jax.device_put(arr, self._sharding(P("codec"))), n

    def _put_stack(self, stack: np.ndarray):
        """[n, T] host stack placed with the tile dim split over the codec
        axis (peers replicated). Returns (device_array, original_T)."""
        import jax
        from jax.sharding import PartitionSpec as P

        self._ensure_mesh()
        t = stack.shape[1]
        pad = (-t) % self._ndev
        if pad:
            stack = np.pad(stack, ((0, 0), (0, pad)))
        if self._ndev == 1:
            return stack, t
        return jax.device_put(stack, self._sharding(P(None, "codec"))), t

    def _jit(self, key: tuple, build: Callable[[], Callable]) -> Callable:
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = self._jit_cache[key] = build()
        return fn

    def _shard_map(self, fn, in_specs, out_specs, **jit_kw):
        """jit(shard_map(fn)) over the codec mesh — the SNIPPETS.md [2]
        wrapping pattern. All codec ops are elementwise over the sharded
        dim, so replication checking has nothing to reject; it stays off to
        keep scatter ops eligible. Spans the jax API split: ``jax.shard_map``
        (new, check_vma) when present, ``jax.experimental.shard_map``
        (0.4.x, check_rep) otherwise — tier-1 runs on the old API and the
        MULTICHIP driver on the new one."""
        import jax

        mesh = self._ensure_mesh()
        sm = getattr(jax, "shard_map", None)
        if sm is not None:
            try:
                wrapped = sm(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
            except TypeError:  # intermediate versions: no check_vma kwarg
                wrapped = sm(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
        else:
            from jax.experimental.shard_map import shard_map

            wrapped = shard_map(fn, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_rep=False)
        return jax.jit(wrapped, **jit_kw)

    # -- pallas inner bodies ----------------------------------------------

    def _pallas_encode_local(self, x):
        """Local-shard bf16 pack through the Pallas kernel; caller
        guarantees the shard size divides the (rows, lanes) blocking."""
        import jax
        from jax.experimental import pallas as pl

        jnp = _jnp()
        rows = x.size // _PALLAS_LANES
        x2 = x.reshape(rows, _PALLAS_LANES)
        grid = rows // _PALLAS_ROWS
        return pl.pallas_call(
            _enc_kernel,
            out_shape=jax.ShapeDtypeStruct((rows, _PALLAS_LANES), jnp.uint16),
            in_specs=[pl.BlockSpec((_PALLAS_ROWS, _PALLAS_LANES), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((_PALLAS_ROWS, _PALLAS_LANES), lambda i: (i, 0)),
            grid=(grid,),
            interpret=self._pallas_mode == "interpret",
        )(x2).reshape(-1)

    def _pallas_dec_axpy_local(self, bits, acc, w):
        import jax
        from jax.experimental import pallas as pl

        jnp = _jnp()
        rows = bits.size // _PALLAS_LANES
        b2 = bits.reshape(rows, _PALLAS_LANES)
        a2 = acc.reshape(rows, _PALLAS_LANES)
        w2 = w.reshape(1, 1)
        grid = rows // _PALLAS_ROWS
        return pl.pallas_call(
            _dec_axpy_kernel,
            out_shape=jax.ShapeDtypeStruct((rows, _PALLAS_LANES), jnp.float32),
            in_specs=[
                pl.BlockSpec((_PALLAS_ROWS, _PALLAS_LANES), lambda i: (i, 0)),
                pl.BlockSpec((_PALLAS_ROWS, _PALLAS_LANES), lambda i: (i, 0)),
                pl.BlockSpec((1, 1), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((_PALLAS_ROWS, _PALLAS_LANES), lambda i: (i, 0)),
            grid=(grid,),
            interpret=self._pallas_mode == "interpret",
        )(b2, a2, w2).reshape(-1)

    def _pallas_eligible(self, n: int) -> bool:
        """Pallas blocking needs every local shard to tile (rows, lanes)
        exactly; off-size buffers take the jnp body instead of padding
        twice."""
        self._ensure_mesh()
        block = self._ndev * _PALLAS_ROWS * _PALLAS_LANES
        return self._pallas_mode != "off" and n > 0 and n % block == 0

    # -- bf16 wire codec ---------------------------------------------------

    def encode_bf16(self, buf: np.ndarray) -> np.ndarray:
        """float32 [n] -> uint16 [n] bf16 bit patterns (round-to-nearest-
        even — bit-compatible with ``native.f32_to_bf16`` on finite
        values)."""
        from distributedvolunteercomputing_tpu import native

        buf = np.ascontiguousarray(buf, np.float32).ravel()

        def dev() -> np.ndarray:
            import jax
            from jax.sharding import PartitionSpec as P

            jnp = _jnp()
            use_pallas = self._pallas_eligible(buf.size)

            def body(x):
                if use_pallas:
                    return self._pallas_encode_local(x)
                return jax.lax.bitcast_convert_type(
                    x.astype(jnp.bfloat16), jnp.uint16
                )

            fn = self._jit(
                ("enc", use_pallas),
                lambda: self._shard_map(body, (P("codec"),), P("codec")),
            )
            x, n = self._put_flat(buf)
            return np.asarray(fn(x))[:n]

        return self._run(dev, lambda: native.f32_to_bf16(buf))

    def decode_bf16(self, bits: np.ndarray, out: Optional[np.ndarray] = None) -> np.ndarray:
        """uint16 bf16 bit patterns -> float32 (exact: bf16 ⊂ f32)."""
        from distributedvolunteercomputing_tpu import native

        bits = np.ascontiguousarray(bits, np.uint16).ravel()

        def dev() -> np.ndarray:
            import jax
            from jax.sharding import PartitionSpec as P

            jnp = _jnp()

            def body(b):
                return _bf16_widen(b)

            fn = self._jit(
                ("dec",), lambda: self._shard_map(body, (P("codec"),), P("codec"))
            )
            b, n = self._put_flat(bits)
            res = np.asarray(fn(b))[:n]
            if out is not None:
                out[: res.size] = res
                return out[: res.size]
            return res

        return self._run(dev, lambda: native.bf16_to_f32(bits, out=out))

    def decode_axpy(self, acc: np.ndarray, bits: np.ndarray, w: float) -> np.ndarray:
        """acc + w · decode(bits) in ONE fused device pass (the host path
        pays a decode allocation plus a second axpy pass). Returns the new
        accumulator; the host fallback mutates ``acc`` in place and returns
        it — callers must use the return value either way."""
        from distributedvolunteercomputing_tpu import native

        acc = np.ascontiguousarray(acc, np.float32).ravel()
        bits = np.ascontiguousarray(bits, np.uint16).ravel()
        if acc.size != bits.size:
            raise ValueError(f"decode_axpy size mismatch: {acc.size} vs {bits.size}")

        def dev() -> np.ndarray:
            import jax
            from jax.sharding import PartitionSpec as P

            jnp = _jnp()
            use_pallas = self._pallas_eligible(acc.size)

            def body(a, b, wv):
                if use_pallas:
                    return self._pallas_dec_axpy_local(b, a, wv)
                return a + wv[0] * _bf16_widen(b)

            fn = self._jit(
                ("dec_axpy", use_pallas),
                lambda: self._shard_map(
                    body, (P("codec"), P("codec"), P()), P("codec")
                ),
            )
            a, n = self._put_flat(acc)
            b, _ = self._put_flat(bits)
            return np.asarray(fn(a, b, np.float32([w])))[:n]

        def host() -> np.ndarray:
            native.weighted_sum_inplace(acc, native.bf16_to_f32(bits), float(w))
            return acc

        return self._run(dev, host)

    # -- window folds ------------------------------------------------------

    def aggregate(self, stack: np.ndarray, method: str, **kw) -> np.ndarray:
        """``ops.robust.aggregate`` with the decomposable estimators run on
        the mesh (see the module placement table); every other method — and
        every failure — takes the host path unchanged, so this is always
        safe to call wherever ``robust.aggregate`` was."""
        from distributedvolunteercomputing_tpu.ops import robust

        host = lambda: robust.aggregate(stack, method, **kw)  # noqa: E731
        if method not in ("mean", "median", "trimmed_mean") or stack.ndim != 2:
            self.ops_host += 1
            return robust.aggregate(stack, method, **kw)
        n = stack.shape[0]
        if method == "trimmed_mean":
            trim = int(kw.get("trim", 1))
            if 2 * trim >= n:
                raise ValueError(f"trim={trim} too large for n={n}")
            if trim == 0:
                method, kw = "mean", {}
        if method == "mean" and n == 1:
            # Degenerate window: device round-trip buys nothing.
            self.ops_host += 1
            return robust.aggregate(stack, method, **kw)

        def dev() -> np.ndarray:
            s = np.ascontiguousarray(stack, np.float32)
            if method == "mean":
                w = kw.get("weights")
                wn = (
                    np.asarray(w, np.float64) / np.asarray(w, np.float64).sum()
                    if w is not None
                    else np.full(n, 1.0 / n)
                ).astype(np.float32)
                fn = self._jit(("wmean", n), self._build_wmean)
                d, t = self._put_stack(s)
                return np.asarray(fn(d, wn))[:t]
            trim = int(kw.get("trim", 1)) if method == "trimmed_mean" else None
            key = (method, n, trim)
            fn = self._jit(key, lambda: self._build_window(method, n, trim))
            d, t = self._put_stack(s)
            return np.asarray(fn(d))[:t]

        return self._run(dev, host)

    def _build_wmean(self) -> Callable:
        from jax.sharding import PartitionSpec as P

        def body(s, w):
            return (s * w[:, None]).sum(axis=0)

        return self._shard_map(body, (P(None, "codec"), P()), P("codec"))

    def _build_window(self, method: str, n: int, trim: Optional[int]) -> Callable:
        """Sorting-network window estimator over the peer axis: rows are
        unrolled into separate [T] arrays so every compare-exchange is two
        fusable elementwise ops (an ``.at[].set`` formulation scatters and
        is ~50× slower on the CPU backend, measured)."""
        from jax.sharding import PartitionSpec as P

        jnp = _jnp()
        m = 1 << max(n - 1, 0).bit_length() if n > 1 else 1
        pairs = _batcher_pairs(m) if m > 1 else []

        def body(s):
            # NaN -> +inf BEFORE the network: jnp.minimum/maximum PROPAGATE
            # NaN, so one NaN-filled byzantine row would otherwise poison
            # every row of the coordinate — the exact failure the robust
            # estimator exists to absorb. +inf reproduces numpy's sort
            # order (NaN sorts last), so trimming drops the attacker the
            # same way the host path does; a NaN count beyond the trim
            # yields inf instead of host's NaN — both are poisoned, and
            # inf at least names the direction.
            s = jnp.where(jnp.isnan(s), jnp.inf, s)
            rows = [s[i] for i in range(n)]
            rows += [jnp.full_like(rows[0], jnp.inf)] * (m - n)
            for i, j in pairs:
                a, b = rows[i], rows[j]
                rows[i] = jnp.minimum(a, b)
                rows[j] = jnp.maximum(a, b)
            if method == "median":
                return (rows[(n - 1) // 2] + rows[n // 2]) * jnp.float32(0.5)
            kept = rows[trim : n - trim]
            return sum(kept[1:], kept[0]) / jnp.float32(len(kept))

        return self._shard_map(body, (P(None, "codec"),), P("codec"))

    def aggregate_bits(self, bits_stack: np.ndarray, method: str, **kw) -> np.ndarray:
        """Window fold straight from bf16 wire bits [n, T] — the decode
        fuses into the estimator on device; host decodes then folds."""
        from distributedvolunteercomputing_tpu import native
        from distributedvolunteercomputing_tpu.ops import robust

        def host() -> np.ndarray:
            dec = np.stack([native.bf16_to_f32(row) for row in bits_stack])
            return robust.aggregate(dec, method, **kw)

        if not self.active:
            self.ops_host += 1
            return host()

        def dev_decode() -> np.ndarray:
            import jax
            from jax.sharding import PartitionSpec as P

            jnp = _jnp()

            def body(b):
                return _bf16_widen(b)

            fn = self._jit(
                ("dec2d",),
                lambda: self._shard_map(body, (P(None, "codec"),), P(None, "codec")),
            )
            d, t = self._put_stack(np.ascontiguousarray(bits_stack, np.uint16))
            return np.asarray(fn(d))[:, :t]

        dec = self._run(dev_decode, lambda: np.stack(
            [native.bf16_to_f32(row) for row in bits_stack]
        ))
        return self.aggregate(dec, method, **kw)

    # -- PowerSGD ----------------------------------------------------------

    def low_rank_iterate(
        self, mat: np.ndarray, q: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One PowerSGD power iteration on device:
        P = QR-orthonormalize(M·Q), Q' = Mᵀ·P (Q' carries the scale)."""

        def dev() -> Tuple[np.ndarray, np.ndarray]:
            import jax

            jnp = _jnp()

            def body(m_, q_):
                p_, _ = jnp.linalg.qr(m_ @ q_)
                return p_, m_.T @ p_

            # Matmul + QR want the whole matrix: replicated compute (the
            # matrices are one TENSOR's, small next to the flat buffer; the
            # elementwise codec ops are where the sharding pays).
            fn = self._jit(("psgd_iter",), lambda: jax.jit(body))
            p, q_new = fn(
                np.ascontiguousarray(mat, np.float32),
                np.ascontiguousarray(q, np.float32),
            )
            return (
                np.ascontiguousarray(np.asarray(p), np.float32),
                np.ascontiguousarray(np.asarray(q_new), np.float32),
            )

        def host() -> Tuple[np.ndarray, np.ndarray]:
            p, _ = np.linalg.qr((mat @ q).astype(np.float32, copy=False))
            p = np.ascontiguousarray(p, np.float32)
            return p, mat.T @ p

        return self._run(dev, host)

    def lowrank_reconstruct(self, p: np.ndarray, q: np.ndarray) -> np.ndarray:
        """Dense rank-r reconstruction (P·Qᵀ).ravel() — the decoder's hot
        matmul when contributions arrive."""

        def dev() -> np.ndarray:
            import jax

            fn = self._jit(("psgd_rec",), lambda: jax.jit(lambda a, b: a @ b.T))
            return np.asarray(
                fn(
                    np.ascontiguousarray(p, np.float32),
                    np.ascontiguousarray(q, np.float32),
                )
            ).ravel()

        return self._run(dev, lambda: (p @ q.T).ravel())

    # -- streaming mean folder --------------------------------------------

    def mean_folder(
        self, n_elems: int, tile_elems: int, n_tiles: int, wire: str
    ) -> Optional["MeshMeanFolder"]:
        """A device mean folder for one round, or None when this codec
        can't host one (inactive, or the tile dim doesn't split over the
        codec axis — chunk sizes and device counts are both powers of two
        in practice, so the None case is the host backend).

        With the ring collective enabled (and a bf16 wire on >= 2 devices)
        the folder is the fused ring pipeline (ops.mesh_collective): chunks
        land WHOLE on devices and decode+fold+forward run in one device
        pass, instead of the staged element-split scatter-add. On one
        device the ring degenerates to a plain fold — the staged folder IS
        that plain fold, so it is returned unchanged."""
        if not self.active:
            return None
        self._ensure_mesh()
        if tile_elems % self._ndev:
            return None
        if self._collective == "ring" and wire == "bf16" and self._ndev >= 2:
            from distributedvolunteercomputing_tpu.ops import mesh_collective

            return mesh_collective.RingMeanFolder(
                self, n_elems, tile_elems, n_tiles, wire
            )
        return MeshMeanFolder(self, n_elems, tile_elems, n_tiles, wire)


class MeshMeanFolder:
    """Device-resident mean accumulator for one streaming round.

    The streaming aggregator's mean mode stages arriving wire chunks as raw
    bytes (zero decode on the frame-reader thread) and flushes them in
    batches: ONE jitted scatter-add decodes the whole batch (bf16 bitcast +
    widen, fused) and folds it into an ``[n_tiles, tile_elems]`` device
    accumulator. Short tail chunks zero-pad to a full tile (zeros fold
    harmlessly); per-tile WEIGHT tallies stay host-side in the aggregator
    (scalar work). ``result()`` flushes the remainder and pulls the flat
    accumulator back once.

    Degrade contract: a flush that fails mid-round pulls the last good
    device accumulator to host and folds the failed batch (and everything
    after it) with host numpy — the round commits through a mesh shrink.
    Only if the accumulated state itself is unrecoverable does the round
    fail, and the codec is degraded either way so the next round starts on
    host."""

    kind = "staged"  # vs "ring" (ops.mesh_collective.RingMeanFolder)

    def __init__(
        self, codec: MeshCodec, n_elems: int, tile_elems: int, n_tiles: int, wire: str
    ):
        if wire not in ("f32", "bf16"):
            raise ValueError(f"mean folder needs an elementwise wire, got {wire!r}")
        self.codec = codec
        self.n_elems = int(n_elems)
        self.tile_elems = int(tile_elems)
        self.n_tiles = int(n_tiles)
        self.wire = wire
        self.esz = 4 if wire == "f32" else 2
        self._lock = threading.Lock()
        self._staged: List[Tuple[int, float, bytes]] = []
        self._staged_bytes = 0
        # High-water of raw wire bytes held between flushes: the aggregator
        # adds this to its peak-held gauge (staged chunks are real resident
        # memory the O(D) accumulator accounting alone would hide).
        self.peak_staged_bytes = 0
        self.flush_bytes = FOLDER_FLUSH_BYTES
        self._acc = None  # device [n_tiles, tile_elems] f32, set lazily
        self._host_acc: Optional[np.ndarray] = None  # degraded-mode shadow
        self.flushes = 0

    # -- staging (called under the aggregator's lock) ----------------------

    def add(self, tile: int, weight: float, data: bytes) -> bool:
        """Stage one verified wire chunk; True when a flush is due (the
        caller spawns ``flush`` on a worker, off the frame-reader)."""
        with self._lock:
            self._staged.append((tile, float(weight), data))
            self._staged_bytes += len(data)
            if self._staged_bytes > self.peak_staged_bytes:
                self.peak_staged_bytes = self._staged_bytes
            return self._staged_bytes >= self.flush_bytes

    def add_dense(self, buf: np.ndarray, weight: float) -> None:
        """Fold a complete dense f32 contribution (leader's own / parked)."""
        buf = np.ascontiguousarray(buf, np.float32).ravel()
        if buf.size != self.n_elems:
            raise ValueError(f"dense feed size {buf.size} != {self.n_elems}")

        def dev() -> bool:
            pad = self.n_tiles * self.tile_elems - self.n_elems
            x = np.pad(buf, (0, pad)).reshape(self.n_tiles, self.tile_elems)

            def body(a, x_, w_):
                return a + w_[0] * x_

            fn = self.codec._jit(
                ("folder_dense", self.n_tiles, self.tile_elems),
                lambda: self._fold_jit(body, n_in=1),
            )
            with self._lock:
                if self._host_acc is not None:
                    # A concurrent flush already migrated the accumulator
                    # to host (mid-round degrade): folding into a fresh
                    # device acc would silently DROP this mass at result().
                    raise MeshCodecError("folder already degraded")  # -> host()
                acc = self._device_acc()
                self._acc = fn(acc, self._put(x), np.float32([weight]))
            return True

        def host() -> bool:
            with self._lock:
                self._to_host_locked()
                from distributedvolunteercomputing_tpu import native

                native.weighted_sum_inplace(
                    self._host_acc[: self.n_elems], buf, float(weight)
                )
            return True

        self.codec._run(dev, host)

    # -- device plumbing ---------------------------------------------------

    def _put(self, arr: np.ndarray):
        import jax
        from jax.sharding import PartitionSpec as P

        if self.codec._ndev == 1:
            return arr  # XLA:CPU consumes aligned numpy zero-copy
        return jax.device_put(arr, self.codec._sharding(P(None, "codec")))

    def _fold_jit(self, body, n_in: int):
        from jax.sharding import PartitionSpec as P

        specs = (P(None, "codec"),) * (1 + n_in) + (P(),) * 1
        return self.codec._shard_map(
            body, specs, P(None, "codec"), donate_argnums=(0,)
        )

    def _device_acc(self):
        if self._acc is None:
            import jax
            from jax.sharding import PartitionSpec as P

            self._acc = jax.device_put(
                np.zeros((self.n_tiles, self.tile_elems), np.float32),
                self.codec._sharding(P(None, "codec")),
            )
        return self._acc

    def _to_host_locked(self) -> None:
        """Adopt the host shadow accumulator (degraded mode), folding in
        whatever the device holds. Raises only when the device state is
        truly unrecoverable — then the round fails loudly rather than
        committing without the mass already folded."""
        if self._host_acc is None:
            if self._acc is not None:
                self._host_acc = np.asarray(self._acc).ravel().copy()
                self._acc = None
            else:
                self._host_acc = np.zeros(self.n_tiles * self.tile_elems, np.float32)

    def _decode_host(self, data: bytes) -> np.ndarray:
        from distributedvolunteercomputing_tpu import native

        if self.wire == "f32":
            return np.frombuffer(data, np.float32)
        return native.bf16_to_f32(np.frombuffer(data, np.uint16))

    # -- folding -----------------------------------------------------------

    def _pop_staged(self) -> List[Tuple[int, float, bytes]]:
        with self._lock:
            batch, self._staged = self._staged, []
            self._staged_bytes = 0
        return batch

    def _batch_arrays(self, batch: List[Tuple[int, float, bytes]], kb: int):
        """(tiles [kb] i32, ws [kb] f32, raw [kb, row_bytes] u8) — the
        staged batch as padded host arrays. Padding rows carry weight 0
        into tile 0: a no-op fold. Shared by the staged scatter-add and the
        ring collective flush (one home for the wire-chunk layout)."""
        k = len(batch)
        tiles = np.zeros(kb, np.int32)
        ws = np.zeros(kb, np.float32)
        tiles[:k] = [t for t, _, _ in batch]
        ws[:k] = [w for _, w, _ in batch]
        row_bytes = self.tile_elems * self.esz
        raw = np.zeros((kb, row_bytes), np.uint8)
        for i, (_, _, data) in enumerate(batch):
            raw[i, : len(data)] = np.frombuffer(data, np.uint8)
        return tiles, ws, raw

    def _flush_dev(self, batch: List[Tuple[int, float, bytes]]) -> bool:
        """Device half of flush: the PR 5 staged path — batch element-split
        over the codec axis, ONE jitted scatter-add (bf16 decode fused).
        Overridden by the ring collective folder."""
        # Pad the batch to the next power of two: the scatter-add jits
        # per batch LENGTH, and chunk arrival makes that length
        # arbitrary — bucketing bounds the compile count at ~log(max
        # batch).
        k = len(batch)
        kb = 1 << max(k - 1, 0).bit_length()
        tiles, ws, raw = self._batch_arrays(batch, kb)

        if self.wire == "f32":
            x = raw.view(np.float32)

            def body(a, x_, t_, w_):
                return a.at[t_].add(w_[:, None] * x_)
        else:
            x = raw.view(np.uint16)

            def body(a, x_, t_, w_):
                return a.at[t_].add(w_[:, None] * _bf16_widen(x_))

        from jax.sharding import PartitionSpec as P

        fn = self.codec._jit(
            ("folder_flush", self.wire, kb, self.tile_elems),
            lambda: self.codec._shard_map(
                body,
                (P(None, "codec"), P(None, "codec"), P(), P()),
                P(None, "codec"),
                donate_argnums=(0,),
            ),
        )
        with self._lock:
            if self._host_acc is not None:
                raise MeshCodecError("folder already degraded")  # -> host()
            acc = self._device_acc()
            self._acc = fn(acc, self._put(x), tiles, ws)
        return True

    def _flush_host(self, batch: List[Tuple[int, float, bytes]]) -> bool:
        """Host half of flush: the degraded-slice replay — the SAME batch
        folds with host numpy, committing the in-flight round."""
        from distributedvolunteercomputing_tpu import native

        with self._lock:
            self._to_host_locked()
            acc = self._host_acc
            for tile, w, data in batch:
                e0 = tile * self.tile_elems
                x = self._decode_host(data)
                native.weighted_sum_inplace(acc[e0 : e0 + x.size], x, w)
        return True

    def flush(self) -> None:
        """Fold every staged chunk (worker-thread context)."""
        batch = self._pop_staged()
        if not batch:
            return
        self.flushes += 1
        self.codec._run(
            lambda: self._flush_dev(batch), lambda: self._flush_host(batch)
        )

    def result(self) -> np.ndarray:
        """Flush the tail and return the flat RAW accumulator [n_elems]
        (per-tile re-normalization stays with the aggregator — one
        implementation for the device and host paths)."""
        self.flush()
        with self._lock:
            if self._host_acc is not None:
                return self._host_acc[: self.n_elems]
            if self._acc is None:
                return np.zeros(self.n_elems, np.float32)
            out = np.asarray(self._acc).ravel()[: self.n_elems].copy()
            self._acc = None
            return out

    @property
    def device_bytes(self) -> int:
        return self.n_tiles * self.tile_elems * 4


# ---------------------------------------------------------------------------
# process-wide default (one codec per volunteer process)
# ---------------------------------------------------------------------------

_default: Optional[MeshCodec] = None
_default_lock = threading.Lock()


def get_default() -> MeshCodec:
    """The process's codec; built on first use with auto backend selection
    (host unless the default backend is TPU silicon or DVC_MESH_CODEC=1)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = MeshCodec()
    return _default


def configure(
    mesh=None,
    backend: str = "auto",
    pallas: Optional[str] = None,
    collective: Optional[str] = None,
) -> MeshCodec:
    """Select THIS volunteer's codec at startup (the per-volunteer
    selection surfaced in stats()): called by the volunteer once its local
    training mesh exists, before the first averaging round."""
    global _default
    with _default_lock:
        _default = MeshCodec(
            mesh=mesh, backend=backend, pallas=pallas, collective=collective
        )
    return _default


def reset() -> None:
    """Drop the process default (tests)."""
    global _default
    with _default_lock:
        _default = None
