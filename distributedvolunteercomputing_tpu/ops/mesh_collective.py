"""Fused ring reduce pipeline over the volunteer's local codec mesh.

The PR 5 staged data path (``ops.mesh_codec.MeshMeanFolder``) element-splits
every arriving wire chunk across the codec axis before folding: the host
slices each chunk into per-device columns (a strided device_put) and ONE
scatter-add folds the batch — so fold ingest is bounded by the single host's
PCIe, not the slice. This module keeps the whole reduce path resident on the
device mesh (the mesh-networks paper's position): chunks land WHOLE on
devices, round-robin over the 1-D "codec" view of the local ``dp*sp*tp``
mesh, and a ring reduce-scatter turns the per-device partial folds into the
element-sharded accumulator layout the staged folder already maintains — so
``result()``, the degraded-slice contract, and the aggregator's
re-normalization are inherited unchanged.

The kernel (``_ring_fold_kernel``) is ONE ``pallas_call`` whose grid is the
ring schedule: grid step ``s`` on device ``d`` decodes the bf16 wire tiles'
slice for shard ``b = (d - s - 1) mod ndev``, folds it into the f32 partial,
and forwards the previous step's partial to the right ring neighbor via
inter-chip send/recv DMA semaphores. Compute and DMA are double-buffered
(two partial slots): the decode+fold for step ``s`` runs while step
``s-1``'s partial is in flight, so fold throughput scales with slice size.
Each wire element is decoded exactly once across the whole grid. A second
kernel (``_ring_ag_kernel``) is the matching ring all-gather used by
``result()`` — one device pass reassembles the full accumulator so the
round result crosses the host link once.

Lowering ladder (``DVC_RING_LOWER`` overrides; auto follows the codec's
pallas mode):

- ``compiled``  — the Pallas kernel on TPU silicon, remote DMA + a REGULAR
  capacity-semaphore handshake (a partial slot is overwritten only after
  its last send completed; the interpreter serializes and needs none).
- ``interpret`` — the SAME kernel body interpreted on CPU: tier-1 tests and
  the MULTICHIP dryrun gate cover the exact grid schedule, DMA descriptors,
  and fold math bit-for-bit against the host path.
- ``xla``       — the same math and placement with the collective lowered
  by XLA (``lax.psum_scatter`` / ``lax.all_gather``) instead of the hand
  ring: the fast CPU lowering (interpret-mode Pallas is a Python emulator)
  and the fallback when the kernel's working set exceeds the VMEM cap.

Degrade contract (inherited from ``MeshMeanFolder``): the first device
failure pulls the last good accumulator to host and replays the in-flight
batch with host numpy — the round commits through a mesh shrink, and the
codec permanently degrades so the next round starts on host.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import List, Tuple

import numpy as np

from distributedvolunteercomputing_tpu.ops.mesh_codec import (
    MeshCodecError,
    MeshMeanFolder,
    _bf16_widen,
    _jnp,
)

log = logging.getLogger("dvc.mesh_collective")

# Compiled-mode working-set cap: buffers above this fall back to the xla
# lowering rather than risk a VMEM OOM mid-round (the ring kernel keeps two
# partial slots + the scratch partial + the accumulator shard resident).
_VMEM_CAP_BYTES = int(
    float(os.environ.get("DVC_RING_VMEM_MB", "10")) * (1 << 20)
)


def ring_available(codec) -> bool:
    """True when ``codec`` routes mean folds through the ring collective
    (active mesh backend, ring selected, >= 2 devices on the codec axis)."""
    if not codec.active or codec._collective != "ring":
        return False
    codec._ensure_mesh()
    return codec._ndev >= 2


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _ring_fold_kernel(
    nd,
    per_dev,
    shard,
    n_tiles,
    handshake,
    tiles_ref,
    ws_ref,
    bits_ref,
    acc_ref,
    o_ref,
    buf_ref,
    ctmp_ref,
    send_sem,
    recv_sem,
    cap_sem,
):
    """One grid step == one ring step: decode + fold + forward, overlapped.

    Device ``d`` at step ``s`` works shard ``b = (d - s - 1) mod nd``: it
    starts the DMA forwarding step ``s-1``'s partial to the right neighbor,
    then (while that DMA is in flight) decodes its local chunks' ``b``-slice
    and folds it into the scratch partial, then waits the DMA and adds the
    scratch into the freshly received slot. The partial for shard ``b``
    terminates at device ``b`` on the last step, where it folds into the
    resident accumulator shard. ``handshake`` (compiled mode) closes the
    one-step-ahead race: a slot is re-targeted only after the right
    neighbor confirms its send from that slot completed — the interpreter
    has no remote signal and serializes safely without it.
    """
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    jnp = _jnp()
    s = pl.program_id(0)
    d = jax.lax.axis_index("codec")
    right = jax.lax.rem(d + 1, nd)
    left = jax.lax.rem(d + nd - 1, nd)
    slot = jax.lax.rem(s, 2)
    prev = jax.lax.rem(s + 1, 2)
    b = jax.lax.rem(d - s - 1 + 2 * nd, nd)

    fwd = pltpu.make_async_remote_copy(
        src_ref=buf_ref.at[prev],
        dst_ref=buf_ref.at[slot],
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )

    if handshake:

        @pl.when(s > 0)
        def _window_open():
            # Right neighbor finished sending FROM the slot this send
            # targets (its previous-step wait signalled us).
            pltpu.semaphore_wait(cap_sem, 1)

    @pl.when(s > 0)
    def _forward():
        fwd.start()

    # Fused decode+fold for this step's shard slice — runs while the DMA is
    # in flight. Across the nd grid steps the slices partition tile_elems,
    # so every wire element is decoded exactly once.
    ctmp_ref[...] = jnp.zeros((n_tiles, shard), jnp.float32)

    def _fold_one(i, carry):
        t = tiles_ref[i]
        w = ws_ref[i]
        bits = pl.load(bits_ref, (pl.ds(i, 1), pl.ds(b * shard, shard)))
        row = pl.load(ctmp_ref, (pl.ds(t, 1), slice(None)))
        pl.store(
            ctmp_ref,
            (pl.ds(t, 1), slice(None)),
            row + w * _bf16_widen(bits),
        )
        return carry

    jax.lax.fori_loop(0, per_dev, _fold_one, 0)

    @pl.when(s == 0)
    def _seed():
        pl.store(
            buf_ref,
            (pl.ds(0, 1), slice(None), slice(None)),
            ctmp_ref[...][None],
        )

    @pl.when(s > 0)
    def _accumulate():
        fwd.wait()
        got = pl.load(buf_ref, (pl.ds(slot, 1), slice(None), slice(None)))
        pl.store(
            buf_ref,
            (pl.ds(slot, 1), slice(None), slice(None)),
            got + ctmp_ref[...][None],
        )

    if handshake:

        @pl.when(s < nd - 1)
        def _window_grant():
            # My send from buf[prev] completed (fwd.wait above covers the
            # send side at s>0; at s==0 the slot is virgin): the left
            # neighbor may target it next step.
            pltpu.semaphore_signal(cap_sem, 1, device_id=left)

    @pl.when(s == nd - 1)
    def _emit():
        final = pl.load(buf_ref, (pl.ds(slot, 1), slice(None), slice(None)))
        o_ref[...] = acc_ref[...] + final[0]


def _ring_ag_kernel(nd, x_ref, o_ref, send_sem, recv_sem):
    """Ring all-gather: step ``s`` forwards the block received at ``s-1``
    (own block at ``s==0``) to the right neighbor. Every step's DMA targets
    a distinct block slot on the receiver, so no capacity handshake is
    needed — the send/recv semaphores alone order the chain."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = pl.program_id(0)
    d = jax.lax.axis_index("codec")
    right = jax.lax.rem(d + 1, nd)
    blk = jax.lax.rem(d - s + 2 * nd, nd)

    @pl.when(s == 0)
    def _own():
        pl.store(
            o_ref,
            (pl.ds(d, 1), slice(None), slice(None)),
            x_ref[...][None],
        )

    fwd = pltpu.make_async_remote_copy(
        src_ref=o_ref.at[blk],
        dst_ref=o_ref.at[blk],
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    fwd.start()
    fwd.wait()


# ---------------------------------------------------------------------------
# folder
# ---------------------------------------------------------------------------


class RingMeanFolder(MeshMeanFolder):
    """``MeshMeanFolder`` with the flush/result device halves replaced by
    the fused ring pipeline. Staging, host bookkeeping, the degraded-slice
    replay, and the accumulator layout ([n_tiles, tile_elems] with elements
    split over the codec axis) are all inherited — the aggregator cannot
    tell the folders apart except through ``kind`` and the gauges."""

    kind = "ring"

    def __init__(self, codec, n_elems, tile_elems, n_tiles, wire):
        super().__init__(codec, n_elems, tile_elems, n_tiles, wire)
        if wire != "bf16":
            raise ValueError("ring folder is bf16-wire only")
        codec._ensure_mesh()
        if codec._ndev < 2:
            raise ValueError("ring folder needs >= 2 devices")
        if tile_elems % codec._ndev:
            raise ValueError("tile_elems must split over the codec axis")
        self.shard = tile_elems // codec._ndev
        self.ring_flushes = 0
        self._lower_cfg = self._resolve_lower(codec)
        # Surface the lowering choice on the codec so coord.status can see
        # it: a fleet quietly re-lowered to xla by the VMEM estimate looks
        # identical to one running the kernel otherwise.
        codec.ring_lower = self._lower_cfg
        if codec.ring_lower_effective is None:
            codec.ring_lower_effective = self._lower_cfg
        # Eager ingest (xla lowering): every chunk is ALSO put to its column
        # shard at add() time, so the host-link crossing overlaps chunk
        # arrival and flush() folds device-resident bits with no host
        # consolidation pass. The raw bytes stay staged regardless — they
        # are the degrade-replay source of truth.
        self._eager = self._lower_cfg == "xla"
        self._pending: List = []
        self._eager_broken = False
        self._pad_chunk = None

    # -- lowering ---------------------------------------------------------

    @staticmethod
    def _resolve_lower(codec) -> str:
        env = os.environ.get("DVC_RING_LOWER", "auto").strip().lower()
        if env == "xla":
            return "xla"
        if env == "pallas":
            return "compiled" if codec._pallas_mode == "compiled" else "interpret"
        return {"compiled": "compiled", "interpret": "interpret"}.get(
            codec._pallas_mode, "xla"
        )

    def _lower_for(self, per_dev: int) -> str:
        """The flush lowering for one batch size: compiled falls back to
        xla when the kernel working set would blow VMEM (two partial slots
        + scratch partial + acc shard + out, f32, plus the u16 bits)."""
        lower = self._lower_cfg
        if lower != "compiled":
            return lower
        buf_bytes = self.n_tiles * self.shard * 4
        est = 5 * buf_bytes + 2 * per_dev * self.tile_elems
        if est > _VMEM_CAP_BYTES:
            self._note_vmem_fallback("flush", est)
            return "xla"
        self.codec.ring_lower_effective = lower
        return lower

    def _note_vmem_fallback(self, site: str, est: int) -> None:
        """Book a compiled->xla re-lowering on the codec gauges and warn
        exactly once per codec — the fallback is correct but should never
        be silent, or a whole fleet pinned to xla by DVC_RING_VMEM_MB
        reads as if the kernel were live."""
        codec = self.codec
        reason = "%s working set %.1fMB > VMEM cap %.0fMB" % (
            site,
            est / (1 << 20),
            _VMEM_CAP_BYTES / (1 << 20),
        )
        codec.ring_lower_effective = "xla"
        codec.ring_lower_fallback = reason
        codec.ring_vmem_fallbacks += 1
        if not codec._ring_vmem_warned:
            codec._ring_vmem_warned = True
            log.warning(
                "ring lowering fell back compiled->xla: %s "
                "(raise DVC_RING_VMEM_MB to keep the kernel)",
                reason,
            )

    # -- eager ingest (xla lowering) --------------------------------------

    def add(self, tile: int, weight: float, data: bytes) -> bool:
        dev = None
        if self._eager and not self._eager_broken and self._host_acc is None:
            try:
                dev = self._eager_put(data)
            except Exception:  # noqa: BLE001 — the flush degrades with context
                self._eager_broken = True
        with self._lock:
            self._staged.append((tile, float(weight), data))
            self._staged_bytes += len(data)
            if self._staged_bytes > self.peak_staged_bytes:
                self.peak_staged_bytes = self._staged_bytes
            if self._eager:
                self._pending.append(dev)
            return self._staged_bytes >= self.flush_bytes

    def _eager_put(self, data: bytes):
        import jax
        from jax.sharding import PartitionSpec as P

        arr = np.frombuffer(data, np.uint16)
        if arr.size != self.tile_elems:  # short tail chunk: pad like _batch_arrays
            pad = np.zeros(self.tile_elems, np.uint16)
            pad[: arr.size] = arr
            arr = pad
        # Flat 1-D split: every device's slice is one contiguous memcpy
        # (the staged path's [kb, row] column split strides per row).
        return jax.device_put(arr, self.codec._sharding(P("codec")))

    def flush(self) -> None:
        with self._lock:
            batch, self._staged = self._staged, []
            pend, self._pending = self._pending, []
            self._staged_bytes = 0
        if not batch:
            return
        self.flushes += 1
        self.codec._run(
            lambda: self._flush_dev(batch, pend),
            lambda: self._flush_host(batch),
        )

    # -- flush ------------------------------------------------------------

    def _flush_dev(self, batch: List[Tuple[int, float, bytes]], pend=None) -> bool:
        import jax
        from jax.sharding import PartitionSpec as P

        codec = self.codec
        codec._ensure_mesh()
        nd = codec._ndev
        if self._eager:
            if self._eager_broken or pend is None or any(d is None for d in pend):
                raise MeshCodecError("eager ingest lost chunks (device put failed)")
            return self._flush_eager(batch, pend)
        # Bucket the PER-DEVICE chunk count to a power of two (same
        # compile-count bound as the staged folder); the batch dim must
        # split evenly over the codec axis for whole-chunk placement.
        per_dev = 1 << max(-(-len(batch) // nd) - 1, 0).bit_length()
        kb = per_dev * nd
        tiles, ws, raw = self._batch_arrays(batch, kb)
        x = raw.view(np.uint16)
        lower = self._lower_for(per_dev)
        fn = codec._jit(
            ("ring_flush", lower, kb, self.n_tiles, self.tile_elems),
            lambda: self._build_flush(lower, per_dev),
        )
        # Whole-chunk placement: batch rows split over the codec axis
        # (contiguous rows per device — no host element-splitting).
        xd = jax.device_put(x, codec._sharding(P("codec", None)))
        meta_spec = P() if lower == "xla" else P("codec")
        td = jax.device_put(tiles, codec._sharding(meta_spec))
        wd = jax.device_put(ws, codec._sharding(meta_spec))
        with self._lock:
            if self._host_acc is not None:
                raise MeshCodecError("folder already degraded")  # -> host()
            acc = self._device_acc()
            self._acc = fn(acc, xd, td, wd)
        self.ring_flushes += 1
        return True

    def _flush_eager(self, batch, pend) -> bool:
        """Fold the device-resident eager chunks: per-chunk row scatter-adds
        into the donated accumulator shard — the wire bytes cross the host
        link exactly once (at add() time) and the fold reads them exactly
        once. No consolidation pass, no exchange: every chunk already sits
        column-split on its owners."""
        codec = self.codec
        kb = 1 << max(len(batch) - 1, 0).bit_length()
        tiles = np.zeros(kb, np.int32)
        ws = np.zeros(kb, np.float32)
        tiles[: len(batch)] = [t for t, _, _ in batch]
        ws[: len(batch)] = [w for _, w, _ in batch]
        chunks = list(pend)
        if kb > len(chunks):
            if self._pad_chunk is None:
                self._pad_chunk = self._eager_put(b"")
            chunks += [self._pad_chunk] * (kb - len(chunks))
        fn = codec._jit(
            ("ring_eager", kb, self.n_tiles, self.tile_elems),
            lambda: self._build_eager(kb),
        )
        with self._lock:
            if self._host_acc is not None:
                raise MeshCodecError("folder already degraded")  # -> host()
            acc = self._device_acc()
            self._acc = fn(acc, tiles, ws, *chunks)
        self.ring_flushes += 1
        return True

    def _build_eager(self, kb: int):
        from jax.sharding import PartitionSpec as P

        codec = self.codec

        def body(a, t_, w_, *xs):
            # Each x is this device's [shard] slice of one chunk: one
            # dynamic row update per chunk, nothing widened twice, no
            # batch-matrix materialization at any width.
            for i, x in enumerate(xs):
                a = a.at[t_[i]].add(w_[i] * _bf16_widen(x))
            return a

        in_specs = (P(None, "codec"), P(), P()) + (P("codec"),) * kb
        return codec._shard_map(
            body, in_specs, P(None, "codec"), donate_argnums=(0,)
        )

    def _build_flush(self, lower: str, per_dev: int):
        import jax
        from jax.sharding import PartitionSpec as P

        jnp = _jnp()
        codec = self.codec
        nd = codec._ndev
        shard = self.shard
        n_tiles = self.n_tiles
        tile_elems = self.tile_elems

        del tile_elems  # width only flows through nd * shard below

        if lower == "xla":

            def body(a, x_, t_, w_):
                # Same schedule, XLA collective: the reduce-scatter runs on
                # the RAW bf16 bits (an all_to_all moving half the bytes a
                # f32 partial exchange would), then the decode+fold is
                # column-local — never a full-width f32 partial per device.
                # x_ local [per_dev, nd*shard] u16; t_/w_ replicated [kb].
                xs = x_.reshape(per_dev, nd, shard)
                mine = jax.lax.all_to_all(
                    xs, "codec", split_axis=1, concat_axis=0, tiled=False
                )
                # [nd, per_dev, shard]: every chunk's slice of my columns,
                # source-device-major == the global batch row order. The
                # fold scatter-adds straight into the donated accumulator —
                # no per-device partial buffer exists at any width.
                mine = mine.reshape(per_dev * nd, shard)
                return a.at[t_].add(w_[:, None] * _bf16_widen(mine))

        else:
            interp = lower == "interpret"
            kern = functools.partial(
                _ring_fold_kernel, nd, per_dev, shard, n_tiles, not interp
            )

            def body(a, x_, t_, w_):
                from jax.experimental import pallas as pl
                from jax.experimental.pallas import tpu as pltpu

                return pl.pallas_call(
                    kern,
                    grid=(nd,),
                    out_shape=jax.ShapeDtypeStruct((n_tiles, shard), jnp.float32),
                    in_specs=[
                        pl.BlockSpec(memory_space=pltpu.SMEM),
                        pl.BlockSpec(memory_space=pltpu.SMEM),
                        pl.BlockSpec(memory_space=pltpu.ANY),
                        pl.BlockSpec(memory_space=pltpu.ANY),
                    ],
                    out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
                    scratch_shapes=[
                        pltpu.VMEM((2, n_tiles, shard), jnp.float32),
                        pltpu.VMEM((n_tiles, shard), jnp.float32),
                        pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.REGULAR,
                    ],
                    interpret=interp,
                    compiler_params=_compiler_params(interp),
                )(t_, w_, x_, a)

        # The pallas ring folds each device's OWN chunks step by step
        # (tiles/ws row-sharded); the xla all_to_all hands every device all
        # kb chunks' column slices, so it reads the full (tiny) tiles/ws.
        meta_spec = P() if lower == "xla" else P("codec")
        return codec._shard_map(
            body,
            (P(None, "codec"), P("codec", None), meta_spec, meta_spec),
            P(None, "codec"),
            donate_argnums=(0,),
        )

    # -- result -----------------------------------------------------------

    def result(self) -> np.ndarray:
        """Flush the tail, then reassemble the sharded accumulator with the
        ring all-gather — one device pass, one host fetch. Falls back to
        the inherited sharded host gather on any device failure. The xla
        lowering skips the device all-gather: XLA's host pull of a sharded
        array already fetches each shard exactly once, and replicating the
        full accumulator on every device first is pure extra traffic."""
        self.flush()
        with self._lock:
            acc = self._acc
        if acc is None or not self.codec.active or self._lower_cfg == "xla":
            return super().result()

        def dev() -> np.ndarray:
            fn = self.codec._jit(
                ("ring_ag", self._lower_cfg, self.n_tiles, self.tile_elems),
                self._build_gather,
            )
            full = np.asarray(fn(acc))
            with self._lock:
                self._acc = None
            return full.ravel()[: self.n_elems].copy()

        return self.codec._run(dev, lambda: super(RingMeanFolder, self).result())

    def _build_gather(self):
        import jax
        from jax.sharding import PartitionSpec as P

        jnp = _jnp()
        codec = self.codec
        nd = codec._ndev
        shard = self.shard
        n_tiles = self.n_tiles
        lower = self._lower_cfg
        gather_bytes = 2 * nd * n_tiles * shard * 4
        if lower == "compiled" and gather_bytes > _VMEM_CAP_BYTES:
            self._note_vmem_fallback("gather", gather_bytes)
            lower = "xla"

        if lower == "xla":

            def body(a):
                return jax.lax.all_gather(a, "codec", axis=1, tiled=True)

        else:
            interp = lower == "interpret"
            kern = functools.partial(_ring_ag_kernel, nd)

            def body(a):
                from jax.experimental import pallas as pl
                from jax.experimental.pallas import tpu as pltpu

                o = pl.pallas_call(
                    kern,
                    grid=(nd - 1,),
                    out_shape=jax.ShapeDtypeStruct(
                        (nd, n_tiles, shard), jnp.float32
                    ),
                    in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
                    out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
                    scratch_shapes=[
                        pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.DMA,
                    ],
                    interpret=interp,
                    compiler_params=_compiler_params(interp),
                )(a)
                return jnp.swapaxes(o, 0, 1).reshape(n_tiles, nd * shard)

        return codec._shard_map(body, (P(None, "codec"),), P(None, None))


def _compiler_params(interp: bool):
    """Mark the kernel side-effecting for the compiled lowering (remote
    DMA + semaphores must not be DCE'd); the interpreter takes none."""
    if interp:
        return None
    try:
        from jax.experimental.pallas import tpu as pltpu

        params = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams", None
        )
        return params(has_side_effects=True) if params else None
    except Exception:  # noqa: BLE001 — params are a silicon-only hint
        return None
