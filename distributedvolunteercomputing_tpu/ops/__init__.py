from distributedvolunteercomputing_tpu.ops.attention import multi_head_attention, rope

__all__ = ["multi_head_attention", "rope"]
