"""Flash attention as Pallas TPU kernels (forward + backward).

The reference's hot path is a CUDA ``train_step`` (BASELINE.json:5); its TPU
equivalent for the transformer zoo is attention that never materialises the
[Tq, Tk] score matrix in HBM. Forward is a block-wise online-softmax kernel
(running max / denominator in f32, MXU matmuls in the input dtype); backward
is the standard two-kernel flash recomputation (dq from k-blocks, dk/dv from
q-blocks) using the saved logsumexp, wired up through ``jax.custom_vjp``.

On non-TPU backends the kernels run in interpret mode, so the same code path
is unit-testable on the CPU mesh (tests/conftest.py forces JAX_PLATFORMS=cpu).
Numerics are validated against ops/attention.py's plain-XLA core in
tests/test_pallas_attention.py.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
# Per-row softmax stats (lse, delta) are carried with a broadcast 128-lane
# trailing dim: Mosaic requires the last block dim to be 128-divisible or
# full, and a [T]-shaped row vector satisfies neither (same layout as the
# in-tree jax.experimental.pallas.ops.tpu.flash_attention).
LANES = 128


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pad_seq(x: jax.Array, block: int) -> jax.Array:
    t = x.shape[2]
    pad = (-t) % block
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_q, block_k, tk_valid):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, D]
    tk_padded = k_ref.shape[2]
    n_kblocks = tk_padded // block_k

    if causal:
        # Rows in this q block see keys up to (iq+1)*bq - 1; later k blocks
        # are entirely masked, so don't visit them at all.
        n_kblocks = jnp.minimum(n_kblocks, pl.cdiv((iq + 1) * block_q, block_k))

    row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        kblk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        col = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = col < tk_valid
        if causal:
            mask = mask & (col <= row)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * corr + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[3]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kblocks, body, (m0, l0, acc0))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l_safe), (block_q, LANES))


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
    block_q: int, block_k: int, interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bq, bk = min(block_q, tq), min(block_k, tk)
    scale = 1.0 / (d ** 0.5)

    qp, kp, vp = _pad_seq(q, bq), _pad_seq(k, bk), _pad_seq(v, bk)
    tq_p, tk_p = qp.shape[2], kp.shape[2]

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk, tk_valid=tk
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, tq_p // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda i, j, iq: (i, j, iq, 0)),
            pl.BlockSpec((1, 1, tk_p, d), lambda i, j, iq: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, tk_p, d), lambda i, j, iq: (i, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda i, j, iq: (i, j, iq, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda i, j, iq: (i, j, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, tq_p, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :, :tq], lse[:, :, :tq, 0]


# ---------------------------------------------------------------------------
# backward: dq kernel (iterates k blocks) and dkv kernel (iterates q blocks)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, scale, causal, block_q, block_k, tk_valid,
):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, 0:1]
    delta = delta_ref[0, 0][:, 0:1]
    tk_padded = k_ref.shape[2]
    n_kblocks = tk_padded // block_k
    if causal:
        n_kblocks = jnp.minimum(n_kblocks, pl.cdiv((iq + 1) * block_q, block_k))

    row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, dq):
        kblk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        col = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = col < tk_valid
        if causal:
            mask = mask & (col <= row)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(
        0, n_kblocks, body, jnp.zeros((block_q, q_ref.shape[3]), jnp.float32)
    )
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, causal, block_q, block_k, tk_valid,
):
    jk = pl.program_id(2)
    kblk = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
    vblk = v_ref[0, 0].astype(jnp.float32)
    tq_padded = q_ref.shape[2]
    n_qblocks = tq_padded // block_q
    # Causal: q blocks strictly before this k block's first row see nothing.
    start = (jk * block_k) // block_q if causal else 0

    col = jk * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    col_valid = col < tk_valid

    def body(i, carry):
        dk, dv = carry
        qblk = q_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32) * scale
        doblk = do_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q), 0:1]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q), 0:1]
        s = jax.lax.dot_general(
            qblk, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        mask = col_valid
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            mask = mask & (col <= row)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(
            p, doblk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            doblk, vblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds, qblk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    d = q_ref.shape[3]
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, n_qblocks, body, (dk0, dv0))
    # q already carried `scale`, so ds.T @ (q*scale) is the full dk.
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_backward(
    causal: bool, block_q: int, block_k: int, interpret: bool,
    residuals, g,
):
    q, k, v, out, lse = residuals
    do = g
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bq, bk = min(block_q, tq), min(block_k, tk)
    scale = 1.0 / (d ** 0.5)

    # delta_i = sum_d dO_i O_i — the softmax-jacobian diagonal term.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    qp, kp, vp = _pad_seq(q, bq), _pad_seq(k, bk), _pad_seq(v, bk)
    dop = _pad_seq(do, bq)
    tq_p, tk_p = qp.shape[2], kp.shape[2]
    pad_q = tq_p - tq
    if pad_q:
        # Padded q rows must not contribute to dk/dv: exp(NEG_INF - 0) would
        # be 1, so give them lse=+large instead so p == 0 exactly.
        lse_p = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_q)), constant_values=1e30)
        delta_p = jnp.pad(delta, ((0, 0), (0, 0), (0, pad_q)))
    else:
        lse_p, delta_p = lse, delta
    lse_p = jnp.broadcast_to(lse_p[..., None], (*lse_p.shape, LANES))
    delta_p = jnp.broadcast_to(delta_p[..., None], (*delta_p.shape, LANES))

    qspec = pl.BlockSpec((1, 1, bq, d), lambda i, j, g_: (i, j, g_, 0))
    kfull = pl.BlockSpec((1, 1, tk_p, d), lambda i, j, g_: (i, j, 0, 0))
    qfull = pl.BlockSpec((1, 1, tq_p, d), lambda i, j, g_: (i, j, 0, 0))
    vecq = pl.BlockSpec((1, 1, bq, LANES), lambda i, j, g_: (i, j, g_, 0))
    vecq_full = pl.BlockSpec((1, 1, tq_p, LANES), lambda i, j, g_: (i, j, 0, 0))

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk, tk_valid=tk,
        ),
        grid=(b, h, tq_p // bq),
        in_specs=[qspec, kfull, kfull, qspec, vecq, vecq],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, tq_p, d), q.dtype),
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    kspec = pl.BlockSpec((1, 1, bk, d), lambda i, j, g_: (i, j, g_, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk, tk_valid=tk,
        ),
        grid=(b, h, tk_p // bk),
        in_specs=[qfull, kspec, kspec, qfull, vecq_full, vecq_full],
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tk_p, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, tk_p, d), v.dtype),
        ],
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    return dq[:, :, :tq], dk[:, :, :tk], dv[:, :, :tk]


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,  # [B, H, Tk, D]
    v: jax.Array,  # [B, H, Tk, D]
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Drop-in for ops.attention.attention_core (no additive mask support).

    Causal masking is top-left aligned: row i attends keys 0..i. For
    Tq != Tk this differs from attention_core's bottom-right alignment —
    the router in ops/attention.py only sends square causal shapes here.
    """
    out, _ = _flash_forward(
        q, k, v, causal, block_q, block_k,
        _interpret_default() if interpret is None else interpret,
    )
    return out


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(
        q, k, v, causal, block_q, block_k,
        _interpret_default() if interpret is None else interpret,
    )
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, block_q, block_k, interpret, residuals, g):
    return _flash_backward(
        causal, block_q, block_k,
        _interpret_default() if interpret is None else interpret,
        residuals, g,
    )


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def flash_mha(
    q: jax.Array,  # [B, T, d_model] (already projected)
    k: jax.Array,
    v: jax.Array,
    n_heads: int,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Multi-head wrapper matching ops.attention.multi_head_attention."""
    from distributedvolunteercomputing_tpu.ops.attention import merge_heads, split_heads

    out = flash_attention(
        split_heads(q, n_heads), split_heads(k, n_heads), split_heads(v, n_heads),
        causal, block_q, block_k,
    )
    return merge_heads(out)
