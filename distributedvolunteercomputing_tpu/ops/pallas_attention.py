"""Flash attention as Pallas TPU kernels (forward + backward).

The reference's hot path is a CUDA ``train_step`` (BASELINE.json:5); its TPU
equivalent for the transformer zoo is attention that never materialises the
[Tq, Tk] score matrix in HBM. Forward is a block-wise online-softmax kernel;
backward is the standard two-kernel flash recomputation (dq from k-blocks,
dk/dv from q-blocks) using the saved logsumexp, wired up through
``jax.custom_vjp``.

r5 redesign, motivated by the r4 hardware sweep
(experiments/results/attn_sweep.json):

- **K/V stream through the GRID** (innermost "arbitrary" dimension) with
  online-softmax state in VMEM scratch, instead of pulling the whole key
  sequence into VMEM per grid step. VMEM footprint is now O(block) not
  O(T), and the Mosaic program is one small k-block body regardless of
  sequence length — the r4 kernel's full-[T, D] windows were the prime
  suspect for the remote-compile failures at f32 T>=4096 / bf16 T=8192
  (the shapes where XLA cliffs to 360 ms and flash exists to win).
- **Matmuls run in the INPUT dtype** (``preferred_element_type=f32``
  accumulation). The r4 kernel upcast q/k/v to f32 before every dot,
  forcing f32 MXU throughput — the measured reason flash LOST to XLA in
  bf16 at T=512-2048 (0.56-0.94x). bf16 x bf16 products are exact in the
  f32 accumulator, so the bf16 path loses no precision on the score
  matmul; the p @ v / gradient matmuls round p/ds to the input dtype (the
  standard flash trade, applied only when inputs are sub-f32).
- Causal blocks that are fully masked skip their compute via ``pl.when``
  (the grid still visits them — index-remapping them away is not worth
  the complexity at these shapes).

On non-TPU backends the kernels run in interpret mode, so the same code
path is unit-testable on the CPU mesh (tests/conftest.py forces
JAX_PLATFORMS=cpu). Numerics are validated against ops/attention.py's
plain-XLA core in tests/test_pallas_attention.py.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# Per-row softmax stats (lse, delta) are carried with a broadcast 128-lane
# trailing dim: Mosaic requires the last block dim to be 128-divisible or
# full, and a [T]-shaped row vector satisfies neither (same layout as the
# in-tree jax.experimental.pallas.ops.tpu.flash_attention).
LANES = 128


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pad_seq(x: jax.Array, block: int) -> jax.Array:
    t = x.shape[2]
    pad = (-t) % block
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


def _dot(a: jax.Array, b: jax.Array, dims) -> jax.Array:
    """dot_general with f32 accumulation, operands kept in THEIR dtype —
    sub-f32 inputs hit the MXU at native rate (see module docstring)."""
    return jax.lax.dot_general(a, b, (dims, ((), ())), preferred_element_type=jnp.float32)


def _to_input_dtype(p: jax.Array, like: jax.Array) -> jax.Array:
    """Round a f32 intermediate to the input dtype for the next matmul —
    only when the inputs are sub-f32 (bf16 path); f32 stays exact."""
    return p.astype(like.dtype) if like.dtype != jnp.float32 else p


def _compiler_params(interpret: bool):
    if interpret:
        return None
    return pltpu.CompilerParams(
        # b, h, q-blocks run in any order; the k-stream dim is sequential
        # (its scratch carry makes steps order-dependent).
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
    )


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale, causal, block_q, block_k, tk_valid, n_k,
):
    iq, jk = pl.program_id(2), pl.program_id(3)

    @pl.when(jk == 0)
    def init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Last k block this q block attends (causal rows end at (iq+1)*bq - 1).
    last_jk = n_k - 1
    if causal:
        last_jk = jnp.minimum(last_jk, ((iq + 1) * block_q - 1) // block_k)

    @pl.when(jk <= last_jk)
    def compute():
        q = q_ref[0, 0]  # [bq, D], input dtype
        kblk = k_ref[0, 0]  # [bk, D]
        s = _dot(q, kblk, ((1,), (1,))) * scale  # f32 [bq, bk]
        col = jk * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = col < tk_valid
        if causal:
            row = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (col <= row)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[...] = jnp.broadcast_to(
            l_prev * corr + jnp.sum(p, axis=1, keepdims=True), l_scr.shape
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        acc_scr[...] = acc_scr[...] * corr + _dot(
            _to_input_dtype(p, v_ref), v_ref[0, 0], ((1,), (0,))
        )

    @pl.when(jk == n_k - 1)
    def finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_scr[:, 0:1] + jnp.log(l_safe), lse_ref.shape[2:]
        )


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
    block_q: int, block_k: int, interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bq, bk = min(block_q, tq), min(block_k, tk)
    scale = 1.0 / (d ** 0.5)

    qp, kp, vp = _pad_seq(q, bq), _pad_seq(k, bk), _pad_seq(v, bk)
    tq_p, tk_p = qp.shape[2], kp.shape[2]
    n_k = tk_p // bk

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=bq, block_k=bk, tk_valid=tk, n_k=n_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, tq_p // bq, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda i, j, iq, jk: (i, j, iq, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda i, j, iq, jk: (i, j, jk, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda i, j, iq, jk: (i, j, jk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda i, j, iq, jk: (i, j, iq, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda i, j, iq, jk: (i, j, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, tq_p, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),  # running max
            pltpu.VMEM((bq, LANES), jnp.float32),  # running denominator
            pltpu.VMEM((bq, d), jnp.float32),      # un-normalized output
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :, :tq], lse[:, :, :tq, 0]


# ---------------------------------------------------------------------------
# backward: dq kernel (streams k blocks) and dkv kernel (streams q blocks)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, scale, causal, block_q, block_k, tk_valid, n_k,
):
    iq, jk = pl.program_id(2), pl.program_id(3)

    @pl.when(jk == 0)
    def init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    last_jk = n_k - 1
    if causal:
        last_jk = jnp.minimum(last_jk, ((iq + 1) * block_q - 1) // block_k)

    @pl.when(jk <= last_jk)
    def compute():
        q = q_ref[0, 0]
        kblk = k_ref[0, 0]
        vblk = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s = _dot(q, kblk, ((1,), (1,))) * scale
        col = jk * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = col < tk_valid
        if causal:
            row = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (col <= row)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk] f32
        dp = _dot(do, vblk, ((1,), (1,)))
        ds = p * (dp - delta)
        dq_scr[...] = dq_scr[...] + _dot(
            _to_input_dtype(ds, k_ref), kblk, ((1,), (0,))
        )

    @pl.when(jk == n_k - 1)
    def finalize():
        dq_ref[0, 0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr,
    *, scale, causal, block_q, block_k, tk_valid, n_q,
):
    jk, iq = pl.program_id(2), pl.program_id(3)

    @pl.when(iq == 0)
    def init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # Causal: q blocks strictly before this k block's first row see nothing.
    first_iq = (jk * block_k) // block_q if causal else 0

    @pl.when(iq >= first_iq)
    def compute():
        kblk = k_ref[0, 0]
        vblk = v_ref[0, 0]
        qblk = q_ref[0, 0]
        doblk = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s = _dot(qblk, kblk, ((1,), (1,))) * scale
        col = jk * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = col < tk_valid
        if causal:
            row = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (col <= row)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk] f32
        p_in = _to_input_dtype(p, v_ref)
        dv_scr[...] = dv_scr[...] + _dot(p_in, doblk, ((0,), (0,)))
        dp = _dot(doblk, vblk, ((1,), (1,)))
        ds = p * (dp - delta)
        ds_in = _to_input_dtype(ds, q_ref)
        # dk accumulates ds.T @ q; scale applied once at finalize.
        dk_scr[...] = dk_scr[...] + _dot(ds_in, qblk, ((0,), (0,)))

    @pl.when(iq == n_q - 1)
    def finalize():
        dk_ref[0, 0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(
    causal: bool, block_q: int, block_k: int, interpret: bool,
    residuals, g,
):
    q, k, v, out, lse = residuals
    do = g
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bq, bk = min(block_q, tq), min(block_k, tk)
    scale = 1.0 / (d ** 0.5)

    # delta_i = sum_d dO_i O_i — the softmax-jacobian diagonal term.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    qp, kp, vp = _pad_seq(q, bq), _pad_seq(k, bk), _pad_seq(v, bk)
    dop = _pad_seq(do, bq)
    tq_p, tk_p = qp.shape[2], kp.shape[2]
    n_q, n_k = tq_p // bq, tk_p // bk
    pad_q = tq_p - tq
    if pad_q:
        # Padded q rows must not contribute to dk/dv: exp(NEG_INF - 0) would
        # be 1, so give them lse=+large instead so p == 0 exactly.
        lse_p = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_q)), constant_values=1e30)
        delta_p = jnp.pad(delta, ((0, 0), (0, 0), (0, pad_q)))
    else:
        lse_p, delta_p = lse, delta
    lse_p = jnp.broadcast_to(lse_p[..., None], (*lse_p.shape, LANES))
    delta_p = jnp.broadcast_to(delta_p[..., None], (*delta_p.shape, LANES))

    # dq: grid (b, h, q-blocks, k-stream)
    qspec = pl.BlockSpec((1, 1, bq, d), lambda i, j, iq, jk: (i, j, iq, 0))
    kstream = pl.BlockSpec((1, 1, bk, d), lambda i, j, iq, jk: (i, j, jk, 0))
    vecq = pl.BlockSpec((1, 1, bq, LANES), lambda i, j, iq, jk: (i, j, iq, 0))
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk, tk_valid=tk, n_k=n_k,
        ),
        grid=(b, h, n_q, n_k),
        in_specs=[qspec, kstream, kstream, qspec, vecq, vecq],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, tq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    # dk/dv: grid (b, h, k-blocks, q-stream)
    kspec = pl.BlockSpec((1, 1, bk, d), lambda i, j, jk, iq: (i, j, jk, 0))
    qstream = pl.BlockSpec((1, 1, bq, d), lambda i, j, jk, iq: (i, j, iq, 0))
    vecq_s = pl.BlockSpec((1, 1, bq, LANES), lambda i, j, jk, iq: (i, j, iq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk, tk_valid=tk, n_q=n_q,
        ),
        grid=(b, h, tk_p // bk, n_q),
        in_specs=[qstream, kspec, kspec, qstream, vecq_s, vecq_s],
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tk_p, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, tk_p, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    return dq[:, :, :tq], dk[:, :, :tk], dv[:, :, :tk]


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,  # [B, H, Tk, D]
    v: jax.Array,  # [B, H, Tk, D]
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Drop-in for ops.attention.attention_core (no additive mask support).

    Causal masking is top-left aligned: row i attends keys 0..i. For
    Tq != Tk this differs from attention_core's bottom-right alignment —
    the router in ops/attention.py only sends square causal shapes here.
    """
    out, _ = _flash_forward(
        q, k, v, causal, block_q, block_k,
        _interpret_default() if interpret is None else interpret,
    )
    return out


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(
        q, k, v, causal, block_q, block_k,
        _interpret_default() if interpret is None else interpret,
    )
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, block_q, block_k, interpret, residuals, g):
    return _flash_backward(
        causal, block_q, block_k,
        _interpret_default() if interpret is None else interpret,
        residuals, g,
    )


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def flash_mha(
    q: jax.Array,  # [B, T, d_model] (already projected)
    k: jax.Array,
    v: jax.Array,
    n_heads: int,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Multi-head wrapper matching ops.attention.multi_head_attention."""
    from distributedvolunteercomputing_tpu.ops.attention import merge_heads, split_heads

    out = flash_attention(
        split_heads(q, n_heads), split_heads(k, n_heads), split_heads(v, n_heads),
        causal, block_q, block_k,
    )
    return merge_heads(out)
