"""Attention ops shared by the transformer zoo (BERT / GPT-2 / Llama).

Plain-XLA reference path: one fused einsum-softmax-einsum that XLA maps onto
the MXU. The pallas flash kernel (ops/pallas_attention.py) and the ring
attention sequence-parallel path (parallel/ring_attention.py) are drop-in
replacements for ``multi_head_attention``'s core.

Softmax statistics run in float32 even when q/k/v are bfloat16 — MXU matmuls
in bf16, reductions in f32, the standard TPU recipe.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax
import jax.numpy as jnp

# Active sequence-parallel context: (mesh, axis_name, impl) or None. When
# set, the attention core routes to the chosen SP implementation so the
# model code is unchanged between single-device and sp-sharded runs. Set by
# make_sharded_train_step at TRACE time (it wraps the step body), or manually.
# impl: "ring" (K/V rotate via ppermute — works for any head count, memory
# O(T/sp) per device) or "ulysses" (all-to-all swaps seq<->heads around a
# full-sequence attention — fewer collective hops on ICI; needs H % sp == 0).
_seq_ctx = None


@contextlib.contextmanager
def sequence_parallel(mesh, axis: str = "sp", impl: str = "ring"):
    if impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown sequence-parallel impl {impl!r}")
    global _seq_ctx
    prev = _seq_ctx
    _seq_ctx = (mesh, axis, impl)
    try:
        yield
    finally:
        _seq_ctx = prev

# Attention core selection: "xla" (fused einsum-softmax-einsum), "flash"
# (pallas kernel, ops/pallas_attention.py), or "auto" (flash on TPU for
# mask-free sequences long enough to fill a block, xla otherwise).
#
# auto routing is measurement-backed (round 4, TPU v5 lite,
# experiments/results/attn_sweep.json + attn_ab.json + the bench A/B),
# and dtype-aware because the measurements differ by dtype:
#   - f32: flagship end-to-end (gpt2_small, bs=8, T=1024) runs 59.07
#     samples/s with the flash kernel vs 51.11 with the XLA core (+15.6%);
#     per-op fwd+bwd agrees from T=1024 (1.02-1.22x). -> flash from 1024.
#   - bf16: per-op XLA wins at T<=2048 (flash 0.85-0.95x) and flash wins
#     at T=4096 (1.48x); no end-to-end bf16 A/B exists yet. -> flash from
#     4096 only.
# Known residual: at T=8192 flash did not compile on the dev tunnel
# (remote-compile-helper HTTP 500). That is infra, not a kernel property:
# the PURE-XLA full-model compile at bs=16/32 died with the identical
# HTTP 500 (BASELINE.md TPU table) — the tunnel's helper kills large
# compiles of any kind. On a standard TPU runtime flash is the
# memory-feasible option at long T (no [T,T] score matrix); users on a
# runtime where it won't compile can force DVC_ATTN_IMPL=xla.
# Micro-benchmarks on this chip's tunneled runtime need care —
# block_until_ready does not synchronize (experiments/timing_diag.py), so
# only chained-execution numbers (the bench, the differenced sweep) are
# trusted for this decision.
_impl = os.environ.get("DVC_ATTN_IMPL", "auto")
# Measured crossovers for auto routing (see block comment above).
_AUTO_FLASH_MIN_T_F32 = 1024
_AUTO_FLASH_MIN_T_OTHER = 4096


def set_attention_impl(name: str) -> None:
    """Select the attention core for subsequent TRACES.

    The impl is read at trace time: computations already jitted (and cached
    by shape) keep whatever core they were traced with — call this before
    the first train step, not between steps.
    """
    global _impl
    if name not in ("auto", "xla", "flash"):
        raise ValueError(f"unknown attention impl {name!r}")
    _impl = name


def get_attention_impl() -> str:
    return _impl


def _route_to_flash(q: jax.Array, k: jax.Array, causal: bool, mask) -> bool:
    if mask is not None:  # flash path has no additive-mask support
        return False
    if causal and q.shape[-2] != k.shape[-2]:
        # The flash kernel's causal mask is top-left aligned (row i sees keys
        # 0..i); this XLA core is bottom-right aligned for Tq != Tk. Only the
        # square case agrees, so rectangular causal always takes the XLA path.
        return False
    if _impl == "flash":
        return True
    from distributedvolunteercomputing_tpu.utils.jaxenv import tpu_backend

    min_t = (
        _AUTO_FLASH_MIN_T_F32 if q.dtype == jnp.float32 else _AUTO_FLASH_MIN_T_OTHER
    )
    return _impl == "auto" and tpu_backend() and q.shape[-2] >= min_t


def attention_core(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,  # [B, H, Tk, D]
    v: jax.Array,  # [B, H, Tk, D]
    causal: bool = False,
    mask: Optional[jax.Array] = None,  # [B, 1|H, Tq, Tk] additive-able bool
) -> jax.Array:
    if _seq_ctx is not None and mask is None and q.shape[-2] == k.shape[-2]:
        mesh, axis, impl = _seq_ctx
        if impl == "ulysses":
            from distributedvolunteercomputing_tpu.parallel.ulysses import (
                ulysses_attention_bhtd,
            )

            return ulysses_attention_bhtd(q, k, v, mesh, axis, causal)
        from distributedvolunteercomputing_tpu.parallel.ring_attention import ring_attention_bhtd

        return ring_attention_bhtd(q, k, v, mesh, axis, causal)
    return attention_core_local(q, k, v, causal, mask)


def attention_core_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """The single-device core (flash kernel or fused XLA), with no
    sequence-parallel routing — also the inner attention the Ulysses path
    runs per head-group after its all-to-all."""
    if _route_to_flash(q, k, causal, mask):
        from distributedvolunteercomputing_tpu.ops.pallas_attention import flash_attention

        bq, bk = _flash_blocks()
        return flash_attention(q, k, v, causal, bq, bk)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(causal_mask, logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_blocks() -> tuple:
    """Flash block-size tuning knobs for chip-window sweeps.

    Read at TRACE time and captured into the compiled program: changing the
    env after a function has compiled does not retrace it, so block A/Bs
    must use fresh processes or freshly-defined jitted closures (attn_sweep
    builds a new closure per arm — cache can't alias across arms).
    Validated here so a bad value names the knob instead of failing deep
    inside Mosaic with a zero-sized grid."""
    try:
        bq = int(os.environ.get("DVC_FLASH_BLOCK_Q") or "128")
        bk = int(os.environ.get("DVC_FLASH_BLOCK_K") or "128")
    except ValueError:
        raise ValueError(
            "DVC_FLASH_BLOCK_Q / DVC_FLASH_BLOCK_K must be integers; got "
            f"{os.environ.get('DVC_FLASH_BLOCK_Q')!r} / "
            f"{os.environ.get('DVC_FLASH_BLOCK_K')!r}"
        ) from None
    if bq < 8 or bk < 8 or bq % 8 or bk % 8:
        raise ValueError(
            f"DVC_FLASH_BLOCK_Q/K must be multiples of 8 and >= 8 (TPU "
            f"sublane tiling), got {bq}/{bk}"
        )
    return bq, bk


def split_heads(x: jax.Array, n_heads: int) -> jax.Array:
    b, t, d = x.shape
    return x.reshape(b, t, n_heads, d // n_heads).transpose(0, 2, 1, 3)


def merge_heads(x: jax.Array) -> jax.Array:
    b, h, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)


def multi_head_attention(
    q: jax.Array,  # [B, T, d_model] (already projected)
    k: jax.Array,
    v: jax.Array,
    n_heads: int,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    out = attention_core(
        split_heads(q, n_heads), split_heads(k, n_heads), split_heads(v, n_heads),
        causal=causal, mask=mask,
    )
    return merge_heads(out)


def rope(x: jax.Array, positions: Optional[jax.Array] = None, base: float = 10000.0) -> jax.Array:
    """Rotary position embedding over the last dim of ``x`` [B, H, T, D]."""
    d = x.shape[-1]
    t = x.shape[-2]
    if positions is None:
        positions = jnp.arange(t)
    freqs = base ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2].astype(jnp.float32), x[..., 1::2].astype(jnp.float32)
    rx1 = x1 * cos - x2 * sin
    rx2 = x1 * sin + x2 * cos
    out = jnp.stack([rx1, rx2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)
