"""Robust (Byzantine-tolerant) aggregation — host-side numpy.

Reference parity: "Byzantine-tolerant aggregation" (BASELINE.json:5,:11),
prescribed to stay on host (BASELINE.json:5 "Keep the coordinator/DHT
peer-discovery and Byzantine-tolerant aggregation on the host"). Inputs are
the flattened float32 param buffers from utils.pytree — one row per peer.

Estimators (standard robust-aggregation menu, cf. Krum/trimmed-mean
literature):
- mean            — baseline (not robust), supports per-peer weights
- coordinate median — breaks down at 50% adversaries, cheap
- trimmed mean    — drop the b largest/smallest per coordinate
- krum            — select the contribution closest to its n-f-2 neighbours
- geometric median — Weiszfeld iterations, strong + smooth

All run in O(n^2 D) worst case (krum/geomedian) with n = volunteers in the
round (reference scale: 4, BASELINE.json:2) — cheap next to the WAN transfer
(SURVEY.md §7 hard part d).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def mean(stack: np.ndarray, weights: Optional[np.ndarray] = None) -> np.ndarray:
    if weights is None:
        return stack.mean(axis=0)
    w = np.asarray(weights, np.float64)
    w = w / w.sum()
    return (stack * w[:, None].astype(stack.dtype)).sum(axis=0)


# Below this size the numpy paths win (thread spawn isn't free); above it the
# native threaded column-sort beats numpy's full-matrix sort ~2x.
_NATIVE_CUTOFF = 1 << 16


def coordinate_median(stack: np.ndarray) -> np.ndarray:
    if stack.dtype == np.float32 and stack.size >= _NATIVE_CUTOFF:
        from distributedvolunteercomputing_tpu import native

        if native.available():
            return native.coordinate_median(np.ascontiguousarray(stack))
    return np.median(stack, axis=0).astype(stack.dtype)


def trimmed_mean(stack: np.ndarray, trim: int = 1) -> np.ndarray:
    n = stack.shape[0]
    if 2 * trim >= n:
        raise ValueError(f"trim={trim} too large for n={n}")
    if stack.dtype == np.float32 and stack.size >= _NATIVE_CUTOFF:
        from distributedvolunteercomputing_tpu import native

        if native.available():
            return native.trimmed_mean(np.ascontiguousarray(stack), trim)
    srt = np.sort(stack, axis=0)
    return srt[trim : n - trim].mean(axis=0)


def krum(stack: np.ndarray, n_byzantine: int = 1, multi: int = 1) -> np.ndarray:
    """(Multi-)Krum: average the ``multi`` contributions with the smallest
    sum of squared distances to their n - f - 2 nearest neighbours."""
    n = stack.shape[0]
    if n < n_byzantine + 3:
        # Not enough honest mass for Krum's guarantee; degrade to median.
        return coordinate_median(stack)
    d2 = ((stack[:, None, :] - stack[None, :, :]) ** 2).sum(axis=-1)
    np.fill_diagonal(d2, np.inf)
    n_neighbors = n - n_byzantine - 2
    scores = np.sort(d2, axis=1)[:, :n_neighbors].sum(axis=1)
    chosen = np.argsort(scores)[:multi]
    return stack[chosen].mean(axis=0)


def geometric_median(stack: np.ndarray, iters: int = 32, eps: float = 1e-8) -> np.ndarray:
    """Weiszfeld algorithm; robust to <50% arbitrary corruption.

    Starts from the coordinate median, not the mean: a mean start under large
    outliers puts z so far out that convergence needs many more iterations.
    """
    z = coordinate_median(stack).astype(np.float64)
    for _ in range(iters):
        dist = np.linalg.norm(stack - z[None, :], axis=1)
        dist = np.maximum(dist, eps)
        w = 1.0 / dist
        z_new = (stack * w[:, None]).sum(axis=0) / w.sum()
        if np.linalg.norm(z_new - z) < eps * (1 + np.linalg.norm(z)):
            z = z_new
            break
        z = z_new
    return z.astype(stack.dtype)


AGGREGATORS = {
    "mean": mean,
    "median": coordinate_median,
    "trimmed_mean": trimmed_mean,
    "krum": krum,
    "geometric_median": geometric_median,
}


def aggregate(stack: np.ndarray, method: str = "mean", **kw) -> np.ndarray:
    if method not in AGGREGATORS:
        raise KeyError(f"unknown aggregator {method!r}; known: {sorted(AGGREGATORS)}")
    if stack.ndim != 2:
        raise ValueError(f"expected [n_peers, D] stack, got shape {stack.shape}")
    return AGGREGATORS[method](stack, **kw)
