"""Robust (Byzantine-tolerant) aggregation — host-side numpy.

Reference parity: "Byzantine-tolerant aggregation" (BASELINE.json:5,:11),
prescribed to stay on host (BASELINE.json:5 "Keep the coordinator/DHT
peer-discovery and Byzantine-tolerant aggregation on the host"). Inputs are
the flattened float32 param buffers from utils.pytree — one row per peer.

Estimators (standard robust-aggregation menu, cf. Krum/trimmed-mean
literature):
- mean            — baseline (not robust), supports per-peer weights
- coordinate median — breaks down at 50% adversaries, cheap
- trimmed mean    — drop the b largest/smallest per coordinate
- krum            — select the contribution closest to its n-f-2 neighbours
- geometric median — Weiszfeld iterations, strong + smooth
- bulyan          — Multi-Krum selection then per-coordinate trimmed mean
                    over the selected set (El Mhamdi et al.): Krum's
                    selection bounds WHO aggregates, the trim bounds each
                    COORDINATE — defends the leeway a single Krum pick
                    leaves in high dimensions
- centered_clip   — iterative L2-clipped averaging (Karimireddy et al.):
                    bounds each peer's PULL in L2 per iteration, closing
                    the spread-over-many-coordinates evasion that
                    coordinate-wise trims leave open

All run in O(n^2 D) worst case (krum/geomedian) with n = volunteers in the
round (reference scale: 4, BASELINE.json:2) — cheap next to the WAN transfer
(SURVEY.md §7 hard part d).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def mean(stack: np.ndarray, weights: Optional[np.ndarray] = None) -> np.ndarray:
    if weights is None:
        return stack.mean(axis=0)
    w = np.asarray(weights, np.float64)
    w = w / w.sum()
    return (stack * w[:, None].astype(stack.dtype)).sum(axis=0)


# Below this size the numpy paths win (thread spawn isn't free); above it the
# native threaded column-sort beats numpy's full-matrix sort ~2x.
_NATIVE_CUTOFF = 1 << 16


def coordinate_median(stack: np.ndarray) -> np.ndarray:
    if stack.dtype == np.float32 and stack.size >= _NATIVE_CUTOFF:
        from distributedvolunteercomputing_tpu import native

        if native.available():
            return native.coordinate_median(np.ascontiguousarray(stack))
    return np.median(stack, axis=0).astype(stack.dtype)


def trimmed_mean(stack: np.ndarray, trim: int = 1) -> np.ndarray:
    n = stack.shape[0]
    if 2 * trim >= n:
        raise ValueError(f"trim={trim} too large for n={n}")
    if stack.dtype == np.float32 and stack.size >= _NATIVE_CUTOFF:
        from distributedvolunteercomputing_tpu import native

        if native.available():
            return native.trimmed_mean(np.ascontiguousarray(stack), trim)
    srt = np.sort(stack, axis=0)
    return srt[trim : n - trim].mean(axis=0)


def pairwise_sq_dists(stack: np.ndarray) -> np.ndarray:
    """[n, n] pairwise squared L2 distances between rows. d² is a plain sum
    over coordinates, which is what lets the streaming leader accumulate it
    tile-by-tile as contributions arrive (swarm/agg_stream.py) instead of
    paying the O(n²·D) pass at commit time."""
    return ((stack[:, None, :] - stack[None, :, :]) ** 2).sum(axis=-1)


def _krum_scores(d2: np.ndarray, n_byzantine: int) -> np.ndarray:
    """Krum score per row of a pairwise squared-distance matrix: sum of the
    m - f - 2 smallest neighbour distances (clamped to >= 1 defensively —
    at zero neighbours every score is 0.0 and selection degrades to an
    arbitrary index-order pick)."""
    m = d2.shape[0]
    d2 = d2.copy()
    np.fill_diagonal(d2, np.inf)
    n_neighbors = max(m - n_byzantine - 2, 1)
    return np.sort(d2, axis=1)[:, :n_neighbors].sum(axis=1)


def krum(
    stack: np.ndarray,
    n_byzantine: int = 1,
    multi: int = 1,
    d2: Optional[np.ndarray] = None,
) -> np.ndarray:
    """(Multi-)Krum: average the ``multi`` contributions with the smallest
    sum of squared distances to their n - f - 2 nearest neighbours.
    ``d2`` may carry a precomputed pairwise squared-distance matrix (the
    streaming leader accumulates it tile-wise during arrival)."""
    n = stack.shape[0]
    if n < n_byzantine + 3:
        # Not enough honest mass for Krum's guarantee; degrade to median.
        return coordinate_median(stack)
    if d2 is None or d2.shape != (n, n):
        d2 = pairwise_sq_dists(stack)
    scores = _krum_scores(d2, n_byzantine)
    chosen = np.argsort(scores)[:multi]
    return stack[chosen].mean(axis=0)


def geometric_median(stack: np.ndarray, iters: int = 32, eps: float = 1e-8) -> np.ndarray:
    """Weiszfeld algorithm; robust to <50% arbitrary corruption.

    Starts from the coordinate median, not the mean: a mean start under large
    outliers puts z so far out that convergence needs many more iterations.
    """
    z = coordinate_median(stack).astype(np.float64)
    for _ in range(iters):
        dist = np.linalg.norm(stack - z[None, :], axis=1)
        dist = np.maximum(dist, eps)
        w = 1.0 / dist
        z_new = (stack * w[:, None]).sum(axis=0) / w.sum()
        if np.linalg.norm(z_new - z) < eps * (1 + np.linalg.norm(z)):
            z = z_new
            break
        z = z_new
    return z.astype(stack.dtype)


def bulyan(
    stack: np.ndarray, n_byzantine: int = 1, d2: Optional[np.ndarray] = None
) -> np.ndarray:
    """Bulyan (El Mhamdi, Guerraoui, Rouault 2018): Multi-Krum repeatedly
    SELECTS the n - 2f contributions closest to their neighbour sets, then a
    per-coordinate trimmed mean (trim f) over the selected set. Needs
    n >= 4f + 3 for its guarantee; below that it degrades to the geometric
    median (the strongest estimator that stays sound at small n), matching
    krum's small-n degradation policy."""
    n = stack.shape[0]
    f = n_byzantine
    if n < 4 * f + 3:
        return geometric_median(stack)
    if d2 is not None and d2.shape != (n, n):
        d2 = None
    # Single-pass Multi-Krum selection: score once on the full set (with
    # n >= 4f + 3 the neighbour count is n - f - 2 >= 3f + 1, never
    # degenerate) and keep the n - 2f best. Iterative select-remove-rescore
    # — the other common formulation — degenerates at its late iterations
    # (m shrinks to f + 2 where the neighbour count hits zero, and the
    # 1-NN clamp then ties symmetric pairs exactly, making the selected
    # SET depend on peer row order; observed before this was changed).
    if d2 is None:
        d2 = pairwise_sq_dists(stack)
    selected = np.argsort(_krum_scores(d2, f))[: n - 2 * f]
    chosen = stack[selected]
    # Bulyan's second phase: per coordinate, keep the (n - 2f) - 2f values
    # closest to the coordinate median of the selected set and average them
    # (El Mhamdi et al.'s beta = theta - 2f).
    med = np.median(chosen, axis=0)
    order = np.argsort(np.abs(chosen - med[None, :]), axis=0)
    keep = order[: len(selected) - 2 * f]
    return np.take_along_axis(chosen, keep, axis=0).mean(axis=0).astype(stack.dtype)


def centered_clip(
    stack: np.ndarray,
    clip_tau: float = 0.0,
    iters: int = 5,
) -> np.ndarray:
    """CenteredClip (Karimireddy, He, Jaggi 2021, "Learning from History
    for Byzantine Robust Optimization"): iterate
        v <- v + mean_i( clip(x_i - v, tau) )
    where clip rescales each peer's deviation to norm <= tau. Honest
    contributions near the center pass through untouched; a byzantine row's
    pull is bounded by tau per iteration REGARDLESS of its magnitude — and
    unlike coordinate-wise trims, the bound is in L2, so a colluding
    attacker can't hide a large vector behind many small coordinates.

    ``clip_tau=0`` (the default) self-tunes per iteration to the median
    peer deviation norm — the scale-free variant: honest radii pass,
    outliers clip. Starts from the coordinate median (a robust seed rather
    than the mean, which an unbounded row could drag arbitrarily before
    the first clip)."""
    if iters < 1:
        raise ValueError(f"centered_clip iters must be >= 1, got {iters}")
    if clip_tau < 0:
        raise ValueError(f"clip_tau must be >= 0, got {clip_tau}")
    # Drop non-finite rows FIRST: an inf deviation would clip to scale 0 but
    # inf * 0 = NaN, and the unclipped mean would adopt it — a single
    # inf-filled byzantine row must cost its sender influence, not poison
    # the aggregate (the coordinate-wise estimators survive this input; the
    # L2 form must too).
    finite = np.isfinite(stack).all(axis=1)
    if not finite.all():
        if not finite.any():
            return np.zeros(stack.shape[1], stack.dtype)
        stack = stack[finite]
    v = np.median(stack, axis=0)
    for _ in range(iters):
        dev = stack - v[None, :]
        norms = np.sqrt((dev * dev).sum(axis=1))
        tau = clip_tau if clip_tau > 0 else max(float(np.median(norms)), 1e-12)
        scale = np.minimum(1.0, tau / np.maximum(norms, 1e-12))
        v = v + (dev * scale[:, None]).mean(axis=0)
    return v.astype(stack.dtype)


AGGREGATORS = {
    "mean": mean,
    "median": coordinate_median,
    "trimmed_mean": trimmed_mean,
    "krum": krum,
    "geometric_median": geometric_median,
    "bulyan": bulyan,
    "centered_clip": centered_clip,
}


def aggregate(stack: np.ndarray, method: str = "mean", **kw) -> np.ndarray:
    if method not in AGGREGATORS:
        raise KeyError(f"unknown aggregator {method!r}; known: {sorted(AGGREGATORS)}")
    if stack.ndim != 2:
        raise ValueError(f"expected [n_peers, D] stack, got shape {stack.shape}")
    return AGGREGATORS[method](stack, **kw)


# -- streaming / tiled aggregation support (swarm/agg_stream.py) ------------
#
# How each estimator decomposes over a column partition (tiles), which is
# what decides the leader's streaming mode and its memory bound:
#
# - "mean":     linear — accumulate w·x per tile, O(D) total state.
# - "window":   COORDINATE-WISE estimators (per-coordinate sort/median/trim
#               touch no other coordinate), so aggregating each [n, tile]
#               window independently is EXACTLY the dense result — only the
#               in-flight window is held, O(n·tile).
# - "d2_dense": selection needs full vectors, but the selection STATISTIC
#               (pairwise d²) is a sum over coordinates and accumulates
#               tile-by-tile; rows stay dense, the O(n²·D) distance pass
#               overlaps arrival.
# - "dense":    genuinely coupled across coordinates (Weiszfeld's per-row
#               L2 norms, centered_clip's full-vector clip radii): tiling
#               would change the estimator, so these keep the dense path.
_TILE_MODES = {
    "mean": "mean",
    "median": "window",
    "trimmed_mean": "window",
    "krum": "d2_dense",
    "bulyan": "d2_dense",
    "geometric_median": "dense",
    "centered_clip": "dense",
}


def tile_mode(method: str) -> str:
    """Streaming decomposition class for ``method`` (see table above);
    unknown methods conservatively report "dense"."""
    return _TILE_MODES.get(method, "dense")
