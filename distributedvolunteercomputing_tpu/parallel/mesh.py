"""Device mesh construction for one volunteer slice.

Axis convention (outer → inner): ``("dp", "sp", "tp")``.

``tp`` is innermost so tensor-parallel collectives (the per-layer
allreduces) land on ICI-adjacent chips; ``dp`` is outermost because its one
gradient reduction per step tolerates the longest hops. ``sp`` (sequence
parallelism for long context) sits between: its ppermute ring wants
neighbours closer than dp but is far less chatty than tp.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "sp", "tp")


def make_mesh(
    dp: int = 1,
    sp: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(dp, sp, tp)`` mesh from the first dp*sp*tp local devices."""
    if devices is None:
        devices = jax.devices()
    need = dp * sp * tp
    if len(devices) < need:
        raise ValueError(
            f"mesh dp={dp} sp={sp} tp={tp} needs {need} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[:need]).reshape(dp, sp, tp)
    return Mesh(arr, AXES)


def mesh_shape(mesh: Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
