"""Device mesh construction for one volunteer slice.

Axis convention (outer → inner): ``("dp", "sp", "pp", "ep", "tp")``.

``tp`` is innermost so tensor-parallel collectives (the per-layer
allreduces) land on ICI-adjacent chips; ``dp`` is outermost because its one
gradient reduction per step tolerates the longest hops. ``sp`` (sequence
parallelism's ppermute ring), ``pp`` (pipeline stages' ppermute chain) and
``ep`` (expert parallelism's dispatch/combine all-to-alls) sit between:
they want contiguous neighbours but are far less chatty than tp. Axes of
size 1 cost nothing — every mesh carries all five names so sharding rules
and ``shard_map`` axis references never need to special-case which
strategies are active.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "sp", "pp", "ep", "tp")


def make_mesh(
    dp: int = 1,
    sp: int = 1,
    tp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(dp, sp, pp, ep, tp)`` mesh from the first
    dp*sp*pp*ep*tp devices."""
    if devices is None:
        devices = jax.devices()
    need = dp * sp * pp * ep * tp
    if len(devices) < need:
        raise ValueError(
            f"mesh dp={dp} sp={sp} pp={pp} ep={ep} tp={tp} needs {need} "
            f"devices, have {len(devices)}"
        )
    arr = np.asarray(devices[:need]).reshape(dp, sp, pp, ep, tp)
    return Mesh(arr, AXES)


def mesh_shape(mesh: Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
