"""Device mesh construction for one volunteer slice.

Axis convention (outer → inner): ``("dp", "sp", "pp", "ep", "tp")``.

``tp`` is innermost so tensor-parallel collectives (the per-layer
allreduces) land on ICI-adjacent chips; ``dp`` is outermost because its one
gradient reduction per step tolerates the longest hops. ``sp`` (sequence
parallelism's ppermute ring), ``pp`` (pipeline stages' ppermute chain) and
``ep`` (expert parallelism's dispatch/combine all-to-alls) sit between:
they want contiguous neighbours but are far less chatty than tp. Axes of
size 1 cost nothing — every mesh carries all five names so sharding rules
and ``shard_map`` axis references never need to special-case which
strategies are active.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "sp", "pp", "ep", "tp")


def make_mesh(
    dp: int = 1,
    sp: int = 1,
    tp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(dp, sp, pp, ep, tp)`` mesh from the first
    dp*sp*pp*ep*tp devices."""
    if devices is None:
        devices = jax.devices()
    need = dp * sp * pp * ep * tp
    if len(devices) < need:
        raise ValueError(
            f"mesh dp={dp} sp={sp} pp={pp} ep={ep} tp={tp} needs {need} "
            f"devices, have {len(devices)}"
        )
    arr = np.asarray(devices[:need]).reshape(dp, sp, pp, ep, tp)
    return Mesh(arr, AXES)


def shard_map_manual(fn, mesh: Mesh, in_specs, out_specs, axis: str):
    """``shard_map`` manual over ONE axis, automatic (GSPMD) over the
    rest — spanning the jax API split the same way the mesh codec's shim
    does (ops/mesh_codec.py): ``jax.shard_map(axis_names={axis},
    check_vma=False)`` on new jax, ``jax.experimental.shard_map`` with
    the complementary ``auto`` frozenset (and ``check_rep=False``) on
    the tier-1 jax, where ``jax.shard_map``/``axis_names`` don't exist."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            axis_names={axis}, check_vma=False,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False, auto=frozenset(mesh.axis_names) - {axis},
    )


def mesh_shape(mesh: Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def parse_mesh_spec(spec: str) -> dict:
    """Parse a ``"dp=2,tp=2"``-style CLI mesh spec into make_mesh kwargs,
    with errors that name the expected format (a bare int() traceback from
    deep inside volunteer startup helps nobody)."""
    axes: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue  # tolerate a trailing comma
        k, eq, v = part.partition("=")
        k = k.strip()
        if not eq or k not in AXES or not v.strip().isdigit() or int(v) < 1:
            raise ValueError(
                f"bad mesh spec {spec!r}: expected comma-separated axis=N "
                f"with axes from {AXES} and N >= 1 (e.g. 'dp=2,tp=2'); "
                f"offending part: {part!r}"
            )
        axes[k] = int(v)
    if not axes:
        raise ValueError(f"empty mesh spec {spec!r}: expected e.g. 'dp=2,tp=2'")
    return axes
