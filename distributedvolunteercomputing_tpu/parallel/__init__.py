"""Intra-slice parallelism: mesh construction, sharding rules, pjit steps.

This is the TPU-native replacement for the reference's NCCL intra-node layer
(BASELINE.json:5): collectives here are XLA-compiler-emitted over ICI, not
hand-called NCCL ops. The swarm/ package handles the WAN (DCN) tier between
volunteer slices; this package handles everything inside one slice:

- ``mesh``       — device mesh construction ((dp, sp, pp, ep, tp) axes)
- ``sharding``   — parameter partition rules (Megatron-style TP, stacked
                   layers over pp, expert stacks over ep) and batch specs
- ``train_step`` — the sharded train step: fwd/bwd/update in ONE compiled
                   computation, gradient reduction over dp emitted by XLA
- ``ring_attention`` — sequence-parallel exact attention over the sp axis
                   (ppermute ring; long-context path)
- ``pipeline``   — GPipe microbatch pipeline over the pp axis inside one
                   shard_map (each stage holds its own layers)
"""

from distributedvolunteercomputing_tpu.parallel.mesh import make_mesh
from distributedvolunteercomputing_tpu.parallel.pipeline import pipeline_trunk
from distributedvolunteercomputing_tpu.parallel.sharding import (
    batch_sharding,
    make_fsdp_param_shardings,
    make_param_shardings,
    make_zero1_opt_shardings,
    partition_spec_for_path,
)
from distributedvolunteercomputing_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_bhtd,
)
from distributedvolunteercomputing_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_attention_bhtd,
)
from distributedvolunteercomputing_tpu.parallel.train_step import (
    make_sharded_train_step,
    shard_train_state,
)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "make_fsdp_param_shardings",
    "make_param_shardings",
    "make_zero1_opt_shardings",
    "partition_spec_for_path",
    "make_sharded_train_step",
    "shard_train_state",
    "ring_attention",
    "ring_attention_bhtd",
    "ulysses_attention",
    "ulysses_attention_bhtd",
    "pipeline_trunk",
]
