"""The multi-chip train step: one compiled computation per slice.

Reference parity: the per-worker CUDA ``train_step`` plus NCCL intra-node
allreduce (BASELINE.json:5) collapse here into a SINGLE ``jax.jit``
computation over the slice mesh — fwd, bwd, the dp gradient reduction, and
the optimizer update are all emitted by XLA with ICI collectives placed by
GSPMD. No hand-written collective calls; the sharding annotations
(parallel/sharding.py) are the entire parallelism specification.

Host code only touches the result every K steps when the WAN averager
(swarm/averager.py) ships the slice's params to other volunteers.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributedvolunteercomputing_tpu.parallel.sharding import (
    batch_sharding,
    make_param_shardings,
)
from distributedvolunteercomputing_tpu.training.steps import (
    Batch,
    Metrics,
    TrainState,
    train_step_body,
)


def _shard_opt_state_like_params(
    opt_state: Any, param_shardings: Any, params_treedef: Any, replicated: Any
) -> Any:
    """Place optimizer state on the mesh, preserving its VALUES.

    Optax states (e.g. Adam's mu/nu) embed whole params-shaped pytrees;
    any subtree whose treedef equals the params' gets the params' per-leaf
    shardings, everything else (step counts, scalars) is replicated. This
    keeps a warm/restored optimizer state intact — re-initialising via
    tx.init would silently zero the moments on resume.
    """

    def rec(node):
        if jax.tree_util.tree_structure(node) == params_treedef:
            return jax.tree_util.tree_map(jax.device_put, node, param_shardings)
        if isinstance(node, tuple):  # optax states are (named)tuples
            out = [rec(c) for c in node]
            return type(node)(*out) if hasattr(node, "_fields") else tuple(out)
        if isinstance(node, list):
            return [rec(c) for c in node]
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if node is None:
            return None
        return jax.device_put(node, replicated)

    return rec(opt_state)


def shard_train_state(
    state: TrainState, mesh: Mesh, tx: Any = None
) -> Tuple[TrainState, Any]:
    """Place a host/single-device TrainState onto the mesh.

    Params get their rule-derived shardings; the optimizer state keeps its
    values (warm moments survive a resume) with params-shaped subtrees
    sharded exactly like their params. ``tx`` is unused and kept for
    call-site compatibility. Returns (sharded_state, param_shardings).
    """
    param_shardings = make_param_shardings(mesh, state.params)
    params_treedef = jax.tree_util.tree_structure(state.params)
    replicated = NamedSharding(mesh, P())
    return (
        TrainState(
            params=jax.device_put(state.params, param_shardings),
            opt_state=_shard_opt_state_like_params(
                state.opt_state, param_shardings, params_treedef, replicated
            ),
            step=jax.device_put(state.step, replicated),
            rng=jax.device_put(state.rng, replicated),
        ),
        param_shardings,
    )


def make_sharded_train_step(
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]],
    tx: Any,
    mesh: Mesh,
    donate: bool = True,
    seq_sharded_batch: bool = False,
    accum_steps: int = 1,
) -> Callable[[TrainState, Batch], Tuple[TrainState, Metrics]]:
    """Build the jitted sharded ``(state, batch) -> (state, metrics)`` step.

    The batch must be device_put with ``batch_sharding(mesh, ...)`` (leading
    dim over dp); state via ``shard_train_state``. Gradient reduction across
    dp is NOT explicit: params are replicated over dp, so XLA emits the psum
    during backward — the TPU equivalent of the reference's NCCL allreduce.

    With ``seq_sharded_batch`` and an ``sp`` mesh axis of size > 1, the step
    body is traced under the sequence-parallel context, so every attention in
    the model routes to ring attention (parallel/ring_attention.py) over sp.
    """
    bspec = batch_sharding(mesh, seq_axis=seq_sharded_batch)
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    use_ring = seq_sharded_batch and axis_sizes.get("sp", 1) > 1

    def step(state: TrainState, batch: Batch) -> Tuple[TrainState, Metrics]:
        batch = jax.lax.with_sharding_constraint(batch, bspec)
        if use_ring:
            # Context is consulted at trace time — this body IS the trace.
            from distributedvolunteercomputing_tpu.ops.attention import sequence_parallel

            with sequence_parallel(mesh, "sp"):
                return train_step_body(loss_fn, tx, state, batch, accum_steps)
        return train_step_body(loss_fn, tx, state, batch, accum_steps)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def put_batch(batch: Batch, mesh: Mesh, seq_sharded: bool = False) -> Batch:
    return jax.device_put(batch, batch_sharding(mesh, seq_axis=seq_sharded))
