"""The multi-chip train step: one compiled computation per slice.

Reference parity: the per-worker CUDA ``train_step`` plus NCCL intra-node
allreduce (BASELINE.json:5) collapse here into a SINGLE ``jax.jit``
computation over the slice mesh — fwd, bwd, the dp gradient reduction, and
the optimizer update are all emitted by XLA with ICI collectives placed by
GSPMD. No hand-written collective calls; the sharding annotations
(parallel/sharding.py) are the entire parallelism specification.

Host code only touches the result every K steps when the WAN averager
(swarm/averager.py) ships the slice's params to other volunteers.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributedvolunteercomputing_tpu.parallel.sharding import (
    batch_sharding,
    make_param_shardings,
)
from distributedvolunteercomputing_tpu.training.steps import (
    Batch,
    Metrics,
    TrainState,
    train_step_body,
)


def shard_train_state(
    state: TrainState, mesh: Mesh, tx: Any
) -> Tuple[TrainState, Any]:
    """Place a host/single-device TrainState onto the mesh.

    Params get their rule-derived shardings; the optimizer state is rebuilt
    *inside* jit from the sharded params so GSPMD propagates each param's
    sharding onto its Adam moments (no per-optimizer spec table needed).
    Returns (sharded_state, param_shardings).
    """
    param_shardings = make_param_shardings(mesh, state.params)
    params = jax.device_put(state.params, param_shardings)
    replicated = NamedSharding(mesh, P())
    rng = jax.device_put(state.rng, replicated)
    step = jax.device_put(state.step, replicated)

    @jax.jit
    def rebuild(p, rng, step):
        st = TrainState.create(p, tx, rng)
        return TrainState(params=st.params, opt_state=st.opt_state, step=step, rng=rng)

    return rebuild(params, rng, step), param_shardings


def make_sharded_train_step(
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]],
    tx: Any,
    mesh: Mesh,
    donate: bool = True,
    seq_sharded_batch: bool = False,
) -> Callable[[TrainState, Batch], Tuple[TrainState, Metrics]]:
    """Build the jitted sharded ``(state, batch) -> (state, metrics)`` step.

    The batch must be device_put with ``batch_sharding(mesh, ...)`` (leading
    dim over dp); state via ``shard_train_state``. Gradient reduction across
    dp is NOT explicit: params are replicated over dp, so XLA emits the psum
    during backward — the TPU equivalent of the reference's NCCL allreduce.
    """
    bspec = batch_sharding(mesh, seq_axis=seq_sharded_batch)

    def step(state: TrainState, batch: Batch) -> Tuple[TrainState, Metrics]:
        batch = jax.lax.with_sharding_constraint(batch, bspec)
        return train_step_body(loss_fn, tx, state, batch)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def put_batch(batch: Batch, mesh: Mesh, seq_sharded: bool = False) -> Batch:
    return jax.device_put(batch, batch_sharding(mesh, seq_axis=seq_sharded))
