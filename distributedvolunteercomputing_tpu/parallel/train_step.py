"""The multi-chip train step: one compiled computation per slice.

Reference parity: the per-worker CUDA ``train_step`` plus NCCL intra-node
allreduce (BASELINE.json:5) collapse here into a SINGLE ``jax.jit``
computation over the slice mesh — fwd, bwd, the dp gradient reduction, and
the optimizer update are all emitted by XLA with ICI collectives placed by
GSPMD. No hand-written collective calls; the sharding annotations
(parallel/sharding.py) are the entire parallelism specification.

Host code only touches the result every K steps when the WAN averager
(swarm/averager.py) ships the slice's params to other volunteers.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributedvolunteercomputing_tpu.parallel.sharding import (
    batch_sharding,
    make_fsdp_param_shardings,
    make_param_shardings,
    make_zero1_opt_shardings,
)
from distributedvolunteercomputing_tpu.training.steps import (
    Batch,
    Metrics,
    TrainState,
    train_step_body,
)


def _map_params_shaped_subtrees(
    opt_state: Any,
    params_treedef: Any,
    subtree_fn: Callable[[Any], Any],
    other_fn: Callable[[Any], Any],
) -> Any:
    """Structural walk over an optax state: apply ``subtree_fn`` to every
    subtree whose treedef equals the params' (Adam's mu/nu and friends),
    ``other_fn`` to every other leaf (step counts, scalars). The single walker
    shared by mesh placement and the ZeRO-1 in-step constraint, so the two
    can't diverge on optax state shapes."""

    def rec(node):
        if jax.tree_util.tree_structure(node) == params_treedef:
            return subtree_fn(node)
        if isinstance(node, tuple):  # optax states are (named)tuples
            out = [rec(c) for c in node]
            return type(node)(*out) if hasattr(node, "_fields") else tuple(out)
        if isinstance(node, list):
            return [rec(c) for c in node]
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if node is None:
            return None
        return other_fn(node)

    return rec(opt_state)


def _shard_opt_state_like_params(
    opt_state: Any, param_shardings: Any, params_treedef: Any, replicated: Any
) -> Any:
    """Place optimizer state on the mesh, preserving its VALUES.

    Params-shaped subtrees get the given per-leaf shardings, everything else
    is replicated. This keeps a warm/restored optimizer state intact —
    re-initialising via tx.init would silently zero the moments on resume.
    """
    return _map_params_shaped_subtrees(
        opt_state,
        params_treedef,
        lambda node: jax.tree_util.tree_map(jax.device_put, node, param_shardings),
        lambda leaf: jax.device_put(leaf, replicated),
    )


def shard_train_state(
    state: TrainState, mesh: Mesh, tx: Any = None, zero1: bool = False,
    fsdp: bool = False,
) -> Tuple[TrainState, Any]:
    """Place a host/single-device TrainState onto the mesh.

    Params get their rule-derived shardings; the optimizer state keeps its
    values (warm moments survive a resume) with params-shaped subtrees
    sharded exactly like their params — or, with ``zero1``, additionally
    sharded over dp (ZeRO-1; see make_zero1_opt_shardings). With ``fsdp``
    the params THEMSELVES are dp-sharded too (ZeRO-3: weights, grads and
    optimizer state all at 1/dp per chip; make_fsdp_param_shardings).
    ``tx`` is unused and kept for call-site compatibility. Returns
    (sharded_state, param_shardings).
    """
    param_shardings = (
        make_fsdp_param_shardings(mesh, state.params)
        if fsdp
        else make_param_shardings(mesh, state.params)
    )
    opt_shardings = (
        make_zero1_opt_shardings(mesh, state.params)
        if (zero1 or fsdp)
        else param_shardings
    )
    params_treedef = jax.tree_util.tree_structure(state.params)
    replicated = NamedSharding(mesh, P())
    return (
        TrainState(
            params=jax.device_put(state.params, param_shardings),
            opt_state=_shard_opt_state_like_params(
                state.opt_state, opt_shardings, params_treedef, replicated
            ),
            step=jax.device_put(state.step, replicated),
            rng=jax.device_put(state.rng, replicated),
        ),
        param_shardings,
    )


def make_sharded_train_step(
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]],
    tx: Any,
    mesh: Mesh,
    donate: bool = True,
    seq_sharded_batch: bool = False,
    accum_steps: int = 1,
    zero1: bool = False,
    fsdp: bool = False,
    sp_impl: str = "ring",
) -> Callable[[TrainState, Batch], Tuple[TrainState, Metrics]]:
    """Build the jitted sharded ``(state, batch) -> (state, metrics)`` step.

    The batch must be device_put with ``batch_sharding(mesh, ...)`` (leading
    dim over dp); state via ``shard_train_state``. Gradient reduction across
    dp is NOT explicit: in the default (non-fsdp) mode params are replicated
    over dp, so XLA emits the psum during backward — the TPU equivalent of
    the reference's NCCL allreduce. Under ``fsdp`` params are dp-SHARDED and
    that reduction becomes a reduce-scatter back to the shards.

    With ``seq_sharded_batch`` and an ``sp`` mesh axis of size > 1, the step
    body is traced under the sequence-parallel context, so every attention in
    the model routes to the chosen SP implementation over sp: ``sp_impl`` =
    "ring" (parallel/ring_attention.py, any head count) or "ulysses"
    (parallel/ulysses.py, all-to-all seq<->heads; needs n_heads % sp == 0).

    With ``zero1`` (state sharded via ``shard_train_state(..., zero1=True)``),
    the updated optimizer moments are constrained back to their dp-sharded
    specs every step, so GSPMD keeps them distributed instead of quietly
    re-replicating — per-chip optimizer memory stays at 1/dp. With ``fsdp``
    the updated PARAMS are constrained to their dp shards as well (ZeRO-3).
    """
    bspec = batch_sharding(mesh, seq_axis=seq_sharded_batch)
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    use_ring = seq_sharded_batch and axis_sizes.get("sp", 1) > 1
    constrain_opt = _make_constrain_opt(mesh, zero1, fsdp)

    def step(state: TrainState, batch: Batch) -> Tuple[TrainState, Metrics]:
        batch = jax.lax.with_sharding_constraint(batch, bspec)
        if use_ring:
            # Context is consulted at trace time — this body IS the trace.
            from distributedvolunteercomputing_tpu.ops.attention import sequence_parallel

            with sequence_parallel(mesh, "sp", impl=sp_impl):
                new_state, metrics = train_step_body(loss_fn, tx, state, batch, accum_steps)
        else:
            new_state, metrics = train_step_body(loss_fn, tx, state, batch, accum_steps)
        return constrain_opt(new_state), metrics

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def _make_constrain_opt(mesh: Mesh, zero1: bool, fsdp: bool):
    """In-step re-constraint of distributed optimizer/param shards (ZeRO-1 /
    ZeRO-3): after tx.update, GSPMD would quietly re-replicate the updated
    moments without this. Shared by the single-step and scanned builders so
    their layouts can't diverge."""

    def constrain_opt(state: TrainState) -> TrainState:
        if not (zero1 or fsdp):
            return state
        opt_shardings = make_zero1_opt_shardings(mesh, state.params)
        constrained = _map_params_shaped_subtrees(
            state.opt_state,
            jax.tree_util.tree_structure(state.params),
            lambda node: jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, node, opt_shardings
            ),
            lambda leaf: leaf,
        )
        params = state.params
        if fsdp:
            params = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint,
                params,
                make_fsdp_param_shardings(mesh, params),
            )
        return TrainState(
            params=params,
            opt_state=constrained,
            step=state.step,
            rng=state.rng,
        )

    return constrain_opt


def make_sharded_multi_step(
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]],
    tx: Any,
    mesh: Mesh,
    donate: bool = True,
    seq_sharded_batch: bool = False,
    accum_steps: int = 1,
    zero1: bool = False,
    fsdp: bool = False,
    sp_impl: str = "ring",
) -> Callable[[TrainState, Batch], Tuple[TrainState, jax.Array]]:
    """N sharded train steps in ONE compiled call: ``(state,
    stacked_batches) -> (state, per_step_losses)``.

    The mesh twin of training/steps.make_multi_step (r4 VERDICT missing
    #5: the dispatch-amortization win was unavailable exactly where a
    volunteer owns a multi-chip slice — the product's own combination).
    ``lax.scan`` over the SAME traced body as make_sharded_train_step,
    including the per-step batch sharding constraint and the ZeRO in-step
    re-constraints, so layouts are identical by construction; on a
    tunneled runtime it also collapses N HTTP dispatch round-trips into
    one. The leading axis of every batch leaf is the step index."""
    bspec = batch_sharding(mesh, seq_axis=seq_sharded_batch)
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    use_ring = seq_sharded_batch and axis_sizes.get("sp", 1) > 1
    constrain_opt = _make_constrain_opt(mesh, zero1, fsdp)

    def multi(state: TrainState, batches: Batch) -> Tuple[TrainState, jax.Array]:
        def body(s: TrainState, b: Batch):
            b = jax.lax.with_sharding_constraint(b, bspec)
            s2, metrics = train_step_body(loss_fn, tx, s, b, accum_steps)
            return constrain_opt(s2), metrics["loss"]

        if use_ring:
            from distributedvolunteercomputing_tpu.ops.attention import sequence_parallel

            with sequence_parallel(mesh, "sp", impl=sp_impl):
                return jax.lax.scan(body, state, batches)
        return jax.lax.scan(body, state, batches)

    # donate=False matters for callers that keep the input state alive
    # (A/B harnesses, retry paths): on the CPU backend a replicated leaf's
    # device_put can ALIAS its source, so donation would delete the
    # caller's tree too (same flag as make_sharded_train_step).
    return jax.jit(multi, donate_argnums=(0,) if donate else ())


def put_batch(batch: Batch, mesh: Mesh, seq_sharded: bool = False) -> Batch:
    return jax.device_put(batch, batch_sharding(mesh, seq_axis=seq_sharded))
