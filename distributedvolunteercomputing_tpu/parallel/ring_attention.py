"""Ring attention: sequence-parallel exact attention over the ``sp`` mesh axis.

Long-context path: the sequence dim is sharded across devices; K/V chunks
rotate around the ring via ``jax.lax.ppermute`` (one ICI hop per step) while
each device accumulates attention for its local queries with the same
online-softmax merge the flash kernel uses. Attention stays EXACT — after
``sp`` steps every q block has seen every k/v block — but no device ever
holds more than its 1/sp slice of K/V or an O(T_local^2) score block.

All ops are differentiable JAX primitives (ppermute has a transpose rule),
so the backward pass needs no custom VJP; each ring step is wrapped in
``jax.checkpoint`` so the O(Tl x Tl) probabilities are recomputed rather
than stored for every step.

Causal masking is by GLOBAL position (chunk origin x chunk length + local
offset), so a causally-masked ring computes exactly what single-device
causal attention computes on the gathered sequence. Chunks entirely in the
masked future still rotate through (their contribution is zeroed) — the
load-balanced "striped" layout is a later optimisation.

Reference note: the reference genre is volunteer data-parallel only
(SURVEY.md §2 — no sequence parallelism evidenced); this module is the
build-side long-context extension, TPU-native by construction (ICI
collectives emitted by XLA from ppermute under shard_map).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributedvolunteercomputing_tpu.parallel.mesh import shard_map_manual

NEG_INF = -1e30


def _ring_step(q, kc, vc, m, l, acc, src, my, tl, causal, scale):
    """Merge one K/V chunk (originally from ring position ``src``) into the
    running (m, l, acc) online-softmax state for local queries.

    Matmuls run in the input dtype (bf16 on the MXU) with f32 accumulation
    via preferred_element_type; only the softmax statistics live in f32 —
    the same recipe as the XLA core and the flash kernel."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, kc, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        # Global positions: rows = my*tl + i, cols = src*tl + j.
        row = my * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 0)
        col = src * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 1)
        s = jnp.where(col <= row, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc * corr + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(vc.dtype), vc, preferred_element_type=jnp.float32
    )
    return m_new, l, acc


def ring_attention(
    q: jax.Array,  # [B, H, Tl, D] — the local sequence shard
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Exact attention over the ring; call INSIDE shard_map over ``axis_name``."""
    # psum(1, axis) is the axis size on BOTH sides of the jax API split
    # (jax.lax.axis_size does not exist on the tier-1 jax).
    size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, tl, d = q.shape
    scale = 1.0 / (d ** 0.5)

    m = jnp.full((b, h, tl, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tl, 1), jnp.float32)
    acc = jnp.zeros((b, h, tl, d), jnp.float32)

    step_fn = jax.checkpoint(
        functools.partial(_ring_step, tl=tl, causal=causal, scale=scale),
        static_argnums=(),
    )

    kc, vc = k, v
    perm = [(i, (i - 1) % size) for i in range(size)]
    for step in range(size):
        src = jax.lax.rem(my + step, size)
        m, l, acc = step_fn(q, kc, vc, m, l, acc, src, my)
        if step != size - 1:
            # Shift chunks one hop left: device i receives chunk held by i+1,
            # so after t steps device i holds the chunk born on (i+t) % size.
            kc = jax.lax.ppermute(kc, axis_name, perm)
            vc = jax.lax.ppermute(vc, axis_name, perm)

    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe).astype(q.dtype)


def sp_shard_map(inner, mesh: Mesh, axis: str):
    """The one shard_map wrapper every SP implementation uses: [B, H, T, D]
    with T sharded over ``axis``, manual over ``axis`` only, every other
    mesh axis automatic (GSPMD). Shared by ring and ulysses so the two
    impls can't diverge on the wrapping."""
    spec = P(None, None, axis, None)
    return shard_map_manual(inner, mesh, (spec, spec, spec), spec, axis)


def ring_attention_bhtd(
    q: jax.Array,  # [B, H, T, D] global; T sharded over ``axis``
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
) -> jax.Array:
    """shard_map'd ring attention on head-split arrays."""
    inner = sp_shard_map(
        functools.partial(ring_attention, axis_name=axis, causal=causal), mesh, axis
    )
    return inner(q, k, v)
