"""Parameter partition rules: Megatron-style tensor parallelism by path.

The model zoo stores params as plain nested dicts/lists (models/*.py), so
partition specs are assigned by matching the pytree *path* against a small
generic rule table that covers every transformer in the zoo:

- column-parallel (shard the OUTPUT feature dim over ``tp``): qkv / wq / wk /
  wv projections, mlp_in / w_gate / w_up — the matmul that *fans out*;
- row-parallel (shard the INPUT feature dim over ``tp``): attn_out / wo /
  mlp_out / w_down — the matmul that *fans in*, after which XLA emits the
  layer's one allreduce over ICI;
- everything else (embeddings, norms, biases of row-parallel layers, LoRA
  adapters — rank ~8, not worth slicing) is replicated.

This is the build-side TP addition documented in SURVEY.md §2 (reference is
volunteer-DP only; TP within a slice is what `pjit` gives us for free).

A rule only applies when the sharded dim is divisible by the mesh axis size;
otherwise that dim silently falls back to replicated (e.g. GPT-2's vocab
50257 is prime — the tied embedding stays replicated on any mesh).
"""

from __future__ import annotations

import re
from typing import Any, List, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec). First match wins; paths look like "blocks/qkv/w"
# (stacked scan-over-layers layout: leaves carry a leading n_layers axis).
# Column-parallel weights are [L, d_in, d_out] → sharded on d_out; their
# biases [L, d_out] → sharded on d_out. Row-parallel weights are sharded on
# d_in; their biases are full-size → replicated. Specs below are written for
# the TRAILING dims and right-aligned by _fit_spec, so the same rule covers a
# stacked leaf and an unstacked one (e.g. lm_head, which has no layer axis).
_RULES: List[Tuple[str, P]] = [
    (r".*/(qkv|mlp_in)/w$", P(None, "tp")),
    (r".*/(qkv|mlp_in)/b$", P("tp")),
    (r".*/(attn_out|mlp_out)/w$", P("tp", None)),
    (r".*/(wq|wk|wv|w_gate|w_up)$", P(None, "tp")),
    (r".*/(wo|w_down)$", P("tp", None)),
    (r".*/lm_head$", P(None, "tp")),
    # MoE expert stacks [E, d, f] / [E, f, d]: experts over ep, per-expert
    # hidden dim over tp (column- then row-parallel, as for the dense FFN).
    (r".*/moe_in$", P("ep", None, "tp")),
    (r".*/moe_out$", P("ep", "tp", None)),
]


def _path_str(path: Tuple[Any, ...]) -> str:
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return "/".join(parts)


def _fit_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """RIGHT-align the spec to the leaf's rank (leading dims replicated) and
    drop axes that don't divide. Right-alignment is what makes one rule serve
    both stacked [L, d_in, d_out] block weights and unstacked [d_in, d_out]
    ones: the feature dims are always the trailing dims."""
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    pad = len(shape) - len(spec)
    out = []
    for dim in range(len(shape)):
        axis = spec[dim - pad] if dim >= pad else None
        if axis is not None and shape[dim] % axis_sizes.get(axis, 1) != 0:
            axis = None
        out.append(axis)
    return P(*out)


def partition_spec_for_path(path_str: str, shape: Tuple[int, ...], mesh: Mesh) -> P:
    spec = P()
    for pattern, rule_spec in _RULES:
        if re.match(pattern, "/" + path_str):
            spec = _fit_spec(rule_spec, shape, mesh)  # full rank after fit
            break
    # Pipeline parallelism: every per-layer leaf under a STACKED "blocks"
    # subtree carries the layer axis first; with a pp axis active that axis
    # is sharded over pp, so each stage holds only its own layers' params
    # (parallel/pipeline.py consumes them under shard_map). Composes with
    # the tp rules (e.g. [L, d_in, d_out] -> ("pp", None, "tp")). Unstacked
    # legacy paths ("blocks/3/qkv/w") are left alone.
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    pp = axis_sizes.get("pp", 1)
    if (
        pp > 1
        and "blocks/" in path_str
        and re.search(r"blocks/\d+(/|$)", path_str) is None
        and shape
        and shape[0] % pp == 0
    ):
        padded = list(spec) if len(spec) == len(shape) else [None] * len(shape)
        if padded[0] is None:
            padded[0] = "pp"
            spec = P(*padded)
    return spec


def make_param_shardings(mesh: Mesh, params: Any) -> Any:
    """Pytree of NamedSharding matching ``params``, rules applied by path."""

    def assign(path, leaf):
        spec = partition_spec_for_path(_path_str(path), leaf.shape, mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(assign, params)


def _dp_sharded_specs(mesh: Mesh, params: Any) -> Any:
    """Each leaf's rule spec plus ``dp`` on the first still-replicated dim the
    dp axis divides (leaves with no such dim keep their rule spec). The shared
    placement rule behind ZeRO-1 (optimizer moments) and FSDP (params)."""
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    dp = axis_sizes.get("dp", 1)

    def assign(path, leaf):
        spec = partition_spec_for_path(_path_str(path), leaf.shape, mesh)
        padded = list(spec) + [None] * (len(leaf.shape) - len(spec))
        if dp > 1:
            for dim in range(len(leaf.shape)):
                if padded[dim] is None and leaf.shape[dim] % dp == 0:
                    padded[dim] = "dp"
                    break
        while padded and padded[-1] is None:  # P(None) and P() compare unequal
            padded.pop()
        return NamedSharding(mesh, P(*padded))

    return jax.tree_util.tree_map_with_path(assign, params)


def make_zero1_opt_shardings(mesh: Mesh, params: Any) -> Any:
    """ZeRO-1 shardings for params-shaped optimizer moments.

    Rationale: params stay replicated over dp (grads psum in backward — the
    genre's data-parallel contract), but Adam's mu/nu never enter a matmul,
    so nothing forces them replicated; sharding them over dp cuts optimizer
    memory per chip by the dp factor (AdamW: from 2x params to 2x/dp). GSPMD
    then emits reduce-scatter(grads) + all-gather(updated params) around the
    elementwise update — the ZeRO-1 communication pattern — from annotations
    alone. Composes with tp/pp rules: a [L, d_in, d_out] qkv leaf on a
    dp2/pp2/tp2 mesh ends up P("pp", "dp", "tp")."""
    return _dp_sharded_specs(mesh, params)


def make_fsdp_param_shardings(mesh: Mesh, params: Any) -> Any:
    """FSDP (ZeRO-3) shardings: the PARAMS themselves sharded over dp (same
    first-free-dim rule), so weights + grads + optimizer state all live at
    1/dp per chip — the regime where Llama-7B-scale models fit a slice.

    GSPMD inserts the FSDP communication pattern from these annotations: an
    all-gather materializes each weight just before its matmul (fwd and bwd),
    and the gradient reduction becomes a reduce-scatter back to the shards.
    The train step re-constrains updated params each step
    (make_sharded_train_step(fsdp=True)) so the sharding persists. Trades
    per-step all-gather bandwidth (ICI-resident on a TPU slice) for dp-fold
    memory — the standard TPU fully-sharded recipe."""
    return _dp_sharded_specs(mesh, params)


def batch_sharding(mesh: Mesh, seq_axis: bool = False) -> Any:
    """Sharding for a batch dict: leading dim over dp, optionally dim 1 over sp.

    Every leaf of the zoo's batches is [B, ...] (images, tokens, targets,
    masks), so one spec fits all leaves; token-model leaves are [B, T] and
    long-context runs additionally split T over ``sp``.
    """
    spec = P("dp", "sp") if seq_axis else P("dp")
    return NamedSharding(mesh, spec)
