"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

Build-side extension beyond reference parity (the reference genre is
volunteer-DP only, SURVEY.md §2 "Parallelism strategies") — but the
TPU-native way to fit models whose LAYERS don't fit one chip: the stacked
block pytree (models store blocks as one [L, ...] stack, models/common.py
``stacked_init``) is sharded over ``pp`` on its layer axis by the partition
rules (parallel/sharding.py), so each pipeline stage physically holds only
L/P layers' weights, and the trunk runs a microbatch pipeline inside one
``shard_map``:

- tick t: stage s applies its layers to microbatch (t - s); activations hop
  stage s -> s+1 over ICI via ``lax.ppermute`` (the same neighbour-chain
  pattern as ring attention, parallel/ring_attention.py);
- M microbatches drain in M + P - 1 ticks (bubble fraction (P-1)/(M+P-1));
- the backward pipeline needs no scheduling code: autodiff of the tick scan
  reverses the schedule, and ppermute's transpose is the inverted permute.

Everything outside the trunk (embeddings, final LN, vocab head/loss) stays
plain GSPMD — replicated over pp, sharded over dp/tp by the usual rules.
Composes with dp (batch dim sharded over dp outside AND inside the
shard_map) and with tp (the per-layer matmul rules still shard the feature
dims; XLA places those collectives within each stage).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributedvolunteercomputing_tpu.parallel.mesh import shard_map_manual


def pipeline_trunk(
    block_fn: Callable[[Any, jax.Array], jax.Array],
    blocks: Any,
    x: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
    microbatches: Optional[int] = None,
    remat: bool = True,
) -> jax.Array:
    """Run ``x`` [B, T, D] through pp-sharded stacked ``blocks``.

    ``blocks`` leaves are [L, ...] sharded over ``axis`` on dim 0 (each
    device holds its stage's L/P layers). ``x``'s batch dim is split into
    ``microbatches`` (default P) equal microbatches; B % M == 0 required.
    Returns [B, T, D], replicated over pp (sharding of other axes is
    whatever GSPMD picks outside).
    """
    from distributedvolunteercomputing_tpu.models.common import scan_blocks

    pp = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    if pp == 1:
        return scan_blocks(block_fn, blocks, x, remat=remat)

    b = x.shape[0]
    m = microbatches or pp
    if b % m != 0:
        raise ValueError(f"batch {b} must divide into {m} microbatches")
    n_layers = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    if n_layers % pp != 0:
        # Fail HERE with the actual precondition, not deep inside shard_map
        # tracing; note the partition rules also decline to shard this case.
        raise ValueError(
            f"pipeline needs n_layers ({n_layers}) divisible by pp ({pp})"
        )
    mbs = x.reshape(m, b // m, *x.shape[1:])

    # Manual over pp ONLY (jax.shard_map axis_names): dp/tp stay automatic,
    # so the batch keeps its dp sharding and the block weights keep their tp
    # feature sharding inside each stage — XLA places those collectives as
    # usual; this code only schedules the pp hops.
    blocks_spec = jax.tree_util.tree_map(lambda _: P(axis), blocks)

    def run(stage_blocks, mbs):
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        n_ticks = m + pp - 1

        def stage_apply(h):
            return scan_blocks(block_fn, stage_blocks, h, remat=remat)

        def tick(carry, t):
            state, outputs = carry
            # Stage 0 ingests microbatch t (clamped once the feed runs dry —
            # those ticks compute garbage that the output mask never keeps);
            # later stages take the activation handed over by ppermute.
            feed = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            inp = jnp.where(idx == 0, feed, state)
            out = stage_apply(inp)
            # The LAST stage finished microbatch (t - P + 1) this tick.
            mb_done = t - (pp - 1)
            slot = jnp.clip(mb_done, 0, m - 1)
            keep = ((idx == pp - 1) & (mb_done >= 0)).astype(out.dtype)
            cur = jax.lax.dynamic_index_in_dim(outputs, slot, 0, keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, cur * (1 - keep) + out * keep, slot, 0
            )
            state = jax.lax.ppermute(out, axis, perm)
            return (state, outputs), None

        state0 = jnp.zeros_like(mbs[0])
        outputs0 = jnp.zeros_like(mbs)
        (_, outputs), _ = jax.lax.scan(
            tick, (state0, outputs0), jnp.arange(n_ticks)
        )
        # Only the last stage holds real outputs (zeros elsewhere): one psum
        # over pp replicates them to every stage.
        return jax.lax.psum(outputs, axis)

    out = shard_map_manual(
        run, mesh, (blocks_spec, P()), P(), axis
    )(blocks, mbs)
    return out.reshape(b, *x.shape[1:])


def make_pp_loss_fn_gpt2(cfg, mesh: Mesh, microbatches: Optional[int] = None):
    """GPT-2 loss with the block trunk pipelined over ``pp``.

    Drop-in replacement for the bundle's loss_fn: embeddings and the
    streamed vocab loss stay plain GSPMD; only the trunk runs the
    microbatch pipeline. Use with ``shard_train_state`` on a pp>1 mesh
    (the partition rules place each stage's layers automatically).
    """
    from distributedvolunteercomputing_tpu.models import gpt2

    def loss_fn(params, batch, rng):
        x = gpt2.embed(params, batch["tokens"], cfg)
        x = pipeline_trunk(
            lambda p, h: gpt2.block_fn(p, h, cfg),
            params["blocks"],
            x,
            mesh,
            microbatches=microbatches,
            remat=cfg.remat,
        )
        return gpt2.lm_loss_from_hidden(params, x, batch, cfg)

    return loss_fn
