"""Ulysses-style sequence parallelism: all-to-all swaps seq <-> heads.

The second sequence-parallel implementation next to ring attention
(parallel/ring_attention.py). Where the ring rotates K/V chunks sp-1 times
(sp-1 ppermute hops, online-softmax merges per hop), Ulysses pays exactly
TWO all-to-alls per attention: one to trade the sequence sharding for a
head sharding (each device then holds H/sp heads of the FULL sequence),
one to trade back after a completely ordinary full-sequence attention —
which on TPU means the pallas flash kernel runs unmodified per head group,
and the collectives are the all-to-alls ICI is built for.

Trade-offs vs the ring (why both exist):
- Ulysses needs ``n_heads % sp == 0``; the ring works for any head count.
- Ulysses holds full-sequence activations for its head group: per-device
  attention memory is O(H/sp * T) vs the ring's O(H * T/sp) — same total,
  but the ring also never materializes more than a [Tl, Tl] score block
  while Ulysses leans on the flash kernel for that.
- Ring = sp-1 neighbor hops; Ulysses = 2 global all-to-alls. On a real ICI
  torus the all-to-alls win at moderate sp; the ring wins at very large sp.

All ops are differentiable JAX primitives (all_to_all has a transpose
rule), so backward needs no custom VJP. Causal masking is exact: the inner
attention sees the full, correctly ordered sequence.

Reference note: the reference genre is volunteer data-parallel only
(SURVEY.md §2); this module is build-side long-context work, prescribed by
the task brief ("ring attention or all-to-all sequence/context
parallelism").
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, PartitionSpec as P


def ulysses_attention(
    q: jax.Array,  # [B, H, Tl, D] — the local sequence shard
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Exact attention via seq<->head all-to-alls; call INSIDE shard_map
    over ``axis_name``."""
    from distributedvolunteercomputing_tpu.ops.attention import attention_core_local

    # psum(1, axis) is the axis size on BOTH sides of the jax API split
    # (jax.lax.axis_size does not exist on the tier-1 jax).
    sp = jax.lax.psum(1, axis_name)
    h = q.shape[1]
    if h % sp != 0:
        raise ValueError(
            f"ulysses sequence parallelism needs n_heads % sp == 0 "
            f"(H={h}, sp={sp}); use the ring impl for this config"
        )

    def seq_to_heads(x):  # [B, H, Tl, D] -> [B, H/sp, T, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):  # [B, H/sp, T, D] -> [B, H, Tl, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    out = attention_core_local(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v), causal=causal
    )
    return heads_to_seq(out)


def ulysses_attention_bhtd(
    q: jax.Array,  # [B, H, T, D] global; T sharded over ``axis``
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
) -> jax.Array:
    """shard_map'd Ulysses attention — same wrapper as ring_attention_bhtd
    (ring_attention.sp_shard_map)."""
    from distributedvolunteercomputing_tpu.parallel.ring_attention import sp_shard_map

    inner = sp_shard_map(
        functools.partial(ulysses_attention, axis_name=axis, causal=causal), mesh, axis
    )
    return inner(q, k, v)
