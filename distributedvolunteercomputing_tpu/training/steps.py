"""The compiled per-volunteer train step.

Reference parity: the per-worker CUDA ``train_step`` (BASELINE.json:5) —
forward + backward + local optimizer update, entirely on-device. Here it is
one ``jax.jit`` computation with donated state, so XLA fuses fwd/bwd/update
and the params never round-trip to host between steps. The multi-chip variant
(psum over ICI inside the same compiled step) lives in
``parallel/train_step.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

Batch = Dict[str, jax.Array]
Metrics = Dict[str, jax.Array]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Everything the volunteer owns on-device: params, opt state, step, rng."""

    params: Any
    opt_state: Any
    step: jax.Array
    rng: jax.Array

    @classmethod
    def create(cls, params: Any, tx: optax.GradientTransformation, rng: jax.Array) -> "TrainState":
        return cls(
            params=params,
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
            rng=rng,
        )


def grad_half(
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]],
    state: TrainState,
    batch: Batch,
    accum_steps: int = 1,
) -> Tuple[Any, Metrics, jax.Array]:
    """fwd/bwd half of the step: (grads, metrics, next_rng).

    ``accum_steps > 1`` runs gradient accumulation INSIDE the compiled step:
    the batch's leading dim is split into ``accum_steps`` microbatches and
    scanned (``lax.scan`` — one microbatch's HLO in the program, activation
    memory of ONE microbatch), grads averaged across them. The optimizer
    semantics are identical to one big batch; only peak activation memory
    changes — the TPU-idiomatic way to train effective batch sizes that
    don't fit HBM."""
    rng, step_rng = jax.random.split(state.rng)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    if accum_steps <= 1:
        (_, metrics), grads = grad_fn(state.params, batch, step_rng)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return grads, metrics, rng

    micro = jax.tree_util.tree_map(
        lambda x: x.reshape((accum_steps, x.shape[0] // accum_steps) + x.shape[1:]),
        batch,
    )

    def body(carry, mb_and_rng):
        g_acc, m_acc = carry
        mb, r = mb_and_rng
        (_, m), g = grad_fn(state.params, mb, r)
        g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
        m_acc = jax.tree_util.tree_map(jnp.add, m_acc, m)
        return (g_acc, m_acc), None

    g0 = jax.tree_util.tree_map(jnp.zeros_like, state.params)
    # One traced microbatch probe would double compile time; metrics trees in
    # the zoo are scalar-valued, so zeros of scalars is the right init.
    m0 = jax.eval_shape(
        lambda p, b, r: loss_fn(p, b, r)[1],
        state.params,
        jax.tree_util.tree_map(lambda x: x[0], micro),
        step_rng,
    )
    m0 = jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), m0)
    rngs = jax.random.split(step_rng, accum_steps)
    (g_sum, m_sum), _ = jax.lax.scan(body, (g0, m0), (micro, rngs))
    inv = 1.0 / accum_steps
    grads = jax.tree_util.tree_map(lambda g: g * inv, g_sum)
    metrics = dict(jax.tree_util.tree_map(lambda m: m * inv, m_sum))
    metrics["grad_norm"] = optax.global_norm(grads)
    return grads, metrics, rng


def apply_half(
    tx: optax.GradientTransformation,
    state: TrainState,
    grads: Any,
    rng: jax.Array,
) -> TrainState:
    """Optimizer-update half of the step."""
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params=params, opt_state=opt_state, step=state.step + 1, rng=rng)


def train_step_body(
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]],
    tx: optax.GradientTransformation,
    state: TrainState,
    batch: Batch,
    accum_steps: int = 1,
) -> Tuple[TrainState, Metrics]:
    """The traced step math, shared by the single-device step, the sharded
    step (parallel/train_step.py), and — via its two halves — the split
    grad/apply steps of gradient-averaging mode, so no path can diverge."""
    grads, metrics, rng = grad_half(loss_fn, state, batch, accum_steps)
    return apply_half(tx, state, grads, rng), metrics


def make_train_step(
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]],
    tx: optax.GradientTransformation,
    donate: bool = True,
    accum_steps: int = 1,
) -> Callable[[TrainState, Batch], Tuple[TrainState, Metrics]]:
    """Build the jitted ``(state, batch) -> (state, metrics)`` step."""

    def step(state: TrainState, batch: Batch) -> Tuple[TrainState, Metrics]:
        return train_step_body(loss_fn, tx, state, batch, accum_steps)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_multi_step(
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]],
    tx: optax.GradientTransformation,
    accum_steps: int = 1,
) -> Callable[[TrainState, Batch], Tuple[TrainState, jax.Array]]:
    """N train steps in ONE compiled call: ``(state, stacked_batches) ->
    (state, per_step_losses)``.

    Host-loop amortization (Trainer ``steps_per_call``): a Python loop
    dispatches one program per step, so per-dispatch overhead (tens of µs
    locally; a full HTTP round-trip on a tunneled runtime) sits on the
    step's critical path. ``lax.scan`` over the SAME traced body
    (``train_step_body`` — identical math to the single step, by
    construction) moves the loop on-device: one dispatch per N steps, and
    XLA can overlap the next step's prologue with the previous epilogue.
    The leading axis of every batch leaf is the step index."""

    def multi(state: TrainState, batches: Batch) -> Tuple[TrainState, jax.Array]:
        def body(s: TrainState, b: Batch):
            s2, metrics = train_step_body(loss_fn, tx, s, b, accum_steps)
            return s2, metrics["loss"]

        return jax.lax.scan(body, state, batches)

    return jax.jit(multi, donate_argnums=(0,))


def make_grad_step(
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]],
    accum_steps: int = 1,
) -> Callable[[TrainState, Batch], Tuple[Any, Metrics, jax.Array]]:
    """Gradient-averaging mode, half 1: fwd/bwd WITHOUT the update.

    The reference's synchronous GradientAverager semantics (BASELINE.json:5)
    average GRADIENTS across volunteers before any optimizer sees them; that
    forces the grads out to host between bwd and update, so the fused step
    splits into (grad_step, apply_step). State is NOT donated here — the
    same state is consumed again by apply_step."""
    return jax.jit(lambda state, batch: grad_half(loss_fn, state, batch, accum_steps))


def make_apply_step(
    tx: optax.GradientTransformation,
    donate: bool = True,
) -> Callable[[TrainState, Any, jax.Array], TrainState]:
    """Gradient-averaging mode, half 2: optimizer update from (possibly
    swarm-averaged) grads."""
    return jax.jit(
        lambda state, grads, rng: apply_half(tx, state, grads, rng),
        donate_argnums=(0,) if donate else (),
    )


def make_eval_step(
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]],
) -> Callable[[Any, Batch, jax.Array], Metrics]:
    def ev(params: Any, batch: Batch, rng: jax.Array) -> Metrics:
        _, metrics = loss_fn(params, batch, rng)
        return metrics

    return jax.jit(ev)
