"""Checkpoint/resume (SURVEY.md §5): Orbax-backed local snapshots.

Volunteer churn only makes sense if a stopped volunteer can come back
(preemption -> restart on a fresh TPU-VM): ``save`` flushes the full
TrainState (params, optimizer state, step, rng), ``maybe_restore`` loads the
newest snapshot if one exists. Peer-pull resume (fetching newer params from
live peers after a long absence) lives in swarm.state_sync.
"""

from __future__ import annotations

import os
import re
from typing import Optional

import jax
import numpy as np

from distributedvolunteercomputing_tpu.utils.logging import get_logger

log = get_logger(__name__)

_STEP_RE = re.compile(r"^step_(\d+)$")


def _state_to_pytree(trainer) -> dict:
    return {
        "params": trainer.state.params,
        "opt_state": trainer.state.opt_state,
        "step": trainer.state.step,
        "rng": trainer.state.rng,
    }


def save(trainer, ckpt_dir: str) -> str:
    import orbax.checkpoint as ocp

    step = int(trainer.state.step)
    path = os.path.abspath(os.path.join(ckpt_dir, f"step_{step}"))
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, _state_to_pytree(trainer), force=True)
    log.info("checkpoint saved: %s", path)
    return path


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [
        int(m.group(1))
        for name in os.listdir(ckpt_dir)
        if (m := _STEP_RE.match(name))
    ]
    return max(steps) if steps else None


def maybe_restore(trainer, ckpt_dir: str) -> bool:
    """Load the newest snapshot into the trainer, if any. Returns True if restored."""
    import orbax.checkpoint as ocp

    step = latest_step(ckpt_dir)
    if step is None:
        return False
    path = os.path.abspath(os.path.join(ckpt_dir, f"step_{step}"))
    template = jax.tree_util.tree_map(np.asarray, _state_to_pytree(trainer))
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path, item=template)
    from distributedvolunteercomputing_tpu.training.steps import TrainState

    trainer.state = TrainState(
        params=jax.device_put(restored["params"]),
        opt_state=jax.device_put(restored["opt_state"]),
        step=jax.device_put(restored["step"]),
        rng=jax.device_put(restored["rng"]),
    )
    # Refresh the cross-thread snapshot: the state-sync provider must
    # announce/serve the RESTORED step, not the cold init from __init__.
    trainer._take_snapshot(step)
    log.info("restored checkpoint step %d from %s", step, path)
    return True
