"""Checkpoint/resume (SURVEY.md §5): Orbax-backed local snapshots.

Volunteer churn only makes sense if a stopped volunteer can come back
(preemption -> restart on a fresh TPU-VM): ``save`` flushes the full
TrainState (params, optimizer state, step, rng), ``maybe_restore`` loads the
newest snapshot if one exists. Peer-pull resume (fetching newer params from
live peers after a long absence) lives in swarm.state_sync.
"""

from __future__ import annotations

import os
import re
from typing import Optional

import jax
import numpy as np

from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

_STEP_RE = re.compile(r"^step_(\d+)$")


def _state_to_pytree(trainer) -> dict:
    return {
        "params": trainer.state.params,
        "opt_state": trainer.state.opt_state,
        "step": trainer.state.step,
        "rng": trainer.state.rng,
    }


# Snapshots kept per directory after a save (DVC_CKPT_KEEP overrides).
# Periodic saves otherwise grow without bound: at gpt2_small scale each
# snapshot is ~1.5 GB (params + AdamW moments), and a long volunteer run
# with --checkpoint-every 200 would fill the disk.
def _keep_last() -> int:
    raw = os.environ.get("DVC_CKPT_KEEP", "3")
    try:
        return int(raw)
    except ValueError:
        log.warning("DVC_CKPT_KEEP=%r is not an integer; keeping 3", raw)
        return 3


KEEP_LAST = _keep_last()


def _gc(ckpt_dir: str, just_saved: int, keep: int = 0) -> None:
    """Delete all but the newest ``keep`` snapshots (by step number), never
    touching steps >= the snapshot just written — stale HIGHER-step entries
    (a reused directory, a second volunteer lagging behind) must not make GC
    eat the save that just happened."""
    keep = keep or KEEP_LAST
    if not os.path.isdir(ckpt_dir) or keep <= 0:
        return
    import shutil

    steps = sorted(
        int(m.group(1)) for name in os.listdir(ckpt_dir) if (m := _STEP_RE.match(name))
    )
    for step in steps[:-keep]:
        if step >= just_saved:
            continue
        path = os.path.join(ckpt_dir, f"step_{step}")
        try:
            shutil.rmtree(path)
            # The outer-state sidecar lives BESIDE the snapshot dir.
            for sidecar in (_outer_state_path(path), _wire_state_path(path)):
                if os.path.exists(sidecar):
                    os.remove(sidecar)
            log.info("checkpoint GC: removed %s", path)
        except OSError as e:
            log.warning("checkpoint GC failed for %s: %s", path, errstr(e))


def _outer_state_path(snapshot_path: str) -> str:
    # Beside (not inside) the orbax directory: orbax owns its directory
    # layout, and a foreign file inside it could break its metadata checks.
    return snapshot_path + ".outer.npz"


def _save_outer_state(trainer, snapshot_path: str) -> None:
    """Persist the DiLoCo outer anchor/momentum beside the snapshot.

    A separate optional file, NOT a new key in the orbax tree: the restore
    template is built from the live TrainState, so widening the tree would
    break restores of every pre-existing checkpoint. Losing the momentum
    stream on every preemption would forfeit the outer optimizer's gain in
    exactly the churn regime the framework targets."""
    anchor = getattr(trainer, "_outer_anchor", None)
    if getattr(trainer, "outer_optimizer", "none") == "none" or anchor is None:
        return
    from distributedvolunteercomputing_tpu.utils.pytree import flatten_to_buffer

    buf_a, _, _ = flatten_to_buffer(anchor)
    buf_m, _, _ = flatten_to_buffer(trainer._outer_m)
    try:
        np.savez(_outer_state_path(snapshot_path), anchor=buf_a, m=buf_m)
    except OSError as e:
        log.warning("outer-state save failed (continuing): %s", errstr(e))


def _wire_state_path(snapshot_path: str) -> str:
    # Same beside-the-snapshot policy as the outer-state sidecar.
    return snapshot_path + ".wire.npz"


def _save_wire_state(trainer, snapshot_path: str) -> None:
    """Persist the averager's compressor state (EF residual, PowerSGD warm
    Q) beside the snapshot (r4 VERDICT #7: a preempted volunteer on the
    powersgd wire rejoined cold for no strong reason — the sidecar
    mechanism already existed). The volunteer attaches its averager as
    ``trainer._wire_averager``; library users without a swarm simply have
    no sidecar."""
    avg = getattr(trainer, "_wire_averager", None)
    if avg is None:
        return
    try:
        state = avg.wire_state()
    except Exception as e:  # noqa: BLE001 — sidecar must never kill a save
        log.warning("wire-state snapshot failed (continuing): %s", errstr(e))
        return
    if not state:
        return
    try:
        np.savez(_wire_state_path(snapshot_path), **state)
    except OSError as e:
        log.warning("wire-state save failed (snapshot is intact): %s", errstr(e))


def _maybe_restore_wire_state(trainer, snapshot_path: str) -> None:
    """Hand the sidecar back to the averager, which validates against its
    schema at first pack and re-seeds on mismatch with one LOUD warning
    naming the old/new wire+rank+size (same cold-start semantics as the
    outer-state sidecar; see AveragerBase._apply_pending_wire_state)."""
    avg = getattr(trainer, "_wire_averager", None)
    if avg is None:
        return
    path = _wire_state_path(snapshot_path)
    if not os.path.exists(path):
        return
    try:
        with np.load(path) as d:
            avg.load_wire_state({k: d[k] for k in d.files})
        log.info("restored averager wire state from %s", path)
    except (OSError, ValueError, KeyError) as e:
        log.warning("wire-state restore failed (re-seeding): %s", errstr(e))


def _maybe_restore_outer_state(trainer, snapshot_path: str) -> None:
    """Rebuild anchor/momentum from the sidecar if it matches the current
    payload schema; silently absent otherwise (the next round re-seeds —
    the documented cold-start semantics)."""
    if getattr(trainer, "outer_optimizer", "none") == "none":
        return
    path = _outer_state_path(snapshot_path)
    if not os.path.exists(path):
        return
    from distributedvolunteercomputing_tpu.utils.pytree import (
        tree_specs,
        unflatten_from_buffer,
    )

    # Specs only — no D2H gather of the payload (tree_specs reads
    # shape/dtype straight off the jax leaves).
    specs, treedef = tree_specs(trainer.bundle.avg_select(trainer.state.params))
    expect = int(sum(s.size for s in specs))
    try:
        with np.load(path) as d:
            buf_a, buf_m = d["anchor"], d["m"]
    except (OSError, ValueError, KeyError) as e:
        log.warning("outer-state restore failed (re-seeding): %s", errstr(e))
        return
    if buf_a.size != expect or buf_m.size != expect:
        log.warning(
            "outer-state size %d != payload schema %d; re-seeding",
            buf_a.size, expect,
        )
        return
    trainer._outer_anchor = unflatten_from_buffer(buf_a, specs, treedef)
    trainer._outer_m = unflatten_from_buffer(buf_m, specs, treedef)
    log.info("restored outer-optimizer state from %s", path)


def save(trainer, ckpt_dir: str) -> str:
    import orbax.checkpoint as ocp

    step = int(trainer.state.step)
    path = os.path.abspath(os.path.join(ckpt_dir, f"step_{step}"))
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, _state_to_pytree(trainer), force=True)
    _save_outer_state(trainer, path)
    _save_wire_state(trainer, path)
    log.info("checkpoint saved: %s", path)
    _gc(ckpt_dir, just_saved=step)
    return path


def save_async(trainer, ckpt_dir: str) -> bool:
    """Periodic-save path: snapshot to HOST on the caller's thread (one D2H
    COPY — np.array, never np.asarray: on the CPU backend asarray can alias
    the live jax buffer, which the donating train step then reuses while the
    writer thread is mid-serialization, silently corrupting the snapshot),
    then write the file on a background thread so the device never idles on
    disk I/O. At most one save in flight PER TRAINER — if its previous
    write is still running, skip this point (the next cadence retries; a
    skipped periodic save just widens one interval). The FINAL save at exit
    must drain via ``wait_pending_saves`` and then use ``save``.
    Returns True if a save was started."""
    import threading

    prev = getattr(trainer, "_ckpt_writer", None)
    if prev is not None and prev.is_alive():
        log.info("checkpoint still writing; skipping this save point")
        return False
    host_tree = jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True), _state_to_pytree(trainer)
    )
    # Record WHICH state this snapshot is (step + out-of-band mutation
    # count), so the final-save path can tell "already saved" apart from
    # "same step number but params mutated since" (end-of-run merge).
    trainer._ckpt_snapshot_id = (int(host_tree["step"]), getattr(trainer, "mutation_counter", 0))
    step = int(host_tree["step"])
    path = os.path.abspath(os.path.join(ckpt_dir, f"step_{step}"))

    # Outer-optimizer state is snapshotted on the CALLER thread too (it is
    # host numpy mutated only between steps on this same thread); the
    # writer thread just serializes the copies.
    outer_bufs = None
    if getattr(trainer, "outer_optimizer", "none") != "none" and getattr(
        trainer, "_outer_anchor", None
    ) is not None:
        from distributedvolunteercomputing_tpu.utils.pytree import flatten_to_buffer

        outer_bufs = (
            flatten_to_buffer(trainer._outer_anchor)[0],
            flatten_to_buffer(trainer._outer_m)[0],
        )
    # Compressor state snapshotted on the caller thread too (wire_state
    # copies arrays that the averager only ever replaces wholesale).
    wire_snapshot = None
    if getattr(trainer, "_wire_averager", None) is not None:
        try:
            wire_snapshot = trainer._wire_averager.wire_state()
        except Exception as e:  # noqa: BLE001
            log.warning("wire-state snapshot failed (continuing): %s", errstr(e))

    def _write():
        import orbax.checkpoint as ocp

        try:
            with ocp.PyTreeCheckpointer() as ckptr:
                ckptr.save(path, host_tree, force=True)
        except Exception as e:  # noqa: BLE001 — a failed periodic save must not kill training
            log.warning("async checkpoint save failed: %s", errstr(e))
            return
        # Sidecar failure must not mislabel the landed snapshot as failed,
        # and must never skip GC (that's how a disk fills).
        if outer_bufs is not None:
            try:
                np.savez(_outer_state_path(path), anchor=outer_bufs[0], m=outer_bufs[1])
            except OSError as e:
                log.warning("outer-state save failed (snapshot is intact): %s", errstr(e))
        if wire_snapshot:
            try:
                np.savez(_wire_state_path(path), **wire_snapshot)
            except OSError as e:
                log.warning("wire-state save failed (snapshot is intact): %s", errstr(e))
        log.info("checkpoint saved (async): %s", path)
        _gc(ckpt_dir, just_saved=step)

    t = threading.Thread(target=_write, name="ckpt-writer", daemon=True)
    trainer._ckpt_writer = t
    t.start()
    return True


def wait_pending_saves(trainer, hard_cap: float = 600.0) -> bool:
    """Block until THIS trainer's in-flight async save lands. Returns True
    when nothing is in flight anymore; False if the writer is still alive
    after ``hard_cap`` (e.g. dead NFS) — in that case the caller must NOT
    write the same directory (concurrent orbax writes to one path corrupt
    both), and should skip its synchronous save."""
    import time as _time

    t = getattr(trainer, "_ckpt_writer", None)
    if t is None or not t.is_alive():
        return True
    deadline = _time.monotonic() + hard_cap
    while t.is_alive():
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            log.error(
                "async checkpoint writer still running after %.0fs; "
                "skipping the conflicting synchronous save", hard_cap,
            )
            return False
        t.join(min(remaining, 10.0))
    return True


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [
        int(m.group(1))
        for name in os.listdir(ckpt_dir)
        if (m := _STEP_RE.match(name))
    ]
    return max(steps) if steps else None


def maybe_restore(trainer, ckpt_dir: str) -> bool:
    """Load the newest snapshot into the trainer, if any. Returns True if restored."""
    import orbax.checkpoint as ocp

    step = latest_step(ckpt_dir)
    if step is None:
        return False
    path = os.path.abspath(os.path.join(ckpt_dir, f"step_{step}"))
    template = jax.tree_util.tree_map(np.asarray, _state_to_pytree(trainer))
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path, item=template)
    from distributedvolunteercomputing_tpu.training.steps import TrainState

    host_state = TrainState(
        params=restored["params"],
        opt_state=restored["opt_state"],
        step=restored["step"],
        rng=restored["rng"],
    )
    if getattr(trainer, "param_dtype", None):
        # Re-apply the CONFIGURED dtype over whatever the snapshot holds:
        # a snapshot taken before a fleet-wide --param-dtype change would
        # otherwise silently restore the old dtype, flip this volunteer's
        # averaging schema hash away from its peers', and strand it
        # training solo (every round refused by _check_schema).
        from distributedvolunteercomputing_tpu.utils.pytree import cast_floating

        host_state = TrainState(
            params=cast_floating(host_state.params, trainer.param_dtype),
            opt_state=cast_floating(host_state.opt_state, trainer.param_dtype),
            step=host_state.step,
            rng=host_state.rng,
        )
    if trainer.mesh is not None:
        # A mesh trainer's state lives SHARDED (tp/pp rules; 1/dp per chip
        # under fsdp). Place the restored HOST trees directly with the
        # rule-derived shardings, exactly as __init__ did — any intermediate
        # whole-tree device_put would materialize the full state on one
        # chip first, which on a slice sized for fsdp (the one regime where
        # the model does NOT fit one chip) is an immediate OOM.
        from distributedvolunteercomputing_tpu.parallel.train_step import (
            shard_train_state,
        )

        trainer.state, trainer._param_shardings = shard_train_state(
            host_state, trainer.mesh, trainer.tx, fsdp=trainer.fsdp
        )
    else:
        trainer.state = jax.tree_util.tree_map(jax.device_put, host_state)
    _maybe_restore_outer_state(trainer, path)
    _maybe_restore_wire_state(trainer, path)
    # Refresh the cross-thread snapshot: the state-sync provider must
    # announce/serve the RESTORED step, not the cold init from __init__.
    trainer._take_snapshot(step)
    log.info("restored checkpoint step %d from %s", step, path)
    return True


# -- sharded snapshots (zone-sharded training, swarm/sharding.py) ------------
#
# A sharded volunteer never HOLDS the full tree, so the full-TrainState save
# above cannot run on it. Instead each holder snapshots its OWN shard slices
# (one .npy per shard plus a json meta carrying the fenced map generation),
# and a zone's worth of shard snapshots reassembles into the full flat
# buffer for export/eval. Deliberately plain numpy files, not Orbax: a shard
# is one contiguous f32 slice with no tree structure, and the recovery
# ladder (not this file) is the availability story — these snapshots exist
# so a COLD-started zone (every holder gone at once, the one case the
# ladder cannot close) resumes from local disk instead of step 0.


def save_shard_snapshot(ckpt_dir: str, store, smap, step: int) -> str:
    """Write every OWNED shard slice + meta under ``ckpt_dir/shards/``.
    Returns the snapshot directory. Meta pins (k, gen, zone members) so a
    restore into a differently-cut world is refused loudly."""
    import json

    d = os.path.join(ckpt_dir, "shards", f"step_{int(step):010d}")
    os.makedirs(d, exist_ok=True)
    owned = []
    for s in store.held():
        arr = store.get(s, allow_replica=False)
        if arr is None:
            continue
        np.save(os.path.join(d, f"shard_{s}.npy"), np.asarray(arr, np.float32))
        owned.append(int(s))
    meta = {
        "step": int(step),
        "k": int(smap.k),
        "gen": int(smap.gen),
        "domain": smap.domain,
        "members": list(smap.members),
        "owned": owned,
    }
    with open(os.path.join(d, "meta.json"), "w") as fh:
        json.dump(meta, fh)
    log.info("saved shard snapshot step %d (%d shard(s)) to %s", step, len(owned), d)
    return d


def load_shard_snapshot(snap_dir: str, k: int) -> dict:
    """Load one holder's shard snapshot: {"meta": ..., "shards": {s: arr}}.
    Refuses a snapshot cut for a different K — shard ranges depend only on
    (n_elems, K), so a K mismatch means the slices are NOT the same tensor
    regions and silently adopting them would scramble the model."""
    import json

    with open(os.path.join(snap_dir, "meta.json")) as fh:
        meta = json.load(fh)
    if int(meta.get("k", -1)) != int(k):
        raise ValueError(
            f"shard snapshot k={meta.get('k')} != configured k={k}: "
            "refusing a differently-cut restore"
        )
    shards = {}
    for s in meta.get("owned", []):
        p = os.path.join(snap_dir, f"shard_{int(s)}.npy")
        if os.path.exists(p):
            shards[int(s)] = np.load(p)
    return {"meta": meta, "shards": shards}


def assemble_full(snap_dirs, n_elems: int, k: int) -> np.ndarray:
    """Reassemble the full flat buffer from a zone's shard snapshots (one
    directory per holder; later directories win ties). Raises if any shard
    range is missing — a partial assembly is not a model."""
    from distributedvolunteercomputing_tpu.swarm.sharding import shard_ranges

    ranges = shard_ranges(int(n_elems), int(k))
    buf = np.zeros(int(n_elems), np.float32)
    got = set()
    for d in snap_dirs:
        snap = load_shard_snapshot(d, k)
        for s, arr in snap["shards"].items():
            lo, hi = ranges[s]
            if arr.size != hi - lo:
                raise ValueError(
                    f"shard {s} snapshot has {arr.size} elems, range needs {hi - lo}"
                )
            buf[lo:hi] = arr
            got.add(s)
    missing = [s for s in range(k) if s not in got]
    if missing:
        raise ValueError(f"shard snapshot set is missing shard(s) {missing}")
    return buf
