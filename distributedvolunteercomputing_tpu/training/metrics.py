"""Per-volunteer metrics: JSONL records + samples/sec/chip.

The headline metric is samples/sec/volunteer-chip and time-to-target-loss
(BASELINE.json:2). Each volunteer writes one JSONL stream; the coordinator
aggregates swarm-level numbers (SURVEY.md §5).
"""

from __future__ import annotations

import json
import time
from typing import IO, Any, Dict, Optional


class MetricsWriter:
    def __init__(self, path: Optional[str] = None, volunteer_id: str = "local"):
        self.volunteer_id = volunteer_id
        self._fh: Optional[IO[str]] = open(path, "a") if path else None
        self._t0 = time.monotonic()
        self._samples = 0
        self._last_rate_t = self._t0
        self._last_rate_samples = 0

    @property
    def has_sink(self) -> bool:
        return self._fh is not None

    def count_samples(self, n: int) -> None:
        """Cheap path: bump the sample counter without touching metric values."""
        self._samples += n

    def _emit(self, step: int, fields: Dict[str, Any]) -> None:
        if self._fh is not None:
            rec = {
                "t": round(time.monotonic() - self._t0, 4),
                "volunteer": self.volunteer_id,
                "step": step,
                **fields,
            }
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()

    def record(self, step: int, metrics: Dict[str, Any], n_samples: int = 0) -> None:
        self._samples += n_samples
        self._emit(step, {k: float(v) for k, v in metrics.items()})

    def record_event(self, step: int, event: str, fields: Dict[str, Any]) -> None:
        """Non-metric timeline record (e.g. one averaging round's wall-clock
        and outcome); same JSONL stream, tagged by ``event``."""
        self._emit(step, {"event": event, **fields})

    def samples_per_sec(self) -> float:
        """Rate since the previous call (windowed, not lifetime)."""
        now = time.monotonic()
        dt = now - self._last_rate_t
        ds = self._samples - self._last_rate_samples
        self._last_rate_t, self._last_rate_samples = now, self._samples
        return ds / dt if dt > 0 else 0.0

    @property
    def total_samples(self) -> int:
        return self._samples

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
