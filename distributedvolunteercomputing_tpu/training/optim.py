"""Optimizers for the volunteer train loop (optax-backed).

The reference's per-worker loop runs a local optimizer step every batch and
averages every K steps (SURVEY.md §3-C); any optax GradientTransformation
slots in here.
"""

from __future__ import annotations

from typing import Optional

import optax


def make_optimizer(
    name: str = "adamw",
    lr: float = 1e-3,
    weight_decay: float = 0.0,
    warmup_steps: int = 0,
    total_steps: Optional[int] = None,
    grad_clip: Optional[float] = 1.0,
    momentum: float = 0.9,
) -> optax.GradientTransformation:
    # optax needs decay_steps strictly past warmup (warmup is clamped to >=1
    # below, so a 1-step run would otherwise ask for a 0-step cosine decay).
    if total_steps and total_steps > max(warmup_steps, 1):
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=lr,
            warmup_steps=max(warmup_steps, 1),
            decay_steps=total_steps,
        )
    elif warmup_steps:
        schedule = optax.linear_schedule(0.0, lr, warmup_steps)
    else:
        schedule = lr

    if name == "adamw":
        core = optax.adamw(schedule, weight_decay=weight_decay)
    elif name == "adam":
        core = optax.adam(schedule)
    elif name == "sgd":
        core = optax.sgd(schedule, momentum=momentum)
    else:
        raise ValueError(f"unknown optimizer {name!r}")

    if grad_clip:
        return optax.chain(optax.clip_by_global_norm(grad_clip), core)
    return core
