"""The volunteer train loop: local SGD + periodic collaborative averaging.

Reference call stack C (SURVEY.md §3): data -> device -> fwd/bwd -> local
optimizer step -> every K steps, hand params to the averager and continue
from the averaged result. The averager is injected as a callback so the
trainer (L5) never imports the swarm (L3/L4) — config 1 (single volunteer,
no averaging, BASELINE.json:7) is just ``averager=None``.

Params mode can OVERLAP the WAN round with continued local compute
(``overlap=True``): at an averaging point the trainer snapshots the payload
to host, hands it to a background thread, and keeps stepping; when the round
completes it merges Moshpit-style with a delta correction,

    new = averaged + (current - snapshot),

so the local steps taken during the round are preserved on top of the
contracted average. Grads mode stays synchronous BY DESIGN: GradientAverager
semantics feed each step's averaged gradient to the optimizer before the
next step — applying it late would mean stale-gradient SGD, a different
algorithm, not an optimization.
"""

from __future__ import annotations

import concurrent.futures
import os
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributedvolunteercomputing_tpu.models.registry import Batch, ModelBundle
from distributedvolunteercomputing_tpu.training.metrics import MetricsWriter
from distributedvolunteercomputing_tpu.training.optim import make_optimizer
from distributedvolunteercomputing_tpu.training.steps import (
    TrainState,
    make_apply_step,
    make_grad_step,
    make_train_step,
)
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

# Averager callback: takes the CURRENT host params pytree, returns the
# averaged pytree (or None to keep local params, e.g. when no group formed).
AveragerFn = Callable[[Any, int], Optional[Any]]


class Trainer:
    def __init__(
        self,
        bundle: ModelBundle,
        batch_size: int = 32,
        optimizer: str = "adamw",
        lr: float = 1e-3,
        seed: int = 0,
        init_seed: int = 0,
        # Cast floating params to this dtype after init ("bfloat16" for
        # bf16 training — the bench's DVC_BENCH_PARAM_DTYPE arm, now a
        # first-class trainer/CLI option); None keeps the model's dtype.
        param_dtype: Optional[str] = None,
        # Microbatch count per optimizer step (gradient accumulation inside
        # the compiled step); batch_size must divide evenly. Semantics match
        # one big batch — only peak activation memory changes.
        accum_steps: int = 1,
        # Host-loop amortization: scan up to N train steps inside ONE
        # compiled call (steps.make_multi_step), so per-step Python dispatch
        # leaves the hot path. Chunks end at every metrics/eval/averaging
        # boundary, so cadence semantics are unchanged; within a chunk,
        # per-step losses still come back (scan ys) for target detection.
        # 1 = off. Params mode, single-device/slice-internal trainers only.
        steps_per_call: int = 1,
        # Extra step cadences scan chunks must end at (beyond eval/log/
        # averaging, which are clipped automatically) — e.g. the volunteer
        # passes its checkpoint_every here, since that cadence lives inside
        # its on_step closure where _chunk_len can't see it.
        chunk_cadences: Tuple[int, ...] = (),
        average_every: int = 10,
        # Wall-clock averaging cadence for HETEROGENEOUS swarms (params mode
        # only; 0 = off, use the step cadence above). Rounds trigger when
        # wall time crosses a multiple of the interval — every volunteer
        # with an NTP-ish clock crosses the same boundary within ms, so a
        # v4-8 doing 40 steps per window rendezvouses cleanly with a v5e-4
        # doing 15, where a step-count cadence would leave the fast peer
        # parked in matchmaking every round (or never aligned at all).
        # Contribution weights carry samples-since-last-merge, so unequal
        # local progress is weighted correctly by construction.
        average_interval_s: float = 0.0,
        # Clock the wall-cadence boundaries are computed on. The volunteer
        # passes its ClockSync's corrected clock (swarm/clocksync.py) so
        # boundaries rendezvous even under multi-second clock skew;
        # defaults to time.time for library users.
        wall_clock: Optional[Callable[[], float]] = None,
        averager: Optional[AveragerFn] = None,
        # params: local-SGD, averaged every `average_every` steps.
        # grads: GradientAverager semantics, averaged EVERY step
        #        (average_every then only sets the host-snapshot cadence).
        average_what: str = "params",
        # Overlap the WAN round with continued local steps (params mode
        # only). ``max_staleness`` bounds how many steps a round's result may
        # lag before it is discarded instead of merged (0 = no bound).
        overlap: bool = False,
        max_staleness: int = 0,
        metrics_path: Optional[str] = None,
        volunteer_id: str = "local",
        total_steps: Optional[int] = None,
        # Called after each HOST-VISIBLE step. With steps_per_call > 1 the
        # scan prefix runs whole chunks on-device, so on_step fires only on
        # chunk-final steps: any per-step or modular cadence inside the
        # callback MUST be declared in chunk_cadences (chunks then end at
        # every multiple, making those steps host-visible) — an undeclared
        # cadence is silently skipped for scan-prefix steps.
        on_step: Optional[Callable[["Trainer", int], None]] = None,
        data: Optional[Iterable[Batch]] = None,  # overrides the synthetic stream
        # In-slice device mesh: when a volunteer owns a multi-chip TPU slice,
        # the step is sharded over it (parallel/train_step.py) — dp/sp/tp/...
        # inside the slice, while the WAN averager still sees one volunteer.
        # ``fsdp`` shards params+opt over the mesh's dp axis (ZeRO-3);
        # ``seq_sharded`` routes attention to the ring kernel over sp.
        mesh: Optional[Any] = None,
        fsdp: bool = False,
        seq_sharded: bool = False,
        sp_impl: str = "ring",  # "ring" | "ulysses" (all-to-all; H % sp == 0)
        # Periodic held-out evaluation: every ``eval_every`` steps, mean loss
        # over ``eval_batches`` batches WITHOUT updating params, recorded as
        # an "eval" metrics event. With synthetic data the eval stream is an
        # independent rng stream (true held-out). With a custom ``data``
        # iterable, pass ``eval_data`` (an independently shuffled stream over
        # the same dataset) for matching semantics; without it, eval falls
        # back to consuming ``data``'s next batches — loss-before-update,
        # but it perturbs the training order volunteers were promised.
        eval_every: int = 0,
        eval_batches: int = 4,
        eval_data: Optional[Iterable[Batch]] = None,
        # DiLoCo-style OUTER optimizer over params-mode averaging rounds
        # (Douillard et al., "DiLoCo: Distributed Low-Communication Training
        # of Language Models"): treat (anchor - averaged) — the swarm's
        # aggregate progress since the last round — as an outer gradient and
        # apply Nesterov momentum to it, instead of adopting the raw mean.
        # At a fixed round cadence this buys convergence-per-round, i.e.
        # time-to-target at the same WAN byte budget (the whole game in the
        # volunteer setting). "none" = plain averaging. Identity when
        # outer_lr=1, outer_momentum=0.
        outer_optimizer: str = "none",
        outer_lr: float = 0.7,
        outer_momentum: float = 0.9,
    ):
        if eval_every and eval_batches < 1:
            raise ValueError(f"eval_batches must be >= 1, got {eval_batches}")
        if average_what not in ("params", "grads"):
            raise ValueError(f"unknown average_what {average_what!r}")
        if average_interval_s < 0:
            raise ValueError(
                f"average_interval_s must be >= 0, got {average_interval_s}"
            )
        if average_interval_s > 0 and average_what == "grads":
            # GradientAverager semantics are per-step by definition — a
            # wall-clock cadence would let optimizer steps run on unmerged
            # gradients, which is params mode's job.
            raise ValueError("average_interval_s requires average_what='params'")
        if steps_per_call < 1:
            raise ValueError(f"steps_per_call must be >= 1, got {steps_per_call}")
        if steps_per_call > 1:
            if averager is not None and average_what == "grads":
                # Grads cross the WAN between bwd and the optimizer EVERY
                # step — there is no multi-step run to amortize.
                raise ValueError("steps_per_call > 1 requires average_what='params'")
        if accum_steps < 1 or batch_size % accum_steps != 0:
            raise ValueError(
                f"accum_steps={accum_steps} must be >=1 and divide batch_size={batch_size}"
            )
        # Persistent XLA compilation cache: volunteers churn (rejoin =
        # re-trace + re-compile, 20-40s on the chip); the cache turns every
        # rejoin after the first into a disk hit. DVC_COMPILE_CACHE= opts out.
        from distributedvolunteercomputing_tpu.utils.jaxenv import enable_compile_cache

        enable_compile_cache()
        self.bundle = bundle
        self.batch_size = batch_size
        self.accum_steps = accum_steps
        self.average_every = average_every
        self.average_interval_s = float(average_interval_s)
        self._wall_clock = wall_clock or time.time
        # Next wall-clock boundary (multiple of the interval) a round is due
        # at; None until run() arms it.
        self._next_avg_t: Optional[float] = None
        # Steps of local progress behind the NEXT params-mode contribution —
        # read by the volunteer's averager callback to weight it in samples.
        # Under the step cadence this is average_every except after failed
        # rounds (progress accumulates); under the interval cadence it is
        # whatever this volunteer managed in the window, which is exactly
        # what makes heterogeneous contributions weigh correctly.
        self.steps_since_merge: int = average_every
        self._last_merge_step: Optional[int] = None
        self.averager = averager
        self.average_what = average_what
        # ``seed`` is PER-VOLUNTEER: it drives the data order and the step
        # rng, so volunteers see different batches. ``init_seed`` is
        # TASK-CONSTANT: every volunteer training the same task must build
        # the same initial params — for LoRA models this is load-bearing
        # (the frozen base is NEVER averaged, so adapters averaged across
        # volunteers are deltas against one shared base; with per-volunteer
        # bases the average would be semantically meaningless), and for full
        # models it makes round 1 start contracted instead of spending early
        # rounds averaging away init noise.
        rng = jax.random.PRNGKey(seed)
        _, data_rng, state_rng = jax.random.split(rng, 3)
        self.tx = make_optimizer(optimizer, lr=lr, total_steps=total_steps)
        params = bundle.init(jax.random.PRNGKey(init_seed))
        self.param_dtype = param_dtype
        if param_dtype:
            # bf16 training (params + optimizer moments + every matmul in
            # the dtype): halves param/optimizer HBM and runs the MXU at
            # native rate. Floating leaves only — integer tables and the
            # step counter keep their dtypes. The swarm tier is
            # dtype-agnostic by construction (flatten_to_buffer ships f32
            # and restores per-leaf dtypes), and init stays bit-identical
            # across volunteers BEFORE the cast, so the task-constant
            # init_seed contract above still holds.
            from distributedvolunteercomputing_tpu.utils.pytree import cast_floating

            params = cast_floating(params, param_dtype)
        self.state = TrainState.create(params, self.tx, state_rng)
        # Gradient-averaging mode splits the step so grads can cross the WAN
        # between bwd and the optimizer (reference GradientAverager
        # semantics); the fused donate-everything step covers the rest.
        self._grads_mode = averager is not None and average_what == "grads"
        self.overlap = bool(overlap) and averager is not None and not self._grads_mode
        self.max_staleness = max_staleness
        # One worker: rounds never overlap each other, only local compute.
        self._avg_pool = (
            concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="avg-round"
            )
            if self.overlap
            else None
        )
        self._inflight: Optional[tuple] = None  # (launch_step, payload0, future)
        if mesh is None and (fsdp or seq_sharded):
            raise ValueError("fsdp/seq_sharded require a mesh (--mesh dp=...,tp=...)")
        if outer_optimizer not in ("none", "nesterov"):
            raise ValueError(f"unknown outer_optimizer {outer_optimizer!r}")
        if outer_optimizer != "none" and averager is not None and average_what != "params":
            # The outer step operates on PARAMETER deltas between rounds;
            # grads mode has no per-round parameter anchor to difference
            # against (each step's gradients are averaged individually).
            raise ValueError("outer_optimizer requires average_what='params'")
        self.outer_optimizer = outer_optimizer
        self.outer_lr = float(outer_lr)
        self.outer_momentum = float(outer_momentum)
        # Host-side outer state: the anchor is the global params the current
        # inner phase STARTED from (payload/avg_select space); the momentum
        # tree accumulates per-round aggregate deltas.
        self._outer_anchor: Any = None
        self._outer_m: Any = None
        if fsdp and average_what == "grads":
            # The split grad/apply steps have no in-step constraint keeping
            # params at 1/dp, so ZeRO-3 would silently re-replicate — and
            # per-step host grad averaging defeats its purpose anyway.
            # Independent of whether an averager is attached NOW: the config
            # asked for grads-mode semantics, and accepting it only when the
            # wiring happens to be absent would make the same flag set pass
            # or fail on an unrelated condition.
            raise ValueError("fsdp is a params-mode feature; use average_what='params'")
        self.mesh = mesh
        self.fsdp = fsdp
        self._param_shardings = None
        self._put_batch: Optional[Callable[[Batch], Batch]] = None
        if mesh is not None:
            from distributedvolunteercomputing_tpu.parallel.train_step import (
                put_batch,
                shard_train_state,
            )

            self.state, self._param_shardings = shard_train_state(
                self.state, mesh, self.tx, fsdp=fsdp
            )
            self._put_batch = lambda b: put_batch(b, mesh, seq_sharded=seq_sharded)
        if self._grads_mode:
            # The split steps are plain jits: with mesh-sharded inputs GSPMD
            # partitions them like the fused sharded step for replicated-dp
            # layouts (tp/pp rules propagate from the input shardings). The
            # fsdp layout needs the fused step's in-step constraints and is
            # rejected above.
            self._grad_fn = make_grad_step(bundle.loss_fn, accum_steps=accum_steps)
            self._apply_fn = make_apply_step(self.tx)
            self._step_fn = None
        elif mesh is not None:
            from distributedvolunteercomputing_tpu.parallel.train_step import (
                make_sharded_train_step,
            )

            self._step_fn = make_sharded_train_step(
                bundle.loss_fn, self.tx, mesh, accum_steps=accum_steps,
                seq_sharded_batch=seq_sharded, fsdp=fsdp, sp_impl=sp_impl,
            )
        else:
            self._step_fn = make_train_step(
                bundle.loss_fn, self.tx, accum_steps=accum_steps
            )
        self.steps_per_call = int(steps_per_call)
        self.chunk_cadences = tuple(int(c) for c in chunk_cadences if c)
        # EMA of seconds per step, measured at chunk granularity — only
        # maintained (and only needed) under the wall-clock averaging
        # cadence, where chunk sizing must anticipate the next boundary.
        self._ema_step_s: Optional[float] = None
        self._multi_fn = None
        if self.steps_per_call > 1 and self._step_fn is not None:
            if mesh is not None:
                # The mesh twin scans the SAME sharded body (incl. the
                # ZeRO in-step re-constraints) — r4 VERDICT missing #5.
                from distributedvolunteercomputing_tpu.parallel.train_step import (
                    make_sharded_multi_step,
                )

                self._multi_fn = make_sharded_multi_step(
                    bundle.loss_fn, self.tx, mesh, accum_steps=accum_steps,
                    seq_sharded_batch=seq_sharded, fsdp=fsdp, sp_impl=sp_impl,
                )
            else:
                from distributedvolunteercomputing_tpu.training.steps import make_multi_step

                self._multi_fn = make_multi_step(
                    bundle.loss_fn, self.tx, accum_steps=accum_steps
                )
        self._data_rng = data_rng
        self._data = data
        self.eval_every = eval_every
        self.eval_batches = eval_batches
        self._eval_fn = None
        self._it: Optional[Any] = None
        self._eval_data = eval_data
        self._eval_it: Optional[Any] = None
        # Held-out stream: a distinct fold of the volunteer seed, so eval
        # batches never collide with any training batch at any seed.
        self._eval_rng = jax.random.fold_in(data_rng, 0x5EED)
        self.metrics = MetricsWriter(metrics_path, volunteer_id)
        self.on_step = on_step
        # Host-side (step, params) snapshot for concurrent readers (the
        # state-sync provider serves fetches from the asyncio thread while
        # the train step DONATES the live state's buffers — reading
        # self.state.params cross-thread would hit deleted arrays). Updated
        # at safe points only; tuple assignment keeps readers consistent.
        self._snapshot: Any = None
        # Bumped on every out-of-band params mutation (averaging merge,
        # peer-pull adoption). Lets the checkpoint layer tell whether state
        # at the SAME step number still matches its last snapshot — the step
        # counter alone can't (the end-of-run overlap drain merges without
        # advancing it).
        self.mutation_counter = 0
        self._take_snapshot(0)

    def adopt_params(self, params: Any, step: Optional[int] = None) -> None:
        """Replace params (and optionally the step counter) in place — the
        peer-pull state sync path. The optimizer state is NOT reset: at
        adoption time it is either cold-init (fresh process) or the restored
        moments, and averaging rounds re-sync it functionally either way."""
        import jax.numpy as jnp

        self.state = TrainState(
            params=jax.device_put(params, self._param_shardings)
            if self._param_shardings is not None
            else jax.device_put(params),
            opt_state=self.state.opt_state,
            step=self.state.step if step is None else jnp.asarray(step, jnp.int32),
            rng=self.state.rng,
        )
        self.mutation_counter += 1
        # A state-sync adoption invalidates the outer momentum stream: the
        # new params did not come from this trainer's anchor, so the next
        # round re-seeds (first-round semantics in _outer_transform).
        self._outer_anchor = None
        self._outer_m = None
        self._take_snapshot(int(self.state.step))

    @staticmethod
    def _host_tree(tree: Any) -> Any:
        """Gather a pytree to host with every leaf's device-to-host DMA
        ISSUED UP FRONT (``copy_to_host_async``) before any blocking
        ``np.asarray``: the transfers run in parallel with each other AND
        with still-dispatching device compute, so the trainer thread waits
        ~max(leaf DMA) instead of the sum of sequential synchronous pulls.
        This is what lets the averaging launch overlap the contribution's
        D2H with the train step's tail instead of stalling on it."""
        for leaf in jax.tree_util.tree_leaves(tree):
            fn = getattr(leaf, "copy_to_host_async", None)
            if fn is not None:
                try:
                    fn()
                except Exception:  # noqa: BLE001 — async copy is an optimization
                    break
        return jax.tree_util.tree_map(np.asarray, tree)

    def _take_snapshot(self, step_no: int) -> None:
        """D2H copy of params at a point where the buffers are live (between
        steps, on the trainer thread). One copy per averaging interval."""
        self._snapshot = (
            step_no,
            self._host_tree(self.state.params),
        )

    def host_snapshot(self):
        """(step, host params pytree) — safe to read from any thread."""
        return self._snapshot

    def data_iter(self) -> Iterable[Batch]:
        rng = self._data_rng
        while True:
            rng, k = jax.random.split(rng)
            yield self.bundle.make_batch(k, self.batch_size)

    def _swap_params(self, new_params: Any, step_no: int) -> None:
        """Replace params on device, keep opt_state/step/rng, refresh the
        cross-thread snapshot. The ONE place a merge becomes live state —
        the overlap and blocking paths must not diverge here."""
        self.state = TrainState(
            params=jax.device_put(new_params, self._param_shardings)
            if self._param_shardings is not None
            else jax.device_put(new_params),
            opt_state=self.state.opt_state,
            step=self.state.step,
            rng=self.state.rng,
        )
        self.mutation_counter += 1
        self._take_snapshot(step_no)

    def evaluate(self, n_batches: Optional[int] = None) -> float:
        """Mean held-out loss over ``n_batches`` without updating params.
        Safe between steps (the jitted step donates buffers DURING a step,
        but params are live again once it returns)."""
        if self._eval_fn is None:
            from distributedvolunteercomputing_tpu.training.steps import make_eval_step

            self._eval_fn = make_eval_step(self.bundle.loss_fn)
        n = self.eval_batches if n_batches is None else n_batches
        if n < 1:
            raise ValueError(f"evaluate() needs n_batches >= 1, got {n}")
        rng = self._eval_rng
        total = 0.0
        done = 0
        for _ in range(n):
            if self._eval_data is not None:
                # Dedicated held-out stream (independently shuffled over the
                # same dataset): training batch order is untouched by eval.
                if self._eval_it is None:
                    self._eval_it = iter(self._eval_data)
                try:
                    batch = next(self._eval_it)
                except StopIteration:
                    break  # finite eval set exhausted
            elif self._data is not None:
                if self._it is None:  # standalone use before run()
                    self._it = iter(self._data)
                try:
                    batch = next(self._it)
                except StopIteration:
                    # Finite dataset exhausted: evaluate on what we got
                    # rather than killing a training run that was sized
                    # without eval's extra draws in mind.
                    break
            else:
                rng, k = jax.random.split(rng)
                batch = self.bundle.make_batch(k, self.batch_size)
            if self._put_batch is not None:
                batch = self._put_batch(batch)
            rng, ek = jax.random.split(rng)
            # Honor accum_steps: training fits memory by microbatching
            # inside the compiled step, so eval must not allocate the
            # whole-batch activation footprint in one forward.
            if self.accum_steps > 1:
                micro = jax.tree_util.tree_map(
                    lambda x: x.reshape(
                        (self.accum_steps, x.shape[0] // self.accum_steps) + x.shape[1:]
                    ),
                    batch,
                )
                losses = []
                for i in range(self.accum_steps):
                    mb = jax.tree_util.tree_map(lambda x: x[i], micro)
                    rng, mk = jax.random.split(rng)
                    losses.append(float(self._eval_fn(self.state.params, mb, mk)["loss"]))
                total += sum(losses) / len(losses)
            else:
                total += float(self._eval_fn(self.state.params, batch, ek)["loss"])
            done += 1
        self._eval_rng = rng
        return total / done if done else float("nan")

    def _outer_transform(self, averaged: Any) -> Any:
        """Apply the outer optimizer to one round's aggregate (payload
        space, host numpy). Plain averaging when disabled.

        Nesterov over the round delta: with anchor a (the global params this
        inner phase started from) and the round's average v,
            g  = a - v                    (aggregate outer gradient)
            m  = mu * m + g
            a' = a - lr * (mu * m + g)    (lookahead step)
        a' becomes the next anchor. lr=1, mu=0 reduces exactly to a' = v.
        The first successful round (or the first after a state-sync
        adoption reset) has no anchor — it adopts the plain average and
        seeds the anchor there."""
        if self.outer_optimizer == "none":
            return averaged
        if self._outer_anchor is None or jax.tree_util.tree_structure(
            self._outer_anchor
        ) != jax.tree_util.tree_structure(averaged):
            self._outer_anchor = jax.tree_util.tree_map(
                lambda v: np.asarray(v, np.float32).copy(), averaged
            )
            self._outer_m = jax.tree_util.tree_map(np.zeros_like, self._outer_anchor)
            return averaged
        lr, mu = self.outer_lr, self.outer_momentum
        grad = jax.tree_util.tree_map(
            lambda a, v: a - np.asarray(v, np.float32), self._outer_anchor, averaged
        )
        self._outer_m = jax.tree_util.tree_map(
            lambda m, g: mu * m + g, self._outer_m, grad
        )
        self._outer_anchor = jax.tree_util.tree_map(
            lambda a, m, g: a - lr * (mu * m + g), self._outer_anchor, self._outer_m, grad
        )
        return self._outer_anchor

    def _avg_due(self, step_no: int) -> bool:
        """Is a params-mode averaging round due at this step?

        Step cadence (the default): every ``average_every`` steps. Wall-clock
        cadence (``average_interval_s > 0``): when wall time crosses a
        multiple of the interval — boundaries are ABSOLUTE (``n * T``) on
        the swarm-consensus clock (``wall_clock``; the volunteer supplies
        ClockSync's corrected clock, so skewed volunteers still fire within
        ms of their peers), which is what makes heterogeneous swarms
        rendezvous without parking the fast peer.
        Advances the armed boundary exactly once per crossing (a slow step
        that skips past several boundaries still yields one round)."""
        if self.average_interval_s > 0:
            now = self._wall_clock()
            if self._next_avg_t is None:
                # First call arms the NEXT boundary: a joining volunteer's
                # first round aligns with the swarm's next window instead of
                # firing solo mid-window.
                self._arm_next_boundary(now)
                return False
            if now >= self._next_avg_t:
                self._arm_next_boundary(now)
                return True
            return False
        return step_no % self.average_every == 0

    def _arm_next_boundary(self, now: float) -> None:
        self._next_avg_t = (
            int(now // self.average_interval_s) + 1
        ) * self.average_interval_s

    def _chunk_len(self, next_step: int, remaining: int, log_every: int) -> int:
        """Steps the scan prefix + final per-step iteration may cover from
        ``next_step`` without straddling a cadence boundary — every
        metrics/eval/averaging/snapshot action happens on the chunk's LAST
        step, so a chunk must END at the first boundary it meets."""
        n = min(self.steps_per_call, remaining)
        cadences = [
            self.eval_every,
            self.average_every if self.averager else 0,
            log_every,
            *self.chunk_cadences,
        ]
        for c in cadences:
            if c:
                n = min(n, c - ((next_step - 1) % c))
        if self.averager is not None and self.average_interval_s > 0:
            # Wall-clock boundaries can't be mapped to a step count without
            # a step-time estimate; size the chunk to END just past the next
            # boundary (EMA maintained by the fast path, which syncs once
            # per chunk in this mode). Until the EMA exists, tiny chunks
            # bootstrap it — due-poll latency is then ~one step once
            # settled, not steps_per_call steps.
            if self._ema_step_s is None:
                n = min(n, 2)
            elif self._next_avg_t is not None:
                until = max(self._next_avg_t - self._wall_clock(), 0.0)
                n = min(n, max(1, int(until / self._ema_step_s) + 1))
        return max(1, n)

    def _record_target_crossed(
        self, cross_step: int, target_loss: float, t_start: float,
        wall_override: Optional[float] = None,
    ) -> Tuple[int, float]:
        """Log + record the first target crossing; shared by the per-step
        path and the scan-prefix path so the two can't diverge.

        ``wall_override``: the scan-prefix path detects a crossing only
        after its whole chunk completes, so it interpolates the crossing
        time from the chunk's per-step rate instead of charging the metric
        with up to a chunk of post-crossing steps (r4 advisor) — keeping
        time-to-target comparable with the per-step path."""
        wall = wall_override if wall_override is not None else time.monotonic() - t_start
        log.info(
            "target loss %.4f reached at step %d (%.1fs)",
            target_loss, cross_step, wall,
        )
        self.metrics.record_event(
            cross_step, "target_crossed",
            {"target_loss": target_loss, "wall_s": round(wall, 3)},
        )
        return (cross_step, wall)

    def _note_window_progress(self, step_no: int) -> None:
        """Record the local steps behind the contribution about to launch —
        the single source the volunteer's weight callback reads, shared by
        the blocking and overlap paths so they can't diverge."""
        if self._last_merge_step is not None:
            self.steps_since_merge = max(1, step_no - self._last_merge_step)

    def _run_average_round(self, tree: Any, step_no: int, what: str) -> Optional[Any]:
        """One WAN round: select payload -> averager -> record -> merge.
        Returns the merged tree, or None when no group formed / round failed.

        The payload crosses to HOST first — the AveragerFn contract is host
        numpy (the overlap path already guarantees it; for a mesh-sharded
        state this is also the gather from the slice's shards). D2H DMAs
        issue up front and drain in parallel (_host_tree)."""
        payload = self._host_tree(self.bundle.avg_select(tree))
        if what == "params":
            self._note_window_progress(step_no)
        t_avg = time.monotonic()
        averaged = self.averager(payload, step_no)
        self.metrics.record_event(
            step_no, "avg_round",
            {"avg_s": time.monotonic() - t_avg, "ok": averaged is not None, "what": what},
        )
        if averaged is None:
            return None
        if what == "params":
            averaged = self._outer_transform(averaged)
        return self.bundle.avg_merge(tree, jax.tree_util.tree_map(np.asarray, averaged))

    # -- overlapped averaging (params mode) --------------------------------

    def _launch_overlap_round(self, step_no: int) -> None:
        """Snapshot the payload to HOST and launch the round on the pool.

        The host copy is load-bearing: the jitted step donates the live
        params' buffers, so the pool thread must never touch device arrays
        the train thread is about to consume. It stays on THIS thread for
        the same reason, but its D2H DMAs issue up front (_host_tree): the
        copies overlap the boundary step's still-dispatching tail, and the
        round then streams on the pool while the next step runs — the
        device never idles for the contribution transfer."""
        payload0 = self._host_tree(self.bundle.avg_select(self.state.params))
        self._note_window_progress(step_no)
        t0 = time.monotonic()
        fut = self._avg_pool.submit(
            lambda: (self.averager(payload0, step_no), time.monotonic() - t0)
        )
        self._inflight = (step_no, payload0, fut)

    def _finish_overlap_round(self, step_no: int, wait: bool = False) -> None:
        """Merge a completed round: new = averaged + (current - snapshot).

        The delta correction keeps the steps taken while the round was in
        flight; the contraction toward the group average still happens on
        the snapshot term (Moshpit-style delayed parameter averaging)."""
        if self._inflight is None:
            return
        launch_step, payload0, fut = self._inflight
        if not wait and not fut.done():
            return
        self._inflight = None
        try:
            # The averager callback carries its own network timeouts; the
            # margin here only guards against a wedged callback at exit.
            averaged, avg_s = fut.result(timeout=600.0 if wait else 0.0)
        except Exception as e:  # noqa: BLE001 — a failed round never kills training
            log.warning("overlapped averaging launched at step %d failed: %s", launch_step, errstr(e))
            self.metrics.record_event(
                step_no, "avg_round", {"ok": False, "what": "params", "overlap": True}
            )
            return
        staleness = step_no - launch_step
        ok = averaged is not None
        if ok and self.max_staleness and staleness > self.max_staleness:
            log.warning(
                "dropping averaging result: staleness %d > bound %d", staleness, self.max_staleness
            )
            ok = False
        self.metrics.record_event(
            step_no, "avg_round",
            {"avg_s": avg_s, "ok": ok, "what": "params", "overlap": True,
             "staleness": staleness},
        )
        if not ok:
            return
        # Outer step first, local-progress delta on top: the contraction
        # toward (outer-updated) consensus happens on the snapshot term,
        # the steps taken while the round was in flight are preserved.
        averaged = self._outer_transform(averaged)
        current = self._host_tree(self.bundle.avg_select(self.state.params))
        merged_payload = jax.tree_util.tree_map(
            lambda avg, cur, p0: np.asarray(avg, np.float32) + (cur - p0),
            averaged, current, payload0,
        )
        self._swap_params(self.bundle.avg_merge(self.state.params, merged_payload), step_no)
        # Progress up to the LAUNCH step entered the average (the delta term
        # above preserved the rest locally).
        self._last_merge_step = launch_step

    def run(
        self,
        steps: int,
        target_loss: Optional[float] = None,
        target_mode: str = "stop",
        log_every: int = 50,
        stop_flag: Optional[Callable[[], bool]] = None,
    ) -> Dict[str, float]:
        """Train for ``steps``; returns summary.

        ``target_loss`` with ``target_mode="stop"`` ends the run at the
        first crossing (config-1 semantics); with ``"record"`` the run keeps
        going for the full ``steps`` and the summary reports WHEN the target
        was first crossed (``target_crossed_step`` / ``target_crossed_s``) —
        the time-to-target-loss half of the driver metric (BASELINE.json:2)
        measured without giving up the fixed-steps throughput row."""
        if target_mode not in ("stop", "record"):
            raise ValueError(f"unknown target_mode {target_mode!r}")
        it = iter(self._data) if self._data is not None else iter(self.data_iter())
        self._it = it  # evaluate() draws from the same iterator for custom data
        # Tracing hook (SURVEY.md §5): DVC_PROFILE_DIR=<dir> captures a
        # jax.profiler trace of steps [DVC_PROFILE_START, +DVC_PROFILE_STEPS)
        # — past warmup/compile, so the trace shows steady-state step time
        # and the compute-vs-averaging split. View with tensorboard/xprof.
        profile_dir = os.environ.get("DVC_PROFILE_DIR")
        profile_start = int(os.environ.get("DVC_PROFILE_START", "10"))
        profile_steps = int(os.environ.get("DVC_PROFILE_STEPS", "10"))
        profiling = False
        # Grads mode averages every step; after a FAILED round (no group —
        # e.g. the only partner died) skip averaging for average_every steps
        # instead of paying a full matchmaking timeout per step.
        avg_skip_until = 0
        # Materialising metrics forces a host<->device sync that breaks JAX's
        # async dispatch pipelining — only pay for it when something consumes
        # the value (target check, JSONL record, or a log line).
        sync_every_step = target_loss is not None or self.metrics.has_sink
        m = None
        last_loss = float("nan")
        start_step = int(self.state.step)
        if self._last_merge_step is None:
            self._last_merge_step = start_step
        t_start = time.monotonic()
        ran_steps = 0
        target_crossed: Optional[Tuple[int, float]] = None  # (step, wall_s)
        for i in range(steps):
            if ran_steps >= steps:
                break  # scan prefixes below may consume several steps per iteration
            if stop_flag is not None and stop_flag():
                log.info("stop flag set; exiting train loop at step %d", int(self.state.step))
                break
            # Multi-step fast path (steps_per_call > 1): run the first n-1
            # steps of this chunk inside ONE compiled scan, then fall
            # through to the ordinary per-step path for the chunk's final
            # step — so metrics records, eval, averaging rounds, and
            # snapshots all keep their exact cadence semantics (chunks end
            # at every boundary, enforced by _chunk_len). Disabled while
            # profiling (the trace hooks are per-step).
            if self._multi_fn is not None and not profile_dir:
                n = self._chunk_len(start_step + ran_steps + 1, steps - ran_steps, log_every)
                if n > 1:
                    prefix = [next(it) for _ in range(n - 1)]
                    stacked = jax.tree_util.tree_map(
                        lambda *xs: jnp.stack(xs), *prefix
                    )
                    t_chunk = time.perf_counter()
                    self.state, losses = self._multi_fn(self.state, stacked)
                    ran_steps += n - 1
                    if self.averager is not None and self.average_interval_s > 0:
                        # One sync per chunk: the real chunk duration feeds
                        # the EMA that sizes chunks around wall boundaries
                        # (_chunk_len). Negligible next to the n-1 steps.
                        float(losses[-1])
                        per_step = (time.perf_counter() - t_chunk) / (n - 1)
                        self._ema_step_s = (
                            per_step
                            if self._ema_step_s is None
                            else 0.5 * self._ema_step_s + 0.5 * per_step
                        )
                    if sync_every_step:
                        host_losses = np.asarray(losses)
                        for k, lv in enumerate(host_losses):
                            self.metrics.record(
                                start_step + ran_steps - (n - 1) + k + 1,
                                {"loss": float(lv)},
                                n_samples=self.batch_size,
                            )
                        last_loss = float(host_losses[-1])
                        if target_loss is not None and target_crossed is None:
                            hit = np.nonzero(host_losses <= target_loss)[0]
                            if hit.size:
                                cross_step = (
                                    start_step + ran_steps - (n - 1) + int(hit[0]) + 1
                                )
                                # Back out the steps that ran AFTER the
                                # crossing at this chunk's per-step rate.
                                per_step = (time.perf_counter() - t_chunk) / (n - 1)
                                wall_est = (
                                    time.monotonic() - t_start
                                    - (n - 2 - int(hit[0])) * per_step
                                )
                                target_crossed = self._record_target_crossed(
                                    cross_step, target_loss, t_start,
                                    wall_override=wall_est,
                                )
                                if target_mode == "stop":
                                    # The end-of-run sync reads m; point it
                                    # at THIS chunk's last loss, not the
                                    # previous chunk's stale metrics.
                                    m = {"loss": host_losses[-1]}
                                    break
                    else:
                        self.metrics.count_samples(self.batch_size * (n - 1))
            batch = next(it)
            if self._put_batch is not None:
                batch = self._put_batch(batch)
            step_no = start_step + ran_steps + 1
            if profile_dir and not profiling and i == profile_start:
                jax.profiler.start_trace(profile_dir)
                profiling = True
            if self._grads_mode:
                # GradientAverager semantics are PER-STEP: every local
                # gradient is averaged before any optimizer sees it (skipping
                # steps would let replica params drift with nothing ever
                # re-contracting them — that's what params mode is for).
                grads, m, next_rng = self._grad_fn(self.state, batch)
                if step_no >= avg_skip_until:
                    merged = self._run_average_round(grads, step_no, "grads")
                    if merged is not None:
                        grads = merged
                    else:
                        avg_skip_until = step_no + self.average_every
                self.state = self._apply_fn(self.state, grads, next_rng)
                if step_no % self.average_every == 0:
                    self._take_snapshot(step_no)
            else:
                self.state, m = self._step_fn(self.state, batch)
            ran_steps += 1
            at_log_point = bool(log_every) and step_no % log_every == 0
            if sync_every_step or at_log_point:
                last_loss = float(m["loss"])
                self.metrics.record(step_no, m, n_samples=self.batch_size)
            else:
                self.metrics.count_samples(self.batch_size)

            if self.eval_every and step_no % self.eval_every == 0:
                ev = self.evaluate()
                if ev == ev:  # nan = finite dataset exhausted; nothing to record
                    self.metrics.record_event(
                        step_no, "eval",
                        {"eval_loss": ev, "n_batches": self.eval_batches},
                    )
                    log.info("step %d eval_loss %.4f", step_no, ev)

            if self.averager is not None and not self._grads_mode:
                if self.overlap:
                    # Merge any round that completed since the last step,
                    # then (at the cadence, with no round in flight) launch
                    # the next one — the device keeps stepping either way.
                    self._finish_overlap_round(step_no)
                    if self._avg_due(step_no):
                        if self._inflight is None:
                            self._launch_overlap_round(step_no)
                        # Refresh the cross-thread snapshot at the cadence
                        # even when no merge landed (failed/skipped rounds):
                        # state-sync must serve CURRENT weights, not the
                        # last merge — a rejoiner pulling a stale snapshot
                        # would bootstrap thousands of steps behind.
                        self._take_snapshot(step_no)
                elif self._avg_due(step_no):
                    merged = self._run_average_round(self.state.params, step_no, "params")
                    if merged is not None:
                        self._swap_params(merged, step_no)
                        self._last_merge_step = step_no
                    else:
                        # Snapshot at the cadence regardless of round outcome
                        # (see overlap branch).
                        self._take_snapshot(step_no)
                if self.average_interval_s > 0 and step_no % self.average_every == 0:
                    # Under the wall-clock cadence, rounds can be a full
                    # interval apart — far longer than average_every steps.
                    # Keep the state-sync snapshot fresh on the STEP cadence
                    # regardless, or a rejoiner pulls a window-old state
                    # (the hazard the comment above describes).
                    self._take_snapshot(step_no)

            if profiling and i + 1 >= profile_start + profile_steps:
                jax.block_until_ready(m["loss"])
                jax.profiler.stop_trace()
                profiling = False
                log.info("profiler trace written to %s", profile_dir)

            if self.on_step is not None:
                self.on_step(self, step_no)

            if at_log_point:
                log.info(
                    "step %d loss %.4f (%.1f samples/s)",
                    step_no,
                    last_loss,
                    self.metrics.samples_per_sec(),
                )
            if target_loss is not None and last_loss <= target_loss:
                if target_crossed is None:
                    target_crossed = self._record_target_crossed(
                        step_no, target_loss, t_start
                    )
                if target_mode == "stop":
                    break
        if profiling:  # loop ended inside the trace window
            jax.profiler.stop_trace()
        # Drain an in-flight round so the returned params are contracted and
        # a partner mid-round isn't abandoned by our exit.
        if self.overlap:
            self._finish_overlap_round(start_step + ran_steps, wait=True)
        if m is not None:
            last_loss = float(m["loss"])  # sync once at the end regardless
        wall = time.monotonic() - t_start
        summary = {
            "final_loss": last_loss,
            "steps": int(self.state.step),
            "wall_time_s": wall,
            "samples_per_sec": ran_steps * self.batch_size / wall if wall > 0 else 0.0,
        }
        if target_loss is not None:
            summary["target_loss"] = target_loss
            summary["target_crossed_step"] = target_crossed[0] if target_crossed else None
            summary["target_crossed_s"] = (
                round(target_crossed[1], 3) if target_crossed else None
            )
        return summary
