from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step
from distributedvolunteercomputing_tpu.training.optim import make_optimizer

__all__ = ["TrainState", "make_train_step", "make_optimizer"]
