"""Synthetic datasets shaped like the five reference workloads.

The sandbox has zero egress, so MNIST/CIFAR/corpora cannot be downloaded.
These generators produce LEARNABLE tasks with the right tensor shapes:

- images: class-conditional Gaussian blobs (fixed per-class prototypes), so a
  classifier provably drives loss well below chance — used by the convergence
  smoke tests (SURVEY.md §4).
- LM: each token has 4 "likely" successors given by fixed affine hash maps
  (mixture: 90% one of the 4, 10% uniform), so next-token prediction has low
  achievable entropy (~log 4 + 0.1 log V vs. chance log V). Generation is
  elementwise over the batch — O(B*T) memory at ANY vocab size. (An earlier
  design used a dense [V, V] bigram table: 10.1 GB f32 at V=50257, which
  OOMed the 16 GB bench chip from inside make_batch regardless of batch
  size — the actual cause of BENCH_r01/r02's failures.)

Real-data loading is a thin swap: anything yielding the same dict-of-arrays
batches works (see training.trainer.Trainer).
"""

from __future__ import annotations

from typing import Dict, Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_PROTO_SEED = 1234  # class prototypes are global constants of the task


def _image_prototypes(shape: Tuple[int, ...], n_classes: int) -> jax.Array:
    rng = jax.random.PRNGKey(_PROTO_SEED)
    return jax.random.normal(rng, (n_classes,) + shape, jnp.float32)


def synthetic_image_batch(
    rng: jax.Array, batch_size: int, shape: Tuple[int, ...], n_classes: int, noise: float = 0.3
) -> Dict[str, jax.Array]:
    ky, kn = jax.random.split(rng)
    y = jax.random.randint(ky, (batch_size,), 0, n_classes)
    protos = _image_prototypes(shape, n_classes)
    x = protos[y] + noise * jax.random.normal(kn, (batch_size,) + shape, jnp.float32)
    return {"x": x, "y": y}


# The 4 successor maps: next = (tok * mult + off) % vocab. Odd multipliers so
# the maps are bijections for even vocab sizes; offsets spread the images.
_SUCC_MULT = (3, 5, 7, 11)
_SUCC_OFF = (13, 101, 997, 4099)
_LIKELY_P = 0.9  # P(successor drawn from the 4 likely maps vs. uniform)


def synthetic_token_stream(rng: jax.Array, batch_size: int, seq_len: int, vocab: int) -> jax.Array:
    mult = jnp.asarray(_SUCC_MULT, jnp.int32)
    off = jnp.asarray([o % vocab for o in _SUCC_OFF], jnp.int32)
    k0, kseq = jax.random.split(rng)
    first = jax.random.randint(k0, (batch_size,), 0, vocab)

    def step(tok, k):
        kc, ku, kb = jax.random.split(k, 3)
        c = jax.random.randint(kc, tok.shape, 0, len(_SUCC_MULT))
        likely = (tok * mult[c] + off[c]) % vocab
        uniform = jax.random.randint(ku, tok.shape, 0, vocab)
        nxt = jnp.where(jax.random.bernoulli(kb, _LIKELY_P, tok.shape), likely, uniform)
        return nxt, nxt

    keys = jax.random.split(kseq, seq_len - 1)
    _, rest = jax.lax.scan(step, first, keys)
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def synthetic_lm_batch(rng: jax.Array, batch_size: int, seq_len: int, vocab: int) -> Dict[str, jax.Array]:
    """Causal LM batch: predict tokens[1:] from tokens[:-1]."""
    toks = synthetic_token_stream(rng, batch_size, seq_len + 1, vocab)
    return {"tokens": toks[:, :-1], "targets": toks[:, 1:]}


def npz_batch_iter(
    path: str, batch_size: int, seed: int = 0
) -> Iterator[Dict[str, np.ndarray]]:
    """Endless shuffled minibatches from an ``.npz`` of aligned arrays.

    The real-data swap-in (file keys become batch-dict keys, so they must
    match the model's schema: ``x``/``y`` for image models, ``tokens``/
    ``targets`` for LMs, plus ``mask`` for MLM). Each pass reshuffles;
    the trailing partial batch is dropped (jit caches per batch shape —
    a ragged final batch would force a recompile every epoch).
    """
    data = {k: np.asarray(v) for k, v in np.load(path).items()}
    if not data:
        raise ValueError(f"{path}: empty npz")
    n = len(next(iter(data.values())))
    for k, v in data.items():
        if len(v) != n:
            raise ValueError(f"{path}: key {k!r} has {len(v)} rows, expected {n}")
    if n < batch_size:
        raise ValueError(f"{path}: {n} examples < batch_size {batch_size}")

    def gen() -> Iterator[Dict[str, np.ndarray]]:
        rng = np.random.default_rng(seed)
        while True:
            idx = rng.permutation(n)
            for s in range(0, n - batch_size + 1, batch_size):
                sel = idx[s : s + batch_size]
                yield {k: v[sel] for k, v in data.items()}

    return gen()


def synthetic_mlm_batch(
    rng: jax.Array, batch_size: int, seq_len: int, vocab: int, mask_id: int, mask_rate: float = 0.15
) -> Dict[str, jax.Array]:
    """BERT-style MLM batch: 15% of positions replaced by [MASK], predict originals."""
    kt, km = jax.random.split(rng)
    toks = synthetic_token_stream(kt, batch_size, seq_len, vocab)
    mask = jax.random.bernoulli(km, mask_rate, toks.shape)
    inputs = jnp.where(mask, mask_id, toks)
    return {"tokens": inputs, "targets": toks, "mask": mask.astype(jnp.float32)}
