"""Platform pinning that survives the sandbox's eager jax pre-import.

The sandbox's sitecustomize registers the axon TPU PJRT plugin at interpreter
startup, importing jax and pinning ``jax_platforms`` before any user code
runs — so setting ``JAX_PLATFORMS`` in the environment (or in ``os.environ``
from Python) is silently ignored. The only reliable override is
``jax.config.update("jax_platforms", ...)`` applied before the first backend
init. This helper is the single home for that workaround; bench.py,
__graft_entry__.py, and tests/conftest.py all route through it so a future
sitecustomize change has one place to fix.
"""

from __future__ import annotations

import os
from typing import Optional


def pin_platform(
    platform: Optional[str] = None, min_host_devices: Optional[int] = None
) -> Optional[str]:
    """Pin jax's platform at the config level; optionally guarantee N virtual
    CPU devices.

    ``platform=None`` honors the ``JAX_PLATFORMS`` env var if set (restoring
    its expected semantics), otherwise leaves the platform alone.
    ``min_host_devices`` appends ``--xla_force_host_platform_device_count`` to
    ``XLA_FLAGS`` when absent — effective only if called before the first
    backend init. Returns the platform pinned, or None if untouched.
    """
    if min_host_devices is not None:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m is None:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={min_host_devices}"
            )
        elif int(m.group(1)) < min_host_devices:
            # A smaller existing count wouldn't give the promised minimum;
            # raise it (effective only before the first backend init).
            os.environ["XLA_FLAGS"] = (
                flags[: m.start()]
                + f"--xla_force_host_platform_device_count={min_host_devices}"
                + flags[m.end() :]
            )

    want = platform if platform is not None else os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)
    return want or None


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at a durable directory.

    Volunteer churn is the framework's normal operating mode (SURVEY.md §1
    L3): every rejoin re-traces and re-compiles the train step, a 20-40s
    stall on the TPU chip before the volunteer contributes again. The
    persistent cache turns every rejoin after the first into a disk hit.
    Resolution order: explicit arg > ``DVC_COMPILE_CACHE`` env (empty string
    disables) > ``~/.cache/dvc_jax_cache``. Safe to call repeatedly; returns
    the directory enabled, or None when disabled/unavailable.

    TPU-only: XLA:CPU persists AOT results whose machine-feature stamp can
    fail at load (observed in-repo: `cpu_aot_loader` feature-mismatch spam +
    SIGILL warnings that broke a swarm e2e when the cache was enabled
    unconditionally), and CPU compiles are fast enough not to need a cache.
    The 20-40s compiles this exists for are the TPU ones."""
    if path is None:
        path = os.environ.get("DVC_COMPILE_CACHE")
        if path == "":
            return None
        if path is None:
            path = os.path.expanduser("~/.cache/dvc_jax_cache")
    try:
        if not tpu_backend():
            return None
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # Cache every program: the default 1s floor would skip the small
        # steps proxies/tests compile most often, and disk here is cheap.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        return path
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        return None


def tpu_backend() -> bool:
    """True when the default backend is TPU silicon — including the sandbox's
    "axon" PJRT plugin (a real TPU chip behind a tunnel, platform-named axon).
    The single source of truth for is-this-a-TPU decisions (bf16 compute
    dtype, pallas kernel routing): checking ``== "tpu"`` alone silently
    degrades the axon chip to the non-TPU code paths."""
    import jax

    return jax.default_backend() in ("tpu", "axon")
