"""Platform pinning that survives the sandbox's eager jax pre-import.

The sandbox's sitecustomize registers the axon TPU PJRT plugin at interpreter
startup, importing jax and pinning ``jax_platforms`` before any user code
runs — so setting ``JAX_PLATFORMS`` in the environment (or in ``os.environ``
from Python) is silently ignored. The only reliable override is
``jax.config.update("jax_platforms", ...)`` applied before the first backend
init. This helper is the single home for that workaround; bench.py,
__graft_entry__.py, and tests/conftest.py all route through it so a future
sitecustomize change has one place to fix.
"""

from __future__ import annotations

import os
from typing import Optional


def pin_platform(
    platform: Optional[str] = None, min_host_devices: Optional[int] = None
) -> Optional[str]:
    """Pin jax's platform at the config level; optionally guarantee N virtual
    CPU devices.

    ``platform=None`` honors the ``JAX_PLATFORMS`` env var if set (restoring
    its expected semantics), otherwise leaves the platform alone.
    ``min_host_devices`` appends ``--xla_force_host_platform_device_count`` to
    ``XLA_FLAGS`` when absent — effective only if called before the first
    backend init. Returns the platform pinned, or None if untouched.
    """
    if min_host_devices is not None:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m is None:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={min_host_devices}"
            )
        elif int(m.group(1)) < min_host_devices:
            # A smaller existing count wouldn't give the promised minimum;
            # raise it (effective only before the first backend init).
            os.environ["XLA_FLAGS"] = (
                flags[: m.start()]
                + f"--xla_force_host_platform_device_count={min_host_devices}"
                + flags[m.end() :]
            )

    want = platform if platform is not None else os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)
    return want or None


def tpu_backend() -> bool:
    """True when the default backend is TPU silicon — including the sandbox's
    "axon" PJRT plugin (a real TPU chip behind a tunnel, platform-named axon).
    The single source of truth for is-this-a-TPU decisions (bf16 compute
    dtype, pallas kernel routing): checking ``== "tpu"`` alone silently
    degrades the axon chip to the non-TPU code paths."""
    import jax

    return jax.default_backend() in ("tpu", "axon")
