from distributedvolunteercomputing_tpu.utils.pytree import (
    TensorSpec,
    flatten_to_buffer,
    unflatten_from_buffer,
    tree_size_bytes,
    tree_zeros_like,
)
from distributedvolunteercomputing_tpu.utils.logging import get_logger

__all__ = [
    "TensorSpec",
    "flatten_to_buffer",
    "unflatten_from_buffer",
    "tree_size_bytes",
    "tree_zeros_like",
    "get_logger",
]
