"""Pytree <-> contiguous-buffer conversion for WAN tensor exchange.

The reference's GradientAverager hands NCCL/gloo a list of torch tensors
(BASELINE.json:5). The TPU-native equivalent moves a whole param/grad pytree
across DCN as ONE contiguous host buffer: a single allocation, chunkable,
checksummable, and cheap to average in-place with numpy on the host.

All averaging math on the WAN path happens on host in float32 regardless of
the on-device dtype (bf16 params would lose precision when averaged over many
peers).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence, Tuple

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """Shape/dtype of one leaf inside a flattened buffer."""

    shape: Tuple[int, ...]
    dtype: str  # numpy dtype name of the ORIGINAL leaf (restored on unflatten)

    @property
    def size(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n


def tree_specs(tree: Any) -> Tuple[List[TensorSpec], Any]:
    """(specs, treedef) of a pytree WITHOUT materializing the flat buffer —
    for callers that only need the schema (e.g. validating an incoming
    buffer's length before adopting it)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    specs = [
        TensorSpec(
            tuple(np.shape(x)),
            str(x.dtype) if hasattr(x, "dtype") else str(np.asarray(x).dtype),
        )
        for x in leaves
    ]
    return specs, treedef


def flatten_to_buffer(tree: Any) -> Tuple[np.ndarray, List[TensorSpec], Any]:
    """Flatten a pytree of arrays into one contiguous float32 host buffer.

    Returns ``(buffer, specs, treedef)``. The buffer is always float32 so host
    averaging across peers is numerically safe; original dtypes are recorded in
    ``specs`` and restored by :func:`unflatten_from_buffer`.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return np.zeros((0,), dtype=np.float32), [], treedef
    host = [np.asarray(x) for x in leaves]
    specs = [TensorSpec(h.shape, str(h.dtype)) for h in host]
    buf = np.concatenate([h.astype(np.float32).ravel() for h in host])
    return buf, specs, treedef


def unflatten_from_buffer(buf: np.ndarray, specs: Sequence[TensorSpec], treedef: Any) -> Any:
    """Inverse of :func:`flatten_to_buffer` (restores shapes and dtypes)."""
    leaves = []
    offset = 0
    for spec in specs:
        n = spec.size
        chunk = buf[offset : offset + n].reshape(spec.shape).astype(spec.dtype)
        leaves.append(chunk)
        offset += n
    if offset != buf.size:
        raise ValueError(f"buffer size {buf.size} != specs total {offset}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def tree_size_bytes(tree: Any) -> int:
    return sum(np.asarray(x).nbytes for x in jax.tree_util.tree_leaves(tree))


def tree_zeros_like(tree: Any) -> Any:
    return jax.tree_util.tree_map(lambda x: np.zeros_like(np.asarray(x)), tree)


def cast_floating(tree: Any, dtype: Any) -> Any:
    """Cast every FLOATING leaf to ``dtype``, leaving integer tables, bools,
    and step counters untouched — the one bf16-training cast shared by the
    Trainer's param_dtype, the bench's DVC_BENCH_PARAM_DTYPE arm, and
    checkpoint restore (which must re-apply a configured dtype over a
    snapshot taken under another one)."""
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    return jax.tree_util.tree_map(
        lambda x: x.astype(dt) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree,
    )
