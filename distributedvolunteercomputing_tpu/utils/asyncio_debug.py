"""Asyncio-level race/stall detection (SURVEY.md §5 "race detection").

The swarm tier is one event loop per process running DHT RPCs, heartbeats,
averaging rounds, and state serving concurrently. The failure mode that
breaks it is not a data race (single-threaded loop) but a BLOCKED LOOP: a
handler doing param-sized numpy work (or a cross-thread call sneaking a
synchronous device transfer in) freezes every timer, so heartbeats miss
their TTL and live peers get evicted as dead — which then looks exactly
like network churn and gets debugged in the wrong layer.

Two complementary detectors:

- ``LoopHealthMonitor`` measures scheduling latency directly: a sentinel
  task sleeps a short interval and records how late it wakes. Catches ANY
  blockage — including native code that asyncio's own debug instrumentation
  can't attribute — and keeps a bounded stall history tests can assert on.
- ``enable_debug`` additionally flips asyncio's built-in debug mode
  (``loop.slow_callback_duration``), which NAMES the offending callback in
  the log — attribution when the monitor says something stalled.

Production entrypoints call ``maybe_enable_from_env()``: set
``DVC_ASYNC_DEBUG=1`` to arm both on a live volunteer/coordinator. The
chaos tests arm the monitor directly and assert on ``stalls``.
"""

from __future__ import annotations

import asyncio
import os
from typing import List, Optional, Tuple

from distributedvolunteercomputing_tpu.utils.logging import get_logger

log = get_logger(__name__)


class LoopHealthMonitor:
    """Sentinel task measuring event-loop scheduling latency.

    ``stalls`` holds (loop_time, lag_seconds) for every wakeup that was more
    than ``stall_threshold`` late — i.e. some callback/coroutine held the
    loop for at least that long. Bounded to the most recent ``max_records``.
    """

    def __init__(
        self,
        interval: float = 0.05,
        stall_threshold: float = 0.25,
        max_records: int = 256,
    ):
        self.interval = interval
        self.stall_threshold = stall_threshold
        self.max_records = max_records
        self.stalls: List[Tuple[float, float]] = []
        self.total_lag: float = 0.0
        self._task: Optional[asyncio.Task] = None

    def start(self) -> "LoopHealthMonitor":
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        last = loop.time()
        while True:
            await asyncio.sleep(self.interval)
            now = loop.time()
            lag = now - last - self.interval
            last = now
            if lag > self.stall_threshold:
                self.total_lag += lag
                self.stalls.append((now, lag))
                del self.stalls[: -self.max_records]
                log.warning(
                    "asyncio loop stalled %.3fs (threshold %.3fs): a handler is "
                    "doing blocking work on the loop — heartbeats/timeouts were "
                    "frozen for the duration",
                    lag,
                    self.stall_threshold,
                )


def enable_debug(
    slow_callback_s: float = 0.2,
    stall_threshold: float = 0.25,
) -> LoopHealthMonitor:
    """Arm both detectors on the RUNNING loop; returns the monitor."""
    loop = asyncio.get_running_loop()
    loop.set_debug(True)
    loop.slow_callback_duration = slow_callback_s
    return LoopHealthMonitor(stall_threshold=stall_threshold).start()


def maybe_enable_from_env() -> Optional[LoopHealthMonitor]:
    """Arm detectors iff DVC_ASYNC_DEBUG is set (entrypoint hook)."""
    if os.environ.get("DVC_ASYNC_DEBUG", "") not in ("", "0"):
        return enable_debug()
    return None
