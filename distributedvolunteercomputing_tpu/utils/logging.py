"""Per-volunteer structured logging.

Swarm-level metric aggregation happens at the coordinator (SURVEY.md §5);
each process logs human-readable lines to stderr by default, or — with
``DVC_LOG_JSON=1`` — machine-readable JSONL carrying the ambient swarm
context (peer id, round key, hierarchy level, zone) so a fleet's stderr
can be shipped to a log store and joined against traces without regex
archaeology. Every swarm module routes through :func:`get_logger`, so the
mode and the context fields apply uniformly.

Context comes from two layers:

- **static fields** (:func:`set_log_fields`): per-process identity —
  peer id, zone — set once at volunteer startup;
- **ambient context** (:func:`log_context`): a contextvar bound around a
  round (round key / trace, level, group) by the averaging tier; it
  follows asyncio tasks the way contextvars do, so concurrent rounds
  don't smear each other's fields.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import sys
from typing import Any, Dict, Iterator

_FORMAT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"

# Process-static fields (peer id, zone, role) merged into every JSONL line.
_STATIC_FIELDS: Dict[str, Any] = {}

# Ambient per-task fields (round_key/trace, level, group) — bound by the
# averaging tier around a round via log_context().
_LOG_CTX: contextvars.ContextVar[Dict[str, Any]] = contextvars.ContextVar(
    "dvc_log_ctx", default={}
)


def set_log_fields(**fields: Any) -> None:
    """Set process-static structured-log fields (e.g. peer=, zone=).
    Only meaningful in JSONL mode; a no-op cost otherwise."""
    for k, v in fields.items():
        if v is None:
            _STATIC_FIELDS.pop(k, None)
        else:
            _STATIC_FIELDS[k] = v


@contextlib.contextmanager
def log_context(**fields: Any) -> Iterator[None]:
    """Bind ambient structured-log fields for the enclosed (async) scope.
    Nested scopes overlay; fields with value None are dropped."""
    cur = dict(_LOG_CTX.get())
    for k, v in fields.items():
        if v is None:
            cur.pop(k, None)
        else:
            cur[k] = v
    token = _LOG_CTX.set(cur)
    try:
        yield
    finally:
        try:
            _LOG_CTX.reset(token)
        except ValueError:
            pass


def current_log_context() -> Dict[str, Any]:
    """The merged static + ambient fields (for tests and custom sinks)."""
    return {**_STATIC_FIELDS, **_LOG_CTX.get()}


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, msg, plus the merged
    static + ambient context fields. Non-serializable context values are
    stringified rather than killing the log call."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info and record.exc_info[1] is not None:
            out["exc"] = errstr(record.exc_info[1])
        core = set(out)
        for k, v in {**_STATIC_FIELDS, **_LOG_CTX.get()}.items():
            # Core record fields win: a context field named "level" must
            # not overwrite the severity (it lands prefixed instead).
            out[f"ctx_{k}" if k in core else k] = v
        try:
            return json.dumps(out, separators=(",", ":"))
        except (TypeError, ValueError):
            return json.dumps(
                {k: str(v) for k, v in out.items()}, separators=(",", ":")
            )


def json_mode_enabled() -> bool:
    return os.environ.get("DVC_LOG_JSON", "") not in ("", "0")


def errstr(e: BaseException) -> str:
    """``TypeName: message`` for log lines.

    Logging the bare exception renders common failures invisibly:
    ``str(asyncio.TimeoutError())`` and ``str(CancelledError())`` are "",
    which produced real ``averaging at step 90 failed: `` lines during the
    round-4 hardware overlap run — the one context (a wedged chip, a timed-
    out round) where the TYPE is the whole diagnosis."""
    msg = str(e)
    name = type(e).__name__
    return f"{name}: {msg}" if msg else name


def _make_formatter() -> logging.Formatter:
    if json_mode_enabled():
        return JsonFormatter()
    return logging.Formatter(_FORMAT, datefmt="%H:%M:%S")


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers and not logging.getLogger().handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_make_formatter())
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("DVC_LOGLEVEL", "INFO").upper())
        logger.propagate = False
    return logger


__all__ = [
    "errstr",
    "get_logger",
    "log_context",
    "set_log_fields",
    "current_log_context",
    "json_mode_enabled",
    "JsonFormatter",
]
