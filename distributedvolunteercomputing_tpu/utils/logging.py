"""Per-volunteer structured logging.

Swarm-level metric aggregation happens at the coordinator (SURVEY.md §5);
each process logs human-readable lines to stderr and machine-readable JSONL
via training.metrics.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"


def errstr(e: BaseException) -> str:
    """``TypeName: message`` for log lines.

    Logging the bare exception renders common failures invisibly:
    ``str(asyncio.TimeoutError())`` and ``str(CancelledError())`` are "",
    which produced real ``averaging at step 90 failed: `` lines during the
    round-4 hardware overlap run — the one context (a wedged chip, a timed-
    out round) where the TYPE is the whole diagnosis."""
    msg = str(e)
    name = type(e).__name__
    return f"{name}: {msg}" if msg else name


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers and not logging.getLogger().handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("DVC_LOGLEVEL", "INFO").upper())
        logger.propagate = False
    return logger
