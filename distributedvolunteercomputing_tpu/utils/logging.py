"""Per-volunteer structured logging.

Swarm-level metric aggregation happens at the coordinator (SURVEY.md §5);
each process logs human-readable lines to stderr and machine-readable JSONL
via training.metrics.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers and not logging.getLogger().handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("DVC_LOGLEVEL", "INFO").upper())
        logger.propagate = False
    return logger
