"""Matchmaking: form an averaging group for one round.

The averaging cohort problem (SURVEY.md §7 hard part a): volunteers at
roughly the same training point must agree on WHO is in this round before any
tensor moves, and a peer dying mid-formation must not wedge anyone.

Protocol (leader-based, one DHT rendezvous key per round):

1. every interested peer announces under ``avg/<round_no>`` (TTL'd);
2. peers poll the key; the smallest peer_id present is the LEADER;
3. the leader freezes the member list, stamps a round EPOCH
   (hash of round key + members), and pushes ``avg.begin`` to each member;
4. members wait for the begin; no begin within the timeout -> round skipped
   (local training continues — averaging is best-effort, Moshpit-style).

The epoch travels with every subsequent tensor exchange; a message from a
stale or conflicting group is rejected by epoch mismatch rather than
corrupting the round.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import math
import re
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from distributedvolunteercomputing_tpu.swarm.dht import (
    ID_BITS,
    DHTNode,
    keyspace_position,
)
from distributedvolunteercomputing_tpu.swarm.transport import Addr, RPCError, Transport
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class GroupAssignment:
    """One volunteer's slot in one rotation of the group schedule."""

    rot: int        # rotation index (wall-clock window of the schedule)
    group_id: str   # rendezvous-key suffix, e.g. "r42.g3" / "r42.zdc1.g0"
    n_groups: int   # how many groups THIS view's live count splits into
    n_peers: int    # live peers behind that split (this view)
    # The peer ids this view puts in MY group (sorted). The whole point of
    # a deterministic schedule: the group is KNOWN before the round, so
    # formation can skip the generic DHT rendezvous (store + poll loop, a
    # full iterative lookup per poll) and members can join their leader
    # candidate directly — see Matchmaker.form_group_direct.
    members: Tuple[str, ...] = ()
    # Hierarchy level this assignment schedules ("flat" = the single-level
    # PR-7 grid; "intra" = a group scoped to one zone's members; "cross" =
    # a cross-zone mixing rotation). The level rides in the group_id, so
    # the group-scoped round key — and therefore the epoch hash, fencing
    # tokens, and retained-bytes keys — is level-scoped by construction.
    level: str = "flat"
    # Zone an "intra" assignment is scoped to ("" otherwise).
    zone: str = ""
    # Shard domain this assignment is scoped to (zone-sharded training,
    # swarm/sharding.py): None = unsharded. When set, every member of the
    # group holds the SAME shard, the ``.s<k>`` segment rides in the
    # group_id — so the round key, epoch hash, and fencing tokens are
    # shard-scoped by construction and two shards' gradients can never
    # rendezvous into one round.
    shard: Optional[int] = None


class GroupSchedule:
    """Moshpit-style rotating multi-group partition of the live swarm.

    One group per epoch caps swarm-wide sync throughput at one leader's
    NIC and one group's size. This schedule instead partitions the live
    membership into ``~n_peers / target_size`` groups every rotation by
    cutting the DHT keyspace into equal arcs: a volunteer's group is the
    arc its salted ``keyspace_position`` falls in, and the salt is the
    rotation index — so successive rounds regroup the swarm along a fresh
    seeded grid and group averages mix globally in O(log N) rounds
    (Moshpit SGD's argument; the mixing unit test in
    tests/test_multigroup.py measures the bound, and a NON-rotating
    schedule measurably fails it).

    Properties the swarm depends on:

    - **deterministic and local**: any volunteer computes its own group
      from (peer ids, rotation) alone — no negotiation, no extra RPCs.
      Each group then runs the ORDINARY rendezvous/leader/begin protocol
      under its group-scoped key, so the epoch+generation fencing from
      leader failover applies per group unchanged.
    - **view-divergence tolerant**: a peer's arc depends only on its OWN
      id, never on its rank in a sorted list, so two volunteers whose
      membership views differ by a churned peer still compute the same
      groups for everyone else. Disagreement about the group COUNT (only
      near ``n / target_size`` boundaries) degrades to an underfilled
      rendezvous, never to mixed tensors (the epoch guards that).
    - **best-effort sizing**: arcs are equal but positions are hashed, so
      group sizes fluctuate around ``target_size``; an undersized group
      skips its round (min_group) and its members re-mix next rotation.

    **Hierarchy** (``cross_zone_every_k`` > 0): real swarms have locality
    structure — same-DC TPU slices next to homes behind asymmetric WAN
    links — and the flat grid burns slow cross-zone bandwidth every round
    moving gradient mass an intra-zone group could have averaged locally.
    With volunteers advertising a ``zone`` (membership ``extra_info``),
    the schedule becomes a two-level grid in the hierarchical-HSDP shape:
    most rotations are INTRA-zone (the hash-arc layout scoped to each
    zone's own member set, so groups never span a zone boundary and no
    cross-zone byte moves), and every k-th rotation is a CROSS-zone
    mixing rotation (the ordinary zone-blind flat grid, whose hashed arcs
    span zones). Group means still reach the global mean because the
    Moshpit argument applies per level — O(log zone_size) intra rotations
    converge each zone, O(log N) cross rotations mix the zone means — and
    the level rides in the group id (``r<rot>.z<zone>.g<i>`` vs
    ``r<rot>.x<i>``), so the epoch+generation fencing and group-local
    failover of the flat schedule carry over unchanged. Fallback rules:
    fewer than two distinct advertised zones (or ``cross_zone_every_k``
    0) degrade to the flat grid — a mixed-version swarm where some peers
    never advertise a zone schedules those peers as one "" pseudo-zone,
    and never crashes.
    """

    def __init__(
        self,
        target_size: int = 8,
        rotation_s: float = 15.0,
        clock: Callable[[], float] = time.time,
        min_size: int = 2,
        cross_zone_every_k: int = 0,
    ):
        if target_size < 2:
            raise ValueError(f"target_size must be >= 2, got {target_size}")
        if rotation_s <= 0:
            raise ValueError(f"rotation_s must be > 0, got {rotation_s}")
        if cross_zone_every_k < 0:
            raise ValueError(
                f"cross_zone_every_k must be >= 0 (0 = flat), got {cross_zone_every_k}"
            )
        self.target_size = int(target_size)
        self.rotation_s = float(rotation_s)
        # The consensus wall clock when one exists (ClockSync.now): every
        # member of a prospective group must land in the same rotation
        # window or they rendezvous under different keys and miss.
        self.clock = clock
        self.min_size = int(min_size)
        # Hierarchy cadence: every k-th rotation mixes across zones; the
        # rest stay intra-zone. 0 = flat single-level grid (and any value
        # degrades to flat while fewer than two zones are advertised).
        self.cross_zone_every_k = int(cross_zone_every_k)

    def rotation(self) -> int:
        return int(self.clock() // self.rotation_s)

    def retune(
        self,
        target_size: Optional[int] = None,
        cross_zone_every_k: Optional[int] = None,
    ) -> None:
        """Live re-tune by the closed-loop controller (swarm/controller.py):
        group geometry (the topology knob — sync-group / butterfly /
        gossip map onto target sizes) and the cross-zone cadence (the
        learned k replacing the static flag). Validated like the ctor.

        Consistency note: the schedule is LOCAL — every volunteer
        computes its own split — so a retune takes effect at this
        volunteer's next ``assign`` and peers whose controllers have not
        (yet) made the same decision compute a different split for one or
        more rotations. That divergence is the schedule's documented
        degradation class: an underfilled rendezvous or a skipped round,
        never mixed tensors (the epoch hash covers the frozen member
        list). Hysteresis + shared evidence converge the fleet; the
        chaos_adaptive campaign measures the cost."""
        if target_size is not None:
            if target_size < 2:
                raise ValueError(f"target_size must be >= 2, got {target_size}")
            self.target_size = int(target_size)
        if cross_zone_every_k is not None:
            if cross_zone_every_k < 0:
                raise ValueError(
                    f"cross_zone_every_k must be >= 0 (0 = flat), got "
                    f"{cross_zone_every_k}"
                )
            self.cross_zone_every_k = int(cross_zone_every_k)

    def level_of(self, rot: int, zones_by_peer: Optional[Dict[str, str]] = None) -> str:
        """Hierarchy level rotation ``rot`` schedules at, given the zone
        advertisements in view ("flat" when the hierarchy is off or fewer
        than two distinct zones are advertised)."""
        k = self.cross_zone_every_k
        if k <= 0 or len(set((zones_by_peer or {}).values())) < 2:
            return "flat"
        return "cross" if rot % k == 0 else "intra"

    @staticmethod
    def zone_tag(zone: str) -> str:
        """Deterministic, key-safe tag for a zone name. Readable when the
        name already is; a sanitized name gets a crc suffix so two zones
        that sanitize identically ("a b" vs "a_b") cannot collide onto one
        keyspace (collision would only cost an accidental cross-zone
        group, never mixed tensors — the epoch hash covers members — but
        it would silently defeat the locality the operator asked for).
        The unzoned "" pseudo-zone tags as "~", a character the sanitizer
        can never emit for a real zone name — so no operator-chosen zone
        (not even one literally named "none") can share its keyspace."""
        if not zone:
            return "~"
        safe = re.sub(r"[^A-Za-z0-9_-]", "_", zone)[:16]
        if safe == zone:
            return safe
        return f"{safe}-{zlib.crc32(zone.encode()) & 0xFFFF:04x}"

    @staticmethod
    def n_groups(n_peers: int, target_size: int, min_size: int = 2) -> int:
        """Groups an ``n_peers`` swarm splits into: ``round(n / target)``,
        floored at 1 and capped so the EXPECTED group size never drops
        below ``min_size`` (a split that mostly produces unformable
        groups is worse than fewer, larger groups)."""
        if n_peers <= 0:
            return 0
        g = int(round(n_peers / float(target_size))) or 1
        return max(1, min(g, n_peers // max(min_size, 1)))

    @staticmethod
    def group_of(peer_id: str, rot: int, n_groups: int) -> int:
        """Arc index of ``peer_id`` under rotation ``rot`` — a function of
        the peer's own id only (view-divergence tolerance, see class doc)."""
        return (keyspace_position(peer_id, rot) * n_groups) >> ID_BITS

    def assign(
        self,
        member_ids,
        peer_id: str,
        rot: Optional[int] = None,
        zones: Optional[Dict[str, str]] = None,
        shards: Optional[Dict[str, int]] = None,
    ) -> Optional[GroupAssignment]:
        """This peer's assignment for rotation ``rot`` (current window when
        None), or None when the live swarm is too small to split — the
        caller then falls back to the single constant rendezvous key,
        which keeps small swarms byte-identical to the pre-schedule
        behavior.

        ``zones`` maps peer_id -> advertised zone (absent/None/"" = the
        unzoned pseudo-zone). With the hierarchy on and >= 2 distinct
        zones in view, intra rotations scope the hash-arc layout to this
        peer's zone — an assignment with fewer than ``min_size`` members
        (a lone peer in its zone) is returned as-is so the caller can
        skip the round CHEAPLY (it is deterministic that nobody else will
        rendezvous under that key) instead of burning a join timeout.

        ``shards`` maps peer_id -> advertised primary shard (zone-sharded
        training). A sharded peer's view is restricted to SAME-shard
        peers before any level logic runs, and the shard rides in the
        group id (``r<rot>.s<k>...``): cross/flat rotations then average
        only the peer's own shard across zones (the ~1/K wire saving),
        and an intra rotation degenerates to a singleton skip (inside a
        zone each shard has one holder; the intra links carry
        gather/scatter, not averaging). Sharded and unsharded peers never
        share a group — mixed fleets split along the advertisement, and
        the shard-scoped key + epoch hash make cross-shard mixing
        structurally impossible rather than merely unlikely. Because a
        shard-scoped view can be far below ``target_size``, an undersized
        sharded group is returned as-is (cheap-skip contract above)
        instead of falling back to the shard-blind constant key."""
        ids = set(member_ids)
        ids.add(peer_id)
        rot = self.rotation() if rot is None else int(rot)
        sk: Optional[int] = None
        if shards:
            if peer_id in shards:
                sk = int(shards[peer_id])
                ids = {pid for pid in ids if shards.get(pid) == sk}
            else:
                ids = {pid for pid in ids if pid not in shards}
        zmap = {pid: str((zones or {}).get(pid) or "") for pid in ids}
        level = self.level_of(rot, zmap)
        stag = "" if sk is None else f"s{sk}."
        if level == "intra":
            zone = zmap[peer_id]
            zone_ids = {pid for pid, z in zmap.items() if z == zone}
            n = len(zone_ids)
            g = max(self.n_groups(n, self.target_size, self.min_size), 1)
            ztag = self.zone_tag(zone)
            for home, grp in self._arcs(zone_ids, rot, g, self.min_size):
                if peer_id in grp:
                    return GroupAssignment(
                        rot=rot, group_id=f"r{rot}.{stag}z{ztag}.g{home}",
                        n_groups=g, n_peers=n, members=tuple(sorted(grp)),
                        level="intra", zone=zone, shard=sk,
                    )
            # Singleton zone: _arcs yields one group of one; still scoped.
            return GroupAssignment(
                rot=rot, group_id=f"r{rot}.{stag}z{ztag}.g0", n_groups=1,
                n_peers=n, members=(peer_id,), level="intra", zone=zone,
                shard=sk,
            )
        n = len(ids)
        g = self.n_groups(n, self.target_size, self.min_size)
        gtag = "x" if level == "cross" else "g"
        if g <= 1:
            if sk is None:
                return None
            # Shard-scoped views are small by design: one same-shard group
            # under the shard-scoped key (never the shard-blind fallback).
            return GroupAssignment(
                rot=rot, group_id=f"r{rot}.{stag}{gtag}0", n_groups=1,
                n_peers=n, members=tuple(sorted(ids)), level=level, shard=sk,
            )
        for home, grp in self._arcs(ids, rot, g, self.min_size):
            if peer_id in grp:
                return GroupAssignment(
                    rot=rot, group_id=f"r{rot}.{stag}{gtag}{home}", n_groups=g,
                    n_peers=n, members=tuple(sorted(grp)), level=level,
                    shard=sk,
                )
        return None  # unreachable: peer_id is in ids

    @classmethod
    def _arcs(
        cls, ids, rot: int, g: int, min_size: int
    ) -> List[Tuple[int, List[str]]]:
        """(home_arc, members) groups for one view: peers bucketed by their
        own salted arc, then undersized arcs CARRY-MERGED into the next
        arc — a hash partition leaves occasional arcs below ``min_size``,
        and without the merge their members burn a whole join timeout on a
        rendezvous that can never form. The merge is computed from the
        local view, so divergent views can disagree about a carried
        member's group; like every other divergence here that costs an
        underfilled round, never mixed tensors."""
        arcs: List[List[str]] = [[] for _ in range(g)]
        for pid in sorted(ids):
            arcs[cls.group_of(pid, rot, g)].append(pid)
        out: List[Tuple[int, List[str]]] = []
        carry: List[str] = []
        for a in range(g):
            cur = arcs[a] + carry
            if 0 < len(cur) < min_size:
                carry = cur
                continue
            if cur:
                out.append((a, cur))
            carry = []
        if carry:
            # Leftover tail: fold into the last formed group (or stand
            # alone when nothing formed at all — the caller's min_group
            # then decides).
            if out:
                out[-1][1].extend(carry)
            else:
                out.append((g - 1, carry))
        return out

    @classmethod
    def partition(
        cls,
        member_ids,
        rot: int,
        target_size: int,
        min_size: int = 2,
        zones: Optional[Dict[str, str]] = None,
        cross_zone_every_k: int = 0,
        shards: Optional[Dict[str, int]] = None,
    ) -> List[List[str]]:
        """The full partition one view computes for rotation ``rot``
        (groups in arc order, members sorted by id). Tests, the chaos
        campaign, and the scale bench use this to know who SHOULD group
        with whom; the swarm itself never needs the global view. With
        ``zones`` + ``cross_zone_every_k`` the partition is the
        hierarchical one: per-zone arcs on intra rotations (zones in
        sorted order), the zone-blind flat grid on cross rotations. With
        ``shards`` the partition runs per shard domain (shards in sorted
        order, unsharded peers last), mirroring ``assign``'s view
        restriction."""
        if shards:
            out: List[List[str]] = []
            ids_all = sorted(set(member_ids))
            buckets = sorted({int(s) for p, s in shards.items() if p in set(ids_all)})
            for sk in buckets:
                sub = [p for p in ids_all if shards.get(p) == sk]
                out.extend(
                    cls.partition(
                        sub, rot, target_size, min_size, zones,
                        cross_zone_every_k,
                    )
                )
            rest = [p for p in ids_all if p not in shards]
            if rest:
                out.extend(
                    cls.partition(
                        rest, rot, target_size, min_size, zones,
                        cross_zone_every_k,
                    )
                )
            return out
        ids = sorted(set(member_ids))
        zmap = {pid: str((zones or {}).get(pid) or "") for pid in ids}
        k = int(cross_zone_every_k)
        hier = k > 0 and len(set(zmap.values())) >= 2
        if hier and rot % k != 0:
            out: List[List[str]] = []
            for zone in sorted(set(zmap.values())):
                zone_ids = [pid for pid in ids if zmap[pid] == zone]
                g = max(cls.n_groups(len(zone_ids), target_size, min_size), 1)
                out.extend(
                    sorted(grp) for _, grp in cls._arcs(zone_ids, rot, g, min_size)
                )
            return out
        g = cls.n_groups(len(ids), target_size, min_size)
        if g <= 1:
            return [ids] if ids else []
        return [sorted(grp) for _, grp in cls._arcs(ids, rot, g, min_size)]


@dataclasses.dataclass
class Group:
    epoch: str
    members: List[Tuple[str, Addr]]  # sorted by peer_id; [0] is the leader
    my_index: int
    # Leader-issued per-member secret: each member receives ONLY its own in
    # its private begin message, and echoes it with every contribution, so a
    # member cannot forge traffic under another member's id (the leader holds
    # the full table in member_tokens; everyone else sees just their own).
    token: str = ""
    member_tokens: Optional[Dict[str, str]] = None
    # Absolute consensus-clock time this round must COMMIT by (leader-stamped
    # at begin; None for legacy leaders). Every member bounds its waits by
    # this instead of its full configured timeout, so the whole group agrees
    # on when the round closes — the deadline-bounded averaging contract.
    deadline: Optional[float] = None
    # The leader's round budget (seconds) behind that deadline, plus when
    # THIS node learned the round on its own monotonic clock. Together they
    # give a skew-free bound on the remaining wait: on step-cadence swarms
    # the deadline clock is raw wall time, and a member whose clock runs
    # ahead of the leader's by more than the budget would otherwise see the
    # round as already expired (see AveragerBase._deadline_wait).
    budget: Optional[float] = None
    formed_mono: float = dataclasses.field(default_factory=time.monotonic)
    # Round GENERATION — the fencing token. 0 for the round the matchmaking
    # leader began; each leader-failover recovery over the same epoch bumps
    # it. Every sync.contribute/sync.fetch carries it, and handlers reject a
    # mismatch, so a deposed or partitioned ex-leader's late serve (or a
    # member's stale push) can never mix into a newer generation's round.
    gen: int = 0
    # Group-schedule id this round formed under ("" = the single constant
    # rendezvous key). Purely observational: the schedule's group id is
    # already folded into the epoch hash via the group-scoped round_key,
    # so fencing/tokens/retained bytes are group-scoped by construction —
    # this field just lets stats and failover logs name the group.
    group_id: str = ""

    @property
    def leader_id(self) -> str:
        return self.members[0][0]

    @property
    def size(self) -> int:
        return len(self.members)

    def addr_of(self, peer_id: str) -> Addr:
        for pid, addr in self.members:
            if pid == peer_id:
                return addr
        raise KeyError(peer_id)


class Matchmaker:
    def __init__(
        self,
        transport: Transport,
        dht: DHTNode,
        peer_id: str,
        *,
        clock: Callable[[], float] = time.time,
        exclude: Optional[Callable[[str], bool]] = None,
        lead_exclude: Optional[Callable[[str], bool]] = None,
        lead_weight: Optional[Callable[[str], Optional[float]]] = None,
        rendezvous_get=None,
    ):
        self.transport = transport
        self.dht = dht
        self.peer_id = peer_id
        # Replicated-control-plane rendezvous reader (an async callable:
        # key -> records dict, or None on failure): form_group's poll loop
        # reads the round key through a replica's micro-cache — N members
        # polling one forming round cost the swarm ~one iterative DHT
        # lookup per cache window instead of one per member per poll. Any
        # failure (replica churn, no control plane) falls back to the
        # direct DHT walk, so matchmaking never depends on a coordinator
        # being alive. Writes stay direct DHT stores either way.
        self.rendezvous_get = rendezvous_get
        # ``clock`` is the consensus wall clock round deadlines are stamped
        # on (the volunteer passes ClockSync.now). ``exclude`` is the
        # straggler pre-exclusion predicate (resilience policy / phi
        # detector): a LEADER drops candidates it returns True for when
        # freezing the member list — they stay in the swarm and retry next
        # round, they just don't gate THIS round. ``lead_exclude`` is the
        # LEADERSHIP exclusion predicate: candidates it flags (recently
        # deposed as leader, currently suspected) are passed over when
        # deciding who self-elects, so a flaky peer is not handed the lead
        # again the moment it reappears. ``lead_weight`` maps a candidate
        # to its advertised uplink bandwidth (bytes/s; None = none
        # advertised): the leader serves the whole group's begin fan-out,
        # contribution gather, and result fetches, so among non-excluded
        # candidates the fattest advertised uplink self-elects — computed
        # from the membership snapshot alone, no extra RPCs.
        self.clock = clock
        self.exclude = exclude
        self.lead_exclude = lead_exclude
        self.lead_weight = lead_weight
        # Peers dropped from the last led round's member list (stats/tests).
        self.last_preexcluded: List[str] = []
        self._begin_futures: Dict[str, asyncio.Future] = {}
        # Begins that arrived while no form_group() was waiting, stamped with
        # arrival time: consumed only if still fresh (a begin parked after a
        # round timed out must not leak into the NEXT round as a dead epoch).
        self._parked_begins: Dict[str, Tuple[float, dict]] = {}
        # Direct-join fast path (form_group_direct): joins collected while
        # we lead a scheduled round, and joins that arrived BEFORE our
        # form_group_direct() registered the collector (a member can dial
        # its leader candidate the instant its clock enters the rotation
        # window) — same park-with-TTL discipline as begins.
        self._join_collectors: Dict[str, dict] = {}
        self._parked_joins: Dict[str, Tuple[float, Dict[str, Addr]]] = {}
        # round_keys we already led (direct path), with lead time: a join
        # arriving AFTER the freeze gets an immediate "too late" reply, so
        # a straggler skips its round in one RPC instead of burning the
        # whole join timeout waiting for a begin that can never come.
        self._recent_leads: Dict[str, float] = {}
        transport.register("avg.begin", self._rpc_begin)
        transport.register("avg.join", self._rpc_join)

    PARKED_BEGIN_TTL = 3.0
    # Distinct round_keys a remote peer can park begins under; entries are
    # also swept by TTL on every begin RPC, so keys that never reach a
    # form_group() cannot accumulate for the process lifetime.
    MAX_PARKED_BEGINS = 64

    async def _rpc_begin(self, args: dict, payload: bytes):
        fut = self._begin_futures.get(args["round_key"])
        if fut is not None and not fut.done():
            fut.set_result(args)
        else:
            # Begin can arrive before our form_group() registers the future.
            now = time.monotonic()
            for k in [
                k for k, (ts, _) in self._parked_begins.items()
                if now - ts > self.PARKED_BEGIN_TTL
            ]:
                del self._parked_begins[k]
            if (
                args["round_key"] not in self._parked_begins
                and len(self._parked_begins) >= self.MAX_PARKED_BEGINS
            ):
                raise RPCError("parked begin cap reached")
            self._parked_begins[args["round_key"]] = (now, args)
        return {"ok": True}, b""

    async def _rpc_join(self, args: dict, payload: bytes):
        """A scheduled member announcing itself directly to this node, its
        computed leader candidate for ``round_key`` (form_group_direct).
        Collected live when our own form_group_direct is leading that key;
        parked briefly otherwise (we may be about to)."""
        round_key = args["round_key"]
        pid = str(args["peer"])
        addr = tuple(args["addr"])
        col = self._join_collectors.get(round_key)
        if col is not None:
            if pid not in col["members"]:
                col["members"][pid] = addr
                col["event"].set()
            return {"ok": True}, b""
        now = time.monotonic()
        led_at = self._recent_leads.get(round_key)
        if led_at is not None and now - led_at <= self.PARKED_BEGIN_TTL:
            return {"ok": False, "late": True}, b""
        for k in [
            k for k, (ts, _) in self._parked_joins.items()
            if now - ts > self.PARKED_BEGIN_TTL
        ]:
            del self._parked_joins[k]
        ts, joiners = self._parked_joins.get(round_key, (now, {}))
        if (
            round_key not in self._parked_joins
            and len(self._parked_joins) >= self.MAX_PARKED_BEGINS
        ):
            # Table full: refuse WITHOUT raising — an RPCError here would
            # read as "candidate dead" to the joiner, who would then
            # self-elect a splinter group under the same key. A not-ok
            # reply makes it retry/skip instead (form_group_direct).
            return {"ok": False, "busy": True}, b""
        joiners[pid] = addr
        self._parked_joins[round_key] = (ts, joiners)
        return {"ok": True}, b""

    async def _read_rendezvous(self, round_key: str) -> Dict[str, object]:
        """One poll of the rendezvous key: via the control plane's cached
        read when wired (and answering), else the direct DHT lookup."""
        if self.rendezvous_get is not None:
            try:
                rec = await self.rendezvous_get(round_key)
            except Exception as e:  # noqa: BLE001 — reader is an accelerator
                log.debug("rendezvous reader failed: %s", errstr(e))
                rec = None
            if rec is not None:
                return rec
        return await self.dht.get(round_key)

    @staticmethod
    def _epoch(round_key: str, member_ids: List[str], nonce: str) -> str:
        return hashlib.sha1(
            (round_key + "|" + ",".join(member_ids) + "|" + nonce).encode()
        ).hexdigest()[:16]

    async def form_group(
        self,
        round_key: str,
        min_group: int = 2,
        max_group: int = 16,
        join_timeout: float = 10.0,
        settle: float = 0.5,
        round_budget_s: Optional[float] = None,
    ) -> Optional[Group]:
        """Rendezvous under ``round_key``.

        The key is a CONSTANT per averaging mode (e.g. ``avg/sync``), not a
        step number: volunteers at different local steps (fast peers, resumed
        checkpoints) must still find each other. Round uniqueness comes from
        the leader's nonce baked into the epoch, so two back-to-back rounds
        under the same key can never mix tensors.
        """
        my_addr = list(self.transport.addr)
        await self.dht.store(round_key, {"addr": my_addr}, subkey=self.peer_id, ttl=60.0)

        # form_group is serial per Matchmaker and always pops its future on
        # exit, so no prior future can exist here.
        fut = self._begin_futures[round_key] = asyncio.Future()
        parked = self._parked_begins.pop(round_key, None)
        if parked is not None and not fut.done():
            ts, begin = parked
            if time.monotonic() - ts <= self.PARKED_BEGIN_TTL:
                fut.set_result(begin)
            else:
                log.info("round %s: dropping stale parked begin (%.1fs old)",
                         round_key, time.monotonic() - ts)

        deadline = time.monotonic() + join_timeout
        members: List[Tuple[str, Addr]] = []
        stable_since = None
        try:
            while time.monotonic() < deadline:
                if fut.done():  # someone elected themselves leader already
                    return self._group_from_begin(fut.result(), round_key)
                rec = await self._read_rendezvous(round_key)
                current = sorted(
                    (pid, tuple(info["addr"])) for pid, info in rec.items() if info is not None
                )
                if [m[0] for m in current] != [m[0] for m in members]:
                    members = current
                    stable_since = time.monotonic()
                enough = len(members) >= min_group
                stable = stable_since is not None and time.monotonic() - stable_since >= settle
                full = len(members) >= max_group
                if enough and (stable or full):
                    # Elect over the same [:max_group] window _lead will
                    # freeze, so the winner is always in its own group.
                    if self._pick_leader(members[:max_group]) == self.peer_id:
                        return await self._lead(
                            round_key, members[:max_group],
                            min_group=min_group, round_budget_s=round_budget_s,
                        )
                    # not leader: fall through to awaiting begin
                    break
                await asyncio.sleep(0.1)

            if not (len(members) >= min_group):
                log.info("round %s: only %d peers, skipping", round_key, len(members))
                return None
            remaining = max(deadline - time.monotonic(), 2.0)
            begin = await asyncio.wait_for(fut, timeout=remaining)
            return self._group_from_begin(begin, round_key)
        except asyncio.TimeoutError:
            log.info("round %s: no begin from leader, skipping", round_key)
            return None
        finally:
            self._begin_futures.pop(round_key, None)

    async def form_group_direct(
        self,
        round_key: str,
        expected: List[Tuple[str, Addr]],
        min_group: int = 2,
        max_group: int = 16,
        join_timeout: float = 10.0,
        settle: float = 0.5,
        round_budget_s: Optional[float] = None,
    ) -> Optional[Group]:
        """Scheduled-group formation: rendezvous WITHOUT the DHT.

        ``expected`` is the (pid, addr) set the group schedule puts in this
        round's group — deterministic and already known to every member, so
        the generic DHT rendezvous (a store fanned to K replicas plus a
        full iterative lookup per 100 ms poll) is pure waste here. Instead
        each member sends ONE ``avg.join`` RPC to its leader candidate
        (``_pick_leader`` over the expected set) and awaits the begin; the
        candidate collects joins and leads the moment every expected member
        has joined (or min_group + a ``settle`` quiet period, or the join
        timeout — whichever first). ~4 RPCs per member-round total, and no
        settle wait on the common path.

        Degradation matches the classic path class-for-class: a dead
        candidate is skipped (its conn failure is the signal) and the next
        expected id self-elects; divergent views (churn near arc
        boundaries, disagreeing suspicion) can split a group into two
        epochs or cost an underfilled round, never mixed tensors — the
        epoch hash still covers the frozen member list. Joiners outside
        ``expected`` (a peer whose view merged them into this arc) are
        accepted up to ``max_group``: inclusion under divergence beats
        symmetry. The epoch/token/begin machinery is byte-identical to
        form_group's — failover, fencing, and recovery see no difference.
        """
        deadline = time.monotonic() + join_timeout
        dead: set = set()
        fut = self._begin_futures[round_key] = asyncio.Future()
        parked = self._parked_begins.pop(round_key, None)
        if parked is not None and not fut.done():
            ts, begin = parked
            if time.monotonic() - ts <= self.PARKED_BEGIN_TTL:
                fut.set_result(begin)
        try:
            while True:
                alive = [m for m in expected if m[0] not in dead]
                if not alive:
                    log.info("round %s: every expected peer dead, skipping",
                             round_key)
                    return None
                # begin-wins, same as form_group: a peer whose view diverged
                # (suspicion, arc-boundary churn) may have self-elected and
                # already sent us a begin — joining it beats leading a
                # splinter group under the same key and stalling its round.
                if fut.done():
                    return self._group_from_begin(fut.result(), round_key)
                cand = self._pick_leader(alive)
                if cand == self.peer_id:
                    return await self._lead_direct(
                        round_key, expected, dead,
                        min_group=min_group, max_group=max_group,
                        settle=settle, deadline_mono=deadline,
                        round_budget_s=round_budget_s,
                    )
                addr = next(a for pid, a in alive if pid == cand)
                try:
                    ret, _ = await self.transport.call(
                        addr, "avg.join",
                        {"round_key": round_key, "peer": self.peer_id,
                         "addr": list(self.transport.addr)},
                        timeout=5.0, connect_timeout=3.0,
                    )
                except Exception as e:  # noqa: BLE001 — candidate down/refusing
                    dead.add(cand)
                    log.info("round %s: leader candidate %s unreachable "
                             "(%s), trying next", round_key, cand, errstr(e))
                    if time.monotonic() >= deadline:
                        return None
                    continue
                if not ret.get("ok", True):
                    # The candidate froze a round under this key moments ago
                    # (late) or its parked-join table is full (busy). When
                    # the cadence runs several rounds per rotation window,
                    # the NEXT round reuses this key and a re-sent join
                    # lands in its collector (or parks once the recent-lead
                    # TTL expires) — so retry at settle intervals until the
                    # join deadline instead of skipping: a genuine
                    # last-round straggler pays a few tiny RPCs and the
                    # same timeout the classic rendezvous would have
                    # burned, while skipping here would drop a whole round
                    # for every member that starts slightly ahead of its
                    # leader, every round.
                    if time.monotonic() >= deadline:
                        log.info("round %s: joined after the freeze, "
                                 "skipping", round_key)
                        return None
                    log.debug("round %s: candidate %s froze without us, "
                              "retrying", round_key, cand)
                    await asyncio.sleep(
                        min(max(settle, 0.05),
                            max(deadline - time.monotonic(), 0.0))
                    )
                    continue
                remaining = max(deadline - time.monotonic(), 2.0)
                begin = await asyncio.wait_for(fut, timeout=remaining)
                return self._group_from_begin(begin, round_key)
        except asyncio.TimeoutError:
            log.info("round %s: no begin from leader, skipping", round_key)
            return None
        finally:
            self._begin_futures.pop(round_key, None)

    async def _lead_direct(
        self,
        round_key: str,
        expected: List[Tuple[str, Addr]],
        dead: set,
        *,
        min_group: int,
        max_group: int,
        settle: float,
        deadline_mono: float,
        round_budget_s: Optional[float],
    ) -> Optional[Group]:
        """Leader half of form_group_direct: collect ``avg.join``s for
        ``round_key``, freeze, and run the ordinary ``_lead``."""
        col = self._join_collectors[round_key] = {
            "members": {}, "event": asyncio.Event(),
        }
        parked = self._parked_joins.pop(round_key, None)
        if parked is not None:
            ts, joiners = parked
            if time.monotonic() - ts <= self.PARKED_BEGIN_TTL:
                col["members"].update(joiners)
        expect_ids = {
            pid for pid, _ in expected
            if pid != self.peer_id and pid not in dead
        }
        try:
            t0 = last_join = time.monotonic()
            # Expected members get a real grace before the quiet-period
            # break can freeze them out: under load a member can easily be
            # a settle late, and freezing early costs it the whole round.
            # Only a dead-but-not-yet-expired expected peer pays this wait.
            grace = min(max(4.0 * settle, 1.0), deadline_mono - t0)
            while True:
                now = time.monotonic()
                joined = col["members"]
                if expect_ids <= joined.keys():
                    break  # everyone this view expects is here: lead NOW
                if len(joined) + 1 >= max_group:
                    break
                if (
                    len(joined) + 1 >= min_group
                    and now - last_join >= settle
                    and now - t0 >= grace
                ):
                    break  # formable, quiet, and stragglers had their grace
                if now >= deadline_mono:
                    if len(joined) + 1 >= min_group:
                        break
                    log.info("round %s: only %d peers joined, skipping",
                             round_key, len(joined) + 1)
                    return None
                col["event"].clear()
                # Formable already: wake at the settle boundary. Not yet:
                # wake at 1s ticks just to re-check the deadline.
                wait = min(
                    settle if len(joined) + 1 >= min_group else 1.0,
                    deadline_mono - now,
                )
                try:
                    await asyncio.wait_for(col["event"].wait(), timeout=wait)
                    last_join = time.monotonic()
                except asyncio.TimeoutError:
                    pass
            if len(col["members"]) + 1 < min_group:
                # Every expected member joined but the group is still
                # below the floor (an undersized scheduled group under a
                # divergent view — the caller's own deterministic check
                # normally skips these before dialing): min_group is a
                # robustness guarantee, never lead beneath it.
                log.info("round %s: only %d peers joined (< min_group %d), "
                         "skipping", round_key, len(col["members"]) + 1,
                         min_group)
                return None
        finally:
            self._join_collectors.pop(round_key, None)
        # Freeze. From here a late join is answered "too late" (bounded
        # map: TTL-swept on insert, same cap discipline as parked begins).
        now = time.monotonic()
        for k in [
            k for k, t in self._recent_leads.items()
            if now - t > self.PARKED_BEGIN_TTL
        ]:
            del self._recent_leads[k]
        while len(self._recent_leads) >= self.MAX_PARKED_BEGINS:
            self._recent_leads.pop(next(iter(self._recent_leads)))
        self._recent_leads[round_key] = now
        # Self leads, joiners fill the group in id order (the cap
        # can never drop the leader).
        others = sorted(col["members"].items())[: max(max_group - 1, 1)]
        members = [(self.peer_id, self.transport.addr)] + [
            (pid, tuple(addr)) for pid, addr in others
        ]
        return await self._lead(
            round_key, sorted(members),
            min_group=min_group, round_budget_s=round_budget_s,
        )

    def _pick_leader(self, members: List[Tuple[str, Addr]]) -> str:
        """Who should self-elect for this candidate set: among candidates
        the local ``lead_exclude`` predicate does NOT flag, the one with
        the fattest advertised uplink (``lead_weight``, bucketed to
        octaves so heartbeat-to-heartbeat EWMA jitter between two
        similar links cannot flap the choice), ties and no-advertisement
        falling back to the smallest peer_id; the plain smallest when
        every candidate is flagged (a round with a suspect leader beats
        no round). Purely local and advisory: peers with divergent
        suspicion or stale bandwidth views may elect different leaders,
        which yields two distinct epochs (never mixed tensors) and one
        underfilled round — the members' begin-wins rule resolves it."""
        best: Optional[Tuple[int, str]] = None
        for pid, _ in members:
            if self.lead_exclude is not None:
                try:
                    flagged = bool(self.lead_exclude(pid))
                except Exception:  # noqa: BLE001 — a policy bug must not kill rounds
                    flagged = False
                if flagged:
                    continue
            bucket = -1
            if self.lead_weight is not None:
                try:
                    bw = self.lead_weight(pid)
                except Exception:  # noqa: BLE001 — a policy bug must not kill rounds
                    bw = None
                if isinstance(bw, (int, float)) and bw > 0:
                    bucket = int(math.log2(float(bw)))
            if best is None or bucket > best[0] or (
                bucket == best[0] and pid < best[1]
            ):
                best = (bucket, pid)
        if best is not None:
            return best[1]
        return members[0][0]

    def _group_from_begin(self, begin: dict, round_key: str) -> Optional[Group]:
        members = [(pid, tuple(addr)) for pid, addr in begin["members"]]
        ids = [pid for pid, _ in members]
        if begin["epoch"] != self._epoch(round_key, ids, begin.get("nonce", "")):
            log.warning("round %s: epoch mismatch in begin, skipping", round_key)
            return None
        if self.peer_id not in ids:
            return None
        deadline = begin.get("deadline")
        budget = begin.get("budget")
        return Group(
            epoch=begin["epoch"],
            members=members,
            my_index=ids.index(self.peer_id),
            token=begin.get("token", ""),
            deadline=float(deadline) if isinstance(deadline, (int, float)) else None,
            budget=float(budget) if isinstance(budget, (int, float)) else None,
        )

    async def _lead(
        self,
        round_key: str,
        members: List[Tuple[str, Addr]],
        *,
        min_group: int = 2,
        round_budget_s: Optional[float] = None,
    ) -> Optional[Group]:
        import os as _os

        members = self._preexclude(members, min_group)
        # The protocol's leader slot IS members[0] (Group.leader_id; the
        # averagers take the leader path iff my_index == 0): rotate
        # ourselves to the front — we are the one leading — so a
        # _pick_leader winner that is not the plain smallest id still
        # produces a coherent group. The rest keep sorted (epoch) order,
        # which successor election depends on. The epoch hash is computed
        # over this exact order and travels in the begin, so every member
        # sees the same rotated list.
        members = (
            [m for m in members if m[0] == self.peer_id]
            + [m for m in members if m[0] != self.peer_id]
        )
        ids = [pid for pid, _ in members]
        # One urandom syscall covers the nonce and every member token
        # (one uuid4 per member was ~5 getrandom syscalls per round).
        rand = _os.urandom(4 + 16 * len(ids))
        nonce = rand[:4].hex()
        epoch = self._epoch(round_key, ids, nonce)
        # One secret per member, delivered only in that member's begin.
        tokens = {
            pid: rand[4 + 16 * i : 20 + 16 * i].hex()
            for i, pid in enumerate(ids)
        }
        # Deadline stamped BEFORE the begin fan-out: the fan-out itself
        # (up to 5s per unreachable member) spends round budget, and every
        # member must agree on the same absolute commit time.
        deadline = (
            self.clock() + float(round_budget_s) if round_budget_s else None
        )
        stamp_mono = time.monotonic()
        begin = {
            "round_key": round_key,
            "epoch": epoch,
            "nonce": nonce,
            "members": [[pid, list(addr)] for pid, addr in members],
        }
        if deadline is not None:
            begin["deadline"] = deadline
            begin["budget"] = float(round_budget_s)
        async def _begin_one(pid: str, addr: Addr) -> Optional[str]:
            try:
                # The begin fan-out spends round budget per member: bound
                # the dial separately (an unreachable member should cost its
                # connect timeout, not the full per-call budget). Members
                # already dialed this round (their join traffic shares the
                # pooled connection) skip the dial entirely.
                await self.transport.call(
                    addr, "avg.begin", {**begin, "token": tokens[pid]},
                    timeout=5.0, connect_timeout=3.0,
                )
                return pid
            except Exception as e:  # noqa: BLE001 — one corpse must not kill the round
                log.warning("round %s: member %s unreachable at begin: %s", round_key, pid, errstr(e))
                return None

        # Concurrent fan-out: one dead member costs its connect timeout in
        # PARALLEL with the live sends, not serially ahead of them (a
        # serial loop made every member behind a corpse start late).
        reached = [
            pid
            for pid in await asyncio.gather(
                *(
                    _begin_one(pid, addr)
                    for pid, addr in members
                    if pid != self.peer_id
                )
            )
            if pid is not None
        ]
        if not reached:
            return None
        return Group(
            epoch=epoch,
            members=members,
            my_index=ids.index(self.peer_id),
            token=tokens[self.peer_id],
            member_tokens=tokens,
            deadline=deadline,
            budget=float(round_budget_s) if deadline is not None else None,
            # The leader's budget counts from the STAMP, not from after the
            # begin fan-out — slow formation must keep shrinking its gather.
            formed_mono=stamp_mono,
        )

    def _preexclude(
        self, members: List[Tuple[str, Addr]], min_group: int
    ) -> List[Tuple[str, Addr]]:
        """Drop likely stragglers from a member list about to be frozen —
        never ourselves (we're leading) and never below ``min_group`` (a
        round with suspects beats no round: the deadline bounds the damage
        a straggler can do anyway)."""
        self.last_preexcluded = []
        if self.exclude is None:
            return members
        kept = list(members)
        for pid, addr in members:
            if len(kept) <= min_group:
                break
            if pid == self.peer_id:
                continue
            try:
                drop = bool(self.exclude(pid))
            except Exception:  # noqa: BLE001 — a policy bug must not kill rounds
                drop = False
            if drop:
                kept.remove((pid, addr))
                self.last_preexcluded.append(pid)
        if self.last_preexcluded:
            log.info(
                "round formation: pre-excluded likely stragglers %s",
                self.last_preexcluded,
            )
        return kept
