"""Matchmaking: form an averaging group for one round.

The averaging cohort problem (SURVEY.md §7 hard part a): volunteers at
roughly the same training point must agree on WHO is in this round before any
tensor moves, and a peer dying mid-formation must not wedge anyone.

Protocol (leader-based, one DHT rendezvous key per round):

1. every interested peer announces under ``avg/<round_no>`` (TTL'd);
2. peers poll the key; the smallest peer_id present is the LEADER;
3. the leader freezes the member list, stamps a round EPOCH
   (hash of round key + members), and pushes ``avg.begin`` to each member;
4. members wait for the begin; no begin within the timeout -> round skipped
   (local training continues — averaging is best-effort, Moshpit-style).

The epoch travels with every subsequent tensor exchange; a message from a
stale or conflicting group is rejected by epoch mismatch rather than
corrupting the round.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import time
from typing import Callable, Dict, List, Optional, Tuple

from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.transport import Addr, RPCError, Transport
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class Group:
    epoch: str
    members: List[Tuple[str, Addr]]  # sorted by peer_id; [0] is the leader
    my_index: int
    # Leader-issued per-member secret: each member receives ONLY its own in
    # its private begin message, and echoes it with every contribution, so a
    # member cannot forge traffic under another member's id (the leader holds
    # the full table in member_tokens; everyone else sees just their own).
    token: str = ""
    member_tokens: Optional[Dict[str, str]] = None
    # Absolute consensus-clock time this round must COMMIT by (leader-stamped
    # at begin; None for legacy leaders). Every member bounds its waits by
    # this instead of its full configured timeout, so the whole group agrees
    # on when the round closes — the deadline-bounded averaging contract.
    deadline: Optional[float] = None
    # The leader's round budget (seconds) behind that deadline, plus when
    # THIS node learned the round on its own monotonic clock. Together they
    # give a skew-free bound on the remaining wait: on step-cadence swarms
    # the deadline clock is raw wall time, and a member whose clock runs
    # ahead of the leader's by more than the budget would otherwise see the
    # round as already expired (see AveragerBase._deadline_wait).
    budget: Optional[float] = None
    formed_mono: float = dataclasses.field(default_factory=time.monotonic)
    # Round GENERATION — the fencing token. 0 for the round the matchmaking
    # leader began; each leader-failover recovery over the same epoch bumps
    # it. Every sync.contribute/sync.fetch carries it, and handlers reject a
    # mismatch, so a deposed or partitioned ex-leader's late serve (or a
    # member's stale push) can never mix into a newer generation's round.
    gen: int = 0

    @property
    def leader_id(self) -> str:
        return self.members[0][0]

    @property
    def size(self) -> int:
        return len(self.members)

    def addr_of(self, peer_id: str) -> Addr:
        for pid, addr in self.members:
            if pid == peer_id:
                return addr
        raise KeyError(peer_id)


class Matchmaker:
    def __init__(
        self,
        transport: Transport,
        dht: DHTNode,
        peer_id: str,
        *,
        clock: Callable[[], float] = time.time,
        exclude: Optional[Callable[[str], bool]] = None,
        lead_exclude: Optional[Callable[[str], bool]] = None,
    ):
        self.transport = transport
        self.dht = dht
        self.peer_id = peer_id
        # ``clock`` is the consensus wall clock round deadlines are stamped
        # on (the volunteer passes ClockSync.now). ``exclude`` is the
        # straggler pre-exclusion predicate (resilience policy / phi
        # detector): a LEADER drops candidates it returns True for when
        # freezing the member list — they stay in the swarm and retry next
        # round, they just don't gate THIS round. ``lead_exclude`` is the
        # LEADERSHIP exclusion predicate: candidates it flags (recently
        # deposed as leader, currently suspected) are passed over when
        # deciding who self-elects, so a flaky peer is not handed the lead
        # again the moment it reappears.
        self.clock = clock
        self.exclude = exclude
        self.lead_exclude = lead_exclude
        # Peers dropped from the last led round's member list (stats/tests).
        self.last_preexcluded: List[str] = []
        self._begin_futures: Dict[str, asyncio.Future] = {}
        # Begins that arrived while no form_group() was waiting, stamped with
        # arrival time: consumed only if still fresh (a begin parked after a
        # round timed out must not leak into the NEXT round as a dead epoch).
        self._parked_begins: Dict[str, Tuple[float, dict]] = {}
        transport.register("avg.begin", self._rpc_begin)

    PARKED_BEGIN_TTL = 3.0
    # Distinct round_keys a remote peer can park begins under; entries are
    # also swept by TTL on every begin RPC, so keys that never reach a
    # form_group() cannot accumulate for the process lifetime.
    MAX_PARKED_BEGINS = 64

    async def _rpc_begin(self, args: dict, payload: bytes):
        fut = self._begin_futures.get(args["round_key"])
        if fut is not None and not fut.done():
            fut.set_result(args)
        else:
            # Begin can arrive before our form_group() registers the future.
            now = time.monotonic()
            for k in [
                k for k, (ts, _) in self._parked_begins.items()
                if now - ts > self.PARKED_BEGIN_TTL
            ]:
                del self._parked_begins[k]
            if (
                args["round_key"] not in self._parked_begins
                and len(self._parked_begins) >= self.MAX_PARKED_BEGINS
            ):
                raise RPCError("parked begin cap reached")
            self._parked_begins[args["round_key"]] = (now, args)
        return {"ok": True}, b""

    @staticmethod
    def _epoch(round_key: str, member_ids: List[str], nonce: str) -> str:
        return hashlib.sha1(
            (round_key + "|" + ",".join(member_ids) + "|" + nonce).encode()
        ).hexdigest()[:16]

    async def form_group(
        self,
        round_key: str,
        min_group: int = 2,
        max_group: int = 16,
        join_timeout: float = 10.0,
        settle: float = 0.5,
        round_budget_s: Optional[float] = None,
    ) -> Optional[Group]:
        """Rendezvous under ``round_key``.

        The key is a CONSTANT per averaging mode (e.g. ``avg/sync``), not a
        step number: volunteers at different local steps (fast peers, resumed
        checkpoints) must still find each other. Round uniqueness comes from
        the leader's nonce baked into the epoch, so two back-to-back rounds
        under the same key can never mix tensors.
        """
        my_addr = list(self.transport.addr)
        await self.dht.store(round_key, {"addr": my_addr}, subkey=self.peer_id, ttl=60.0)

        # form_group is serial per Matchmaker and always pops its future on
        # exit, so no prior future can exist here.
        fut = self._begin_futures[round_key] = asyncio.Future()
        parked = self._parked_begins.pop(round_key, None)
        if parked is not None and not fut.done():
            ts, begin = parked
            if time.monotonic() - ts <= self.PARKED_BEGIN_TTL:
                fut.set_result(begin)
            else:
                log.info("round %s: dropping stale parked begin (%.1fs old)",
                         round_key, time.monotonic() - ts)

        deadline = time.monotonic() + join_timeout
        members: List[Tuple[str, Addr]] = []
        stable_since = None
        try:
            while time.monotonic() < deadline:
                if fut.done():  # someone elected themselves leader already
                    return self._group_from_begin(fut.result(), round_key)
                rec = await self.dht.get(round_key)
                current = sorted(
                    (pid, tuple(info["addr"])) for pid, info in rec.items() if info is not None
                )
                if [m[0] for m in current] != [m[0] for m in members]:
                    members = current
                    stable_since = time.monotonic()
                enough = len(members) >= min_group
                stable = stable_since is not None and time.monotonic() - stable_since >= settle
                full = len(members) >= max_group
                if enough and (stable or full):
                    # Elect over the same [:max_group] window _lead will
                    # freeze, so the winner is always in its own group.
                    if self._pick_leader(members[:max_group]) == self.peer_id:
                        return await self._lead(
                            round_key, members[:max_group],
                            min_group=min_group, round_budget_s=round_budget_s,
                        )
                    # not leader: fall through to awaiting begin
                    break
                await asyncio.sleep(0.1)

            if not (len(members) >= min_group):
                log.info("round %s: only %d peers, skipping", round_key, len(members))
                return None
            remaining = max(deadline - time.monotonic(), 2.0)
            begin = await asyncio.wait_for(fut, timeout=remaining)
            return self._group_from_begin(begin, round_key)
        except asyncio.TimeoutError:
            log.info("round %s: no begin from leader, skipping", round_key)
            return None
        finally:
            self._begin_futures.pop(round_key, None)

    def _pick_leader(self, members: List[Tuple[str, Addr]]) -> str:
        """Who should self-elect for this candidate set: the smallest
        peer_id the local ``lead_exclude`` predicate does NOT flag, falling
        back to the plain smallest when every candidate is flagged (a round
        with a suspect leader beats no round). Purely local and advisory:
        peers with divergent suspicion may elect different leaders, which
        yields two distinct epochs (never mixed tensors) and one
        underfilled round — the members' begin-wins rule resolves it."""
        if self.lead_exclude is not None:
            for pid, _ in members:
                try:
                    flagged = bool(self.lead_exclude(pid))
                except Exception:  # noqa: BLE001 — a policy bug must not kill rounds
                    flagged = False
                if not flagged:
                    return pid
        return members[0][0]

    def _group_from_begin(self, begin: dict, round_key: str) -> Optional[Group]:
        members = [(pid, tuple(addr)) for pid, addr in begin["members"]]
        ids = [pid for pid, _ in members]
        if begin["epoch"] != self._epoch(round_key, ids, begin.get("nonce", "")):
            log.warning("round %s: epoch mismatch in begin, skipping", round_key)
            return None
        if self.peer_id not in ids:
            return None
        deadline = begin.get("deadline")
        budget = begin.get("budget")
        return Group(
            epoch=begin["epoch"],
            members=members,
            my_index=ids.index(self.peer_id),
            token=begin.get("token", ""),
            deadline=float(deadline) if isinstance(deadline, (int, float)) else None,
            budget=float(budget) if isinstance(budget, (int, float)) else None,
        )

    async def _lead(
        self,
        round_key: str,
        members: List[Tuple[str, Addr]],
        *,
        min_group: int = 2,
        round_budget_s: Optional[float] = None,
    ) -> Optional[Group]:
        import uuid

        members = self._preexclude(members, min_group)
        # The protocol's leader slot IS members[0] (Group.leader_id; the
        # averagers take the leader path iff my_index == 0): rotate
        # ourselves to the front — we are the one leading — so a
        # _pick_leader winner that is not the plain smallest id still
        # produces a coherent group. The rest keep sorted (epoch) order,
        # which successor election depends on. The epoch hash is computed
        # over this exact order and travels in the begin, so every member
        # sees the same rotated list.
        members = (
            [m for m in members if m[0] == self.peer_id]
            + [m for m in members if m[0] != self.peer_id]
        )
        ids = [pid for pid, _ in members]
        nonce = uuid.uuid4().hex[:8]
        epoch = self._epoch(round_key, ids, nonce)
        # One secret per member, delivered only in that member's begin.
        tokens = {pid: uuid.uuid4().hex for pid in ids}
        # Deadline stamped BEFORE the begin fan-out: the fan-out itself
        # (up to 5s per unreachable member) spends round budget, and every
        # member must agree on the same absolute commit time.
        deadline = (
            self.clock() + float(round_budget_s) if round_budget_s else None
        )
        stamp_mono = time.monotonic()
        begin = {
            "round_key": round_key,
            "epoch": epoch,
            "nonce": nonce,
            "members": [[pid, list(addr)] for pid, addr in members],
        }
        if deadline is not None:
            begin["deadline"] = deadline
            begin["budget"] = float(round_budget_s)
        reached = []
        for pid, addr in members:
            if pid == self.peer_id:
                continue
            try:
                # The begin fan-out spends round budget per member: bound
                # the dial separately (an unreachable member should cost its
                # connect timeout, not the full per-call budget). Members
                # already dialed this round (their join traffic shares the
                # pooled connection) skip the dial entirely.
                await self.transport.call(
                    addr, "avg.begin", {**begin, "token": tokens[pid]},
                    timeout=5.0, connect_timeout=3.0,
                )
                reached.append(pid)
            except Exception as e:
                log.warning("round %s: member %s unreachable at begin: %s", round_key, pid, errstr(e))
        if not reached:
            return None
        return Group(
            epoch=epoch,
            members=members,
            my_index=ids.index(self.peer_id),
            token=tokens[self.peer_id],
            member_tokens=tokens,
            deadline=deadline,
            budget=float(round_budget_s) if deadline is not None else None,
            # The leader's budget counts from the STAMP, not from after the
            # begin fan-out — slow formation must keep shrinking its gather.
            formed_mono=stamp_mono,
        )

    def _preexclude(
        self, members: List[Tuple[str, Addr]], min_group: int
    ) -> List[Tuple[str, Addr]]:
        """Drop likely stragglers from a member list about to be frozen —
        never ourselves (we're leading) and never below ``min_group`` (a
        round with suspects beats no round: the deadline bounds the damage
        a straggler can do anyway)."""
        self.last_preexcluded = []
        if self.exclude is None:
            return members
        kept = list(members)
        for pid, addr in members:
            if len(kept) <= min_group:
                break
            if pid == self.peer_id:
                continue
            try:
                drop = bool(self.exclude(pid))
            except Exception:  # noqa: BLE001 — a policy bug must not kill rounds
                drop = False
            if drop:
                kept.remove((pid, addr))
                self.last_preexcluded.append(pid)
        if self.last_preexcluded:
            log.info(
                "round formation: pre-excluded likely stragglers %s",
                self.last_preexcluded,
            )
        return kept
