"""Swarm watchdog: streaming anomaly detection, SLO burn rates, and the
alert plane over the telemetry substrate.

PRs 10-11 built a complete sensor suite — round traces, a unified metrics
registry, a flight recorder, training-health signals — but nothing
CONSUMED it: every regression was found by a human reading chaos artifacts
after the fact. This module is the active consumer, in two halves:

- **Volunteer-side streaming detectors** (:class:`Watchdog`, one per
  telemetry bundle): robust online baselines (EWMA mean + EWMA-MAD band,
  warm-up gated — :class:`OnlineBaseline`) over the series every volunteer
  already produces. The stock detector catalog:

  ========================  =========  ==========================================
  kind                      severity   fires on
  ========================  =========  ==========================================
  ``commit_rate_collapse``  page       committed-round rate far below baseline
  ``round_wall_inflation``  warn       per-LEVEL round wall far above baseline
                                       (key = ``flat``/``intra``/``cross``)
  ``mass_frac_drop``        warn       ``mass_committed_frac`` far below baseline
  ``peer_bw_collapse``      warn       a per-peer bandwidth EWMA far below its
                                       own baseline (key = peer / link)
  ``cp_beat_failures``      warn       consecutive control-plane beat failures
                                       (streak, not baseline)
  ``byzantine_contributor`` page       the health monitor's quality flag set
                                       (key = flagged peer)
  ========================  =========  ==========================================

  Every transition is deduplicated and flap-suppressed (hysteresis: a
  separate clear band + consecutive-breach counts; plus a re-raise
  cooldown after each clear) and lands as an ``alert_raised`` /
  ``alert_cleared`` flight-recorder event. The compact firing set rides
  the existing ``cp.exchange`` report beat via :meth:`Watchdog.summary`
  — zero new RPC types, the PR-11 health-sketch pattern.

- **Replica-side SLO plane** (:class:`SwarmWatchdog`, one per
  control-plane replica): declarative objectives (:class:`SLO`, defaults
  in :data:`DEFAULT_SLOS`) evaluated with fast/slow multi-window burn
  rates over the merged rollup — committed-round rate, p99 round wall per
  level (merged from the per-volunteer shared-bucket histograms riding
  the report), ``mass_committed_frac``, and report freshness — plus the
  swarm-level detectors no single volunteer can see (cross-zone mixing
  stall over the health rollup's sketch dispersion). Rolled into
  ``coord.status["slo"]`` and ``coord.status["alerts"]`` under the
  CI-pinned :data:`STATUS_WATCHDOG_SCHEMA`.

Burn-rate semantics (the classic multi-window pair): each evaluation tick
is *good* when the objective's metric meets its bound; over a fast and a
slow window, ``burn = bad_fraction / (1 - target)`` — burn 1.0 spends the
error budget exactly at the objective's target rate, burn N spends it N
times faster. An objective is **burning** (alert ``slo_burn``) when BOTH
windows exceed their thresholds: the fast window gives detection latency,
the slow window suppresses blips.

Everything follows the telemetry plane's contract: advisory and bounded.
Record paths swallow their own exceptions, per-key maps are capped, and a
disabled watchdog (``--no-watchdog`` / ``--no-telemetry``) turns every
call into a no-op and ships NO alert bytes on the heartbeat.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from distributedvolunteercomputing_tpu.swarm.telemetry import HIST_BUCKETS
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

# Version stamp carried by every watchdog summary and the coord.status
# slo/alerts rollups (independent of TELEMETRY_SCHEMA_VERSION; both are
# CI-pinned by tests/test_watchdog.py).
WATCHDOG_SCHEMA_VERSION = 1

SEV_INFO, SEV_WARN, SEV_PAGE = "info", "warn", "page"
SEVERITIES = (SEV_INFO, SEV_WARN, SEV_PAGE)


# -- robust online baseline --------------------------------------------------


class OnlineBaseline:
    """EWMA mean + EWMA absolute-deviation (MAD-style) band, warm-up gated.

    The deviation floor (``max(mad, 5% of |mean|, 1e-9)``) keeps a
    perfectly-steady warm-up (mad 0) from turning numeric jitter into
    infinite deviations — the same degenerate-case guard the health
    monitor's quality threshold uses."""

    __slots__ = ("alpha", "warmup", "n", "mean", "mad")

    def __init__(self, alpha: float = 0.25, warmup: int = 4):
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.n = 0
        self.mean = 0.0
        self.mad = 0.0

    def observe(self, x: float, alpha: Optional[float] = None) -> None:
        a = self.alpha if alpha is None else float(alpha)
        x = float(x)
        self.n += 1
        if self.n == 1:
            self.mean = x
            self.mad = 0.0
            return
        dev = abs(x - self.mean)
        self.mean += a * (x - self.mean)
        self.mad += a * (dev - self.mad)

    @property
    def ready(self) -> bool:
        return self.n >= self.warmup

    def floor(self) -> float:
        return max(self.mad, 0.05 * abs(self.mean), 1e-9)

    def deviation(self, x: float) -> Optional[float]:
        """Signed deviation of ``x`` from the baseline mean, in floored
        MAD units. None while warming up — warm-up NEVER fires."""
        if not self.ready:
            return None
        return (float(x) - self.mean) / self.floor()


# -- detectors ---------------------------------------------------------------


class AnomalyDetector:
    """Baseline-band detector with hysteresis + cooldown flap suppression.

    One instance covers a whole labeled series family (``key`` = level,
    peer, link, ...) with an independent baseline per key. Lifecycle per
    key: WARM-UP (no fires, baseline learns) -> ARMED -> ``min_breaches``
    consecutive out-of-band observations RAISE -> firing until
    ``clear_breaches`` consecutive in-clear-band observations CLEAR ->
    ``cooldown_s`` suppresses an immediate re-raise. While breaching, the
    baseline adopts the anomalous values at ``alpha x adopt_frac`` only —
    the healthy regime holds, yet a genuine permanent regime shift
    eventually re-baselines instead of paging forever."""

    MAX_KEYS = 128

    def __init__(
        self,
        kind: str,
        *,
        direction: str = "high",  # "high": above-band anomalous; "low": below
        fire_dev: float = 4.0,
        clear_dev: float = 2.0,
        min_breaches: int = 2,
        clear_breaches: int = 2,
        cooldown_s: float = 10.0,
        warmup: int = 4,
        alpha: float = 0.25,
        adopt_frac: float = 0.125,
        severity: str = SEV_WARN,
        description: str = "",
    ):
        assert direction in ("high", "low")
        self.kind = kind
        self.direction = direction
        self.fire_dev = float(fire_dev)
        self.clear_dev = float(clear_dev)
        self.min_breaches = int(min_breaches)
        self.clear_breaches = int(clear_breaches)
        self.cooldown_s = float(cooldown_s)
        self.warmup = int(warmup)
        self.alpha = float(alpha)
        self.adopt_frac = float(adopt_frac)
        self.severity = severity
        self.description = description
        self._state: Dict[str, dict] = {}

    def _signed(self, dev: float) -> float:
        """Deviation in the BAD direction (positive = worse)."""
        return dev if self.direction == "high" else -dev

    def observe(self, now: float, value: float, key: str = "") -> List[dict]:
        st = self._state.get(key)
        if st is None:
            if len(self._state) >= self.MAX_KEYS:
                return []
            st = self._state[key] = {
                "base": OnlineBaseline(self.alpha, self.warmup),
                "breach": 0, "inband": 0, "firing": False,
                "since": 0.0, "last_clear": float("-inf"), "value": None,
            }
        base: OnlineBaseline = st["base"]
        dev = base.deviation(value)
        bad = dev is not None and self._signed(dev) >= self.fire_dev
        in_clear = dev is None or self._signed(dev) <= self.clear_dev
        # Baseline update: in-band at full alpha; breaching at a crawl.
        base.observe(value, alpha=None if not bad else self.alpha * self.adopt_frac)
        st["value"] = float(value)
        events: List[dict] = []
        if not st["firing"]:
            if bad:
                st["breach"] += 1
                if (
                    st["breach"] >= self.min_breaches
                    and now - st["last_clear"] >= self.cooldown_s
                ):
                    st["firing"] = True
                    st["since"] = now
                    st["inband"] = 0
                    events.append(self._event("alert_raised", now, key, st, dev))
            else:
                st["breach"] = 0
        else:
            if in_clear:
                st["inband"] += 1
                if st["inband"] >= self.clear_breaches:
                    st["firing"] = False
                    st["breach"] = 0
                    st["last_clear"] = now
                    events.append(self._event("alert_cleared", now, key, st, dev))
            else:
                st["inband"] = 0
        return events

    def _event(self, action: str, now: float, key: str, st: dict, dev) -> dict:
        return {
            "action": action,
            "kind": self.kind,
            "key": key,
            "severity": self.severity,
            "value": round(float(st["value"]), 6),
            "baseline": round(float(st["base"].mean), 6),
            "deviation": round(float(dev), 3) if dev is not None else None,
            "since": round(st["since"], 6),
            "t": round(now, 6),
        }

    def firing(self, key: str = "") -> bool:
        st = self._state.get(key)
        return bool(st and st["firing"])

    def drop_key(self, now: float, key: str) -> List[dict]:
        """Retire a key whose series went away (a departed peer): frees
        its slot under MAX_KEYS and CLEARS any firing alert — a series
        that stopped existing must not page forever."""
        st = self._state.pop(key, None)
        if st is None or not st["firing"]:
            return []
        return [self._event("alert_cleared", now, key, st, None)]


class StreakDetector:
    """Boolean-series detector: RAISE after ``bad_streak`` consecutive bad
    observations, CLEAR after ``good_streak`` consecutive good ones —
    hysteresis for series where 'how bad' is meaningless (a beat either
    failed over or it didn't, a peer is flagged or it isn't)."""

    MAX_KEYS = 128

    def __init__(
        self,
        kind: str,
        *,
        bad_streak: int = 3,
        good_streak: int = 2,
        severity: str = SEV_WARN,
        description: str = "",
    ):
        self.kind = kind
        self.bad_streak = int(bad_streak)
        self.good_streak = int(good_streak)
        self.severity = severity
        self.description = description
        self._state: Dict[str, dict] = {}

    def observe(self, now: float, bad: bool, key: str = "") -> List[dict]:
        st = self._state.get(key)
        if st is None:
            if len(self._state) >= self.MAX_KEYS:
                return []
            st = self._state[key] = {
                "bad": 0, "good": 0, "firing": False, "since": 0.0,
            }
        events: List[dict] = []
        if bad:
            st["bad"] += 1
            st["good"] = 0
        else:
            st["good"] += 1
            st["bad"] = 0
        if not st["firing"] and st["bad"] >= self.bad_streak:
            st["firing"] = True
            st["since"] = now
            events.append(self._event("alert_raised", now, key, st))
        elif st["firing"] and st["good"] >= self.good_streak:
            st["firing"] = False
            events.append(self._event("alert_cleared", now, key, st))
        return events

    def _event(self, action: str, now: float, key: str, st: dict) -> dict:
        return {
            "action": action,
            "kind": self.kind,
            "key": key,
            "severity": self.severity,
            "value": float(st["bad"]),
            "baseline": 0.0,
            "deviation": None,
            "since": round(st["since"], 6),
            "t": round(now, 6),
        }

    def firing(self, key: str = "") -> bool:
        st = self._state.get(key)
        return bool(st and st["firing"])


class StallDetector:
    """No-new-minimum detector for series that are supposed to keep being
    DRIVEN DOWN (cross-zone sketch dispersion: every cross rotation should
    produce a new low). Observations are fed only when the series moves
    (the caller dedups repeats); STALLED when the newest ``window``
    observations contain no value meaningfully below the previous window's
    minimum AND stay above ``floor`` — robust to the healthy intra/cross
    sawtooth, where dispersion re-grows between cross rotations but each
    cross rotation still sets a lower low."""

    def __init__(
        self,
        kind: str = "mixing_stall",
        *,
        window: int = 3,
        improve_tol: float = 0.1,
        floor: float = 0.05,
        severity: str = SEV_WARN,
        description: str = "",
    ):
        self.kind = kind
        self.window = int(window)
        self.improve_tol = float(improve_tol)
        self.floor = float(floor)
        self.severity = severity
        self.description = description
        self._hist: "deque[float]" = deque(maxlen=2 * self.window)
        self._firing = False
        self._since = 0.0
        self._last: Optional[float] = None

    def observe(self, now: float, value: float) -> List[dict]:
        value = float(value)
        if self._last is not None and value == self._last:
            return []  # not a new observation: nothing rotated
        self._last = value
        self._hist.append(value)
        if len(self._hist) < 2 * self.window:
            return []
        vals = list(self._hist)
        prev_min = min(vals[: self.window])
        new_min = min(vals[self.window:])
        stalled = (
            new_min >= (1.0 - self.improve_tol) * prev_min
            and new_min >= self.floor
        )
        events: List[dict] = []
        if stalled and not self._firing:
            self._firing = True
            self._since = now
            events.append(self._event("alert_raised", now, value, prev_min))
        elif self._firing and not stalled:
            self._firing = False
            events.append(self._event("alert_cleared", now, value, prev_min))
        return events

    def _event(self, action: str, now: float, value: float, prev_min: float) -> dict:
        return {
            "action": action,
            "kind": self.kind,
            "key": "",
            "severity": self.severity,
            "value": round(value, 9),
            "baseline": round(prev_min, 9),
            "deviation": None,
            "since": round(self._since, 6),
            "t": round(now, 6),
        }

    def firing(self) -> bool:
        return self._firing


# -- volunteer-side watchdog -------------------------------------------------


def _fold_hist(hist: list, value: float) -> None:
    """Fold one duration into a [counts, count, sum] record over the
    telemetry plane's shared log2 buckets (mergeable cross-volunteer)."""
    counts = hist[0]
    for i, ub in enumerate(HIST_BUCKETS):
        if value <= ub:
            counts[i] += 1
            break
    else:
        counts[-1] += 1
    hist[1] += 1
    hist[2] += float(value)


def hist_quantile(counts: List[int], q: float) -> Optional[float]:
    """Quantile estimate from shared-bucket counts (upper bound of the
    bucket the q-th observation lands in; +inf bucket reports the last
    finite bound x2 — a pessimistic, monotone tail estimate)."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= rank:
            if i < len(HIST_BUCKETS):
                return float(HIST_BUCKETS[i])
            return float(HIST_BUCKETS[-1] * 2.0)
    return float(HIST_BUCKETS[-1] * 2.0)


class Watchdog:
    """Per-volunteer streaming anomaly detection over the telemetry plane.

    Fed from two directions: :meth:`observe_span` consumes ended round
    spans (the tracer's ``on_record`` hook — per-level round wall), and
    :meth:`tick` — called once per report beat — samples the wired probes
    (commit counter, mass fraction, bandwidth EWMAs, beat outcomes).
    Alert transitions land in the flight recorder and the registry;
    :meth:`summary` is the compact per-beat view riding the report."""

    MAX_LEVELS = 8
    # Round-wall histogram window: p99 is estimated over the last 1-2
    # half-windows (5-10 min), so the SLO sees an inflation at report
    # cadence and stops burning within a window of heal.
    WALL_WINDOW_S = 600.0

    def __init__(
        self,
        registry=None,
        recorder=None,
        peer_id: str = "",
        enabled: bool = True,
        clock: Callable[[], float] = time.time,
    ):
        self.registry = registry
        self.recorder = recorder
        self.peer_id = peer_id
        self.enabled = enabled
        self.clock = clock
        self._lock = threading.Lock()
        self.detectors: Dict[str, Any] = {
            "commit_rate_collapse": AnomalyDetector(
                "commit_rate_collapse", direction="low", severity=SEV_PAGE,
                description="committed-round rate collapsed vs baseline",
            ),
            "round_wall_inflation": AnomalyDetector(
                "round_wall_inflation", direction="high", severity=SEV_WARN,
                description="round wall (per level) inflated vs baseline",
            ),
            "mass_frac_drop": AnomalyDetector(
                "mass_frac_drop", direction="low", severity=SEV_WARN,
                description="committed gradient-mass fraction dropped",
            ),
            "peer_bw_collapse": AnomalyDetector(
                "peer_bw_collapse", direction="low", severity=SEV_WARN,
                description="per-peer bandwidth EWMA collapsed",
            ),
            "cp_beat_failures": StreakDetector(
                "cp_beat_failures", bad_streak=3, good_streak=2,
                severity=SEV_WARN,
                description="control-plane beat failure streak",
            ),
            "byzantine_contributor": StreakDetector(
                "byzantine_contributor", bad_streak=1, good_streak=2,
                severity=SEV_PAGE,
                description="contribution-quality flag on a peer",
            ),
        }
        # Wired sample sources, called each tick with (now, dt, feed).
        self._probes: List[Callable[[float, Optional[float]], None]] = []
        self._firing: Dict[Tuple[str, str], dict] = {}
        self.raised_total = 0
        self.cleared_total = 0
        self._last_tick: Optional[float] = None
        # Per-level round-wall histograms over the SHARED telemetry
        # buckets: the report-beat evidence the replica merges for the
        # p99-per-level SLO (count/sum alone cannot give a p99). WINDOWED
        # — two half-window generations rotated in place, summary reports
        # their merge — because a lifetime-cumulative p99 both detects an
        # inflation late (N healthy rounds dilute it) and stays burning
        # long after heal. NOT a telemetry.Histogram: those are
        # cumulative by contract (counters merge across restarts); this
        # is a sliding estimate.
        self._wall_hists: Dict[str, Dict[str, list]] = {}
        self._wall_rotated: Optional[float] = None
        if enabled and registry is not None:
            self._alert_ctr = registry.counter(
                "swarm.watchdog.alerts_total",
                "alert transitions by kind and action",
            )
            self._firing_gauge = registry.gauge_fn(
                "swarm.watchdog.firing", lambda: float(len(self._firing)),
                "alerts currently firing on this volunteer",
            )
        else:
            self._alert_ctr = None

    # -- feeding -----------------------------------------------------------

    def observe_span(self, span: dict) -> None:
        """Tracer ``on_record`` hook: per-level round-wall observations
        (the round span carries ``level`` in its attrs)."""
        if not self.enabled:
            return
        try:
            if span.get("name") != "round":
                return
            dur = span.get("dur_s")
            if dur is None:
                return
            level = str((span.get("attrs") or {}).get("level") or "flat")
            now = self.clock()
            with self._lock:
                if self._wall_rotated is None:
                    self._wall_rotated = now
                elif now - self._wall_rotated >= self.WALL_WINDOW_S / 2:
                    # Rotate generations: current -> prev, fresh current.
                    for gens in self._wall_hists.values():
                        gens["prev"] = gens["cur"]
                        gens["cur"] = [[0] * (len(HIST_BUCKETS) + 1), 0, 0.0]
                    self._wall_rotated = now
                gens = self._wall_hists.get(level)
                if gens is None:
                    if len(self._wall_hists) >= self.MAX_LEVELS:
                        return
                    gens = self._wall_hists[level] = {
                        "cur": [[0] * (len(HIST_BUCKETS) + 1), 0, 0.0],
                        "prev": None,
                    }
                _fold_hist(gens["cur"], float(dur))
                events = self.detectors["round_wall_inflation"].observe(
                    now, float(dur), key=level
                )
            self._emit(events)
        except Exception as e:  # noqa: BLE001 — the watchdog must never fail a round
            log.debug("watchdog span observation failed: %s", errstr(e))

    def observe(self, kind: str, value: float, key: str = "") -> None:
        """Feed one observation into a baseline detector by kind (the
        probes and tests use this; unknown kinds are ignored)."""
        if not self.enabled:
            return
        det = self.detectors.get(kind)
        if det is None or not isinstance(det, AnomalyDetector):
            return
        with self._lock:
            events = det.observe(self.clock(), value, key=key)
        self._emit(events)

    def observe_bool(self, kind: str, bad: bool, key: str = "") -> None:
        if not self.enabled:
            return
        det = self.detectors.get(kind)
        if det is None or not isinstance(det, StreakDetector):
            return
        with self._lock:
            events = det.observe(self.clock(), bool(bad), key=key)
        self._emit(events)

    def annotate(self, kind: str, key: str, **fields) -> None:
        """Merge advisory context onto a FIRING alert (no-op otherwise):
        e.g. the hedged-recovery scorecard onto ``mass_frac_drop``, so the
        alert itself says whether an automated mitigation is already
        recovering the mass. Annotations ride the firing dict into
        ``alerts()``/``summary()``; they never change alert lifecycle."""
        if not self.enabled:
            return
        with self._lock:
            alert = self._firing.get((kind, key))
            if alert is not None:
                alert.update({k: v for k, v in fields.items() if v is not None})

    def retire_key(self, kind: str, key: str) -> None:
        """Drop a detector key whose underlying series went away (peer
        departed): clears any firing alert and frees the key slot."""
        if not self.enabled:
            return
        det = self.detectors.get(kind)
        if det is None or not isinstance(det, AnomalyDetector):
            return
        with self._lock:
            events = det.drop_key(self.clock(), key)
        self._emit(events)

    def add_probe(self, fn: Callable[[float, Optional[float]], None]) -> None:
        """Register a tick-time sampler ``fn(now, dt)`` that calls
        ``observe``/``observe_bool`` with fresh values. ``dt`` is None on
        the first tick (rates undefined)."""
        self._probes.append(fn)

    def wire_volunteer(
        self,
        averager=None,
        control_plane=None,
        health=None,
        bandwidths: Optional[Callable[[], Dict[str, float]]] = None,
    ) -> None:
        """Wire the stock volunteer probes over the surfaces PRs 1-11
        built. Each probe closes over delta state so repeated samples of
        an unchanged gauge do not fabricate observations."""
        if not self.enabled:
            return
        state: Dict[str, Any] = {}

        def probe(now: float, dt: Optional[float]) -> None:
            if averager is not None:
                ok = int(getattr(averager, "rounds_ok", 0))
                prev = state.get("rounds_ok")
                state["rounds_ok"] = ok
                if prev is not None and dt and dt > 0:
                    self.observe(
                        "commit_rate_collapse", (ok - prev) / dt * 60.0
                    )
            if health is not None and getattr(health, "enabled", False):
                n = int(getattr(health, "mass_rounds", 0))
                if n and state.get("mass_rounds") != n:
                    state["mass_rounds"] = n
                    last = getattr(health, "_last_mass", None)
                    if isinstance(last, dict):
                        # The tighter of the weight and slot views: a
                        # SILENT deadline-dropped straggler's undelivered
                        # weight is unknowable (counts 0 in the weight
                        # balance), so only the slot fraction shows it.
                        fracs = [
                            float(last[k]) for k in
                            ("mass_committed_frac", "slot_committed_frac")
                            if isinstance(last.get(k), (int, float))
                        ]
                        if fracs:
                            self.observe("mass_frac_drop", min(fracs))
                        # Hedged-recovery annotation: stamp the LATEST
                        # round's recovered mass onto any firing mass
                        # alert so an operator (and the doctor) can see
                        # whether the hedger is on the case. Stamped on
                        # every fresh report — zeros included — so a
                        # round where recovery stopped cannot leave a
                        # stale "mitigation active" claim on a
                        # still-firing alert.
                        self.annotate(
                            "mass_frac_drop", "",
                            hedge_recovered_weight=float(
                                last.get("recovered_weight") or 0.0
                            ),
                            hedge_recovered_slots=int(
                                last.get("recovered_slots") or 0
                            ),
                        )
                # Quality flags -> per-peer byzantine alerts. Feed every
                # currently-flagged peer as bad and every previously-fed
                # peer that unflagged as good, so clears happen.
                flagged = set(health.flagged_peers())
                seen = state.setdefault("byz_seen", set())
                for p in flagged | seen:
                    self.observe_bool("byzantine_contributor", p in flagged, key=p)
                seen |= flagged
            if bandwidths is not None:
                try:
                    cur = {
                        str(k): float(bps)
                        for k, bps in (bandwidths() or {}).items()
                        if bps is not None
                    }
                    for k, bps in cur.items():
                        self.observe("peer_bw_collapse", bps, key=k)
                    # Keys that vanished (departed peers, aged-out EWMAs):
                    # retire them, so a firing alert for a gone peer
                    # clears and churned host:port keys do not exhaust
                    # the detector's key cap.
                    for k in state.get("bw_seen", set()) - set(cur):
                        self.retire_key("peer_bw_collapse", k)
                    state["bw_seen"] = set(cur)
                except Exception as e:  # noqa: BLE001 — probe is advisory
                    log.debug("bandwidth probe failed: %s", errstr(e))
            if control_plane is not None:
                failed = int(control_plane.counters.get("calls_failed", 0))
                ok_calls = int(control_plane.counters.get("calls_ok", 0))
                pf, po = state.get("cp_failed", 0), state.get("cp_ok", 0)
                state["cp_failed"], state["cp_ok"] = failed, ok_calls
                if "cp_seeded" in state:
                    # A beat is bad when control-plane calls failed and
                    # none succeeded since the last tick; ticks with no
                    # control traffic at all observe nothing.
                    if failed > pf and ok_calls == po:
                        self.observe_bool("cp_beat_failures", True)
                    elif ok_calls > po:
                        self.observe_bool("cp_beat_failures", False)
                state["cp_seeded"] = True

        self.add_probe(probe)

    def tick(self) -> None:
        """One watchdog evaluation pass: sample every wired probe. Called
        once per report beat (the volunteer report build) or per round in
        the chaos campaigns."""
        if not self.enabled:
            return
        try:
            now = self.clock()
            dt = None if self._last_tick is None else max(now - self._last_tick, 0.0)
            self._last_tick = now
            for probe in self._probes:
                try:
                    probe(now, dt)
                except Exception as e:  # noqa: BLE001 — one probe must not kill the tick
                    log.debug("watchdog probe failed: %s", errstr(e))
        except Exception as e:  # noqa: BLE001
            log.debug("watchdog tick failed: %s", errstr(e))

    # -- alert bookkeeping ---------------------------------------------------

    def _emit(self, events: Iterable[dict]) -> None:
        for ev in events:
            akey = (ev["kind"], ev["key"])
            action = ev.pop("action")
            alert = {
                "kind": ev["kind"],
                "key": ev["key"],
                "severity": ev["severity"],
                "value": ev["value"],
                "baseline": ev["baseline"],
                "since": ev["since"],
            }
            with self._lock:
                if action == "alert_raised":
                    self._firing[akey] = alert
                    self.raised_total += 1
                else:
                    self._firing.pop(akey, None)
                    self.cleared_total += 1
            if self._alert_ctr is not None:
                self._alert_ctr.inc(alert=ev["kind"], action=action.split("_")[1])
            if self.recorder is not None:
                try:
                    self.recorder.record(
                        action,
                        alert=ev["kind"],
                        key=ev["key"],
                        sev=ev["severity"] if action == "alert_raised" else SEV_INFO,
                        value=ev["value"],
                        baseline=ev["baseline"],
                        deviation=ev["deviation"],
                    )
                except Exception:  # noqa: BLE001 — recording is advisory
                    pass

    def alerts(self) -> List[dict]:
        """Currently-firing alerts (deduplicated; sorted for stability)."""
        with self._lock:
            return [
                dict(self._firing[k]) for k in sorted(self._firing)
            ]

    def summary(self) -> Optional[dict]:
        """Compact per-beat watchdog view for the volunteer report (rides
        the batched ``cp.exchange`` beat). None when disabled — the
        heartbeat then carries no alert bytes at all."""
        if not self.enabled:
            return None
        with self._lock:
            firing = [dict(self._firing[k]) for k in sorted(self._firing)]
            walls = {}
            for level, gens in self._wall_hists.items():
                counts = list(gens["cur"][0])
                count, sum_s = gens["cur"][1], gens["cur"][2]
                if gens["prev"] is not None:
                    for i, c in enumerate(gens["prev"][0]):
                        counts[i] += c
                    count += gens["prev"][1]
                    sum_s += gens["prev"][2]
                walls[level] = {
                    "buckets": counts, "count": count,
                    "sum_s": round(sum_s, 6),
                }
            return {
                "schema_version": WATCHDOG_SCHEMA_VERSION,
                "firing": firing,
                "n_firing": len(firing),
                "raised_total": self.raised_total,
                "cleared_total": self.cleared_total,
                "round_wall": walls,
            }


# -- SLO plane ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declarative objective: ``metric`` (a key into the evaluation
    context) must meet ``bound`` (per ``direction``) on at least
    ``target`` of evaluation ticks; burn rates measure budget spend."""

    name: str
    metric: str
    bound: float
    direction: str = "min"  # "min": value >= bound is good; "max": <=
    target: float = 0.9
    fast_s: float = 60.0
    slow_s: float = 300.0
    fast_burn: float = 2.0
    slow_burn: float = 1.0
    per_level: bool = False
    description: str = ""


DEFAULT_SLOS: Tuple[SLO, ...] = (
    SLO(
        "committed_round_rate", metric="commit_rate_per_min", bound=1.0,
        direction="min",
        description="the swarm commits at least this many rounds/min",
    ),
    SLO(
        "round_wall_p99", metric="round_wall_p99", bound=10.0,
        direction="max", per_level=True,
        description="p99 round wall per hierarchy level stays under bound",
    ),
    SLO(
        "mass_committed_frac", metric="mass_committed_frac", bound=0.9,
        direction="min",
        description="committed gradient-mass fraction stays above bound",
    ),
    SLO(
        "status_freshness", metric="status_age_s", bound=30.0,
        direction="max", target=0.95,
        description="the freshest volunteer report stays younger than bound",
    ),
    SLO(
        # Zone-sharded training: how long a departed holder's shard stays
        # unrecovered. The metric is the recent-window MAX across the
        # fleet's ``sharding`` report sections (None when no recovery ran
        # recently — no tick, so unsharded swarms never burn this).
        "shard_recovery_latency", metric="shard_recovery_latency_s",
        bound=15.0, direction="max",
        description="recent shard recoveries complete within bound",
    ),
)

# Minimum ticks in the slow window before a burn verdict counts: two
# bad ticks on an empty window must not page.
MIN_BURN_TICKS = 3


class BurnRateTracker:
    """Fast/slow-window burn-rate accounting for one (SLO, level) pair."""

    def __init__(self, slo: SLO):
        self.slo = slo
        self._ticks: "deque[Tuple[float, bool]]" = deque()
        self.value: Optional[float] = None

    def observe(self, now: float, ok: bool, value: float) -> None:
        self.value = float(value)
        self._ticks.append((now, bool(ok)))
        cutoff = now - self.slo.slow_s
        while self._ticks and self._ticks[0][0] < cutoff:
            self._ticks.popleft()

    def evaluate(self, now: float) -> dict:
        # Time-filtered at EVALUATION, not just at observe: a tracker
        # whose metric became uncomputable (reporters gone) must see its
        # windows drain so a firing burn alert can clear, instead of
        # serving a frozen burn_slow forever.
        slow = [(t, ok) for t, ok in self._ticks if t >= now - self.slo.slow_s]
        fast = [(t, ok) for t, ok in slow if t >= now - self.slo.fast_s]
        budget = max(1.0 - self.slo.target, 1e-6)

        def burn(ticks):
            if not ticks:
                return 0.0
            bad = sum(1 for _, ok in ticks if not ok)
            return (bad / len(ticks)) / budget

        bf, bs = burn(fast), burn(slow)
        return {
            "value": self.value,
            "ticks": len(slow),
            "burn_fast": round(bf, 3),
            "burn_slow": round(bs, 3),
            "burning": (
                len(slow) >= MIN_BURN_TICKS
                and bf >= self.slo.fast_burn
                and bs >= self.slo.slow_burn
            ),
        }


# -- coord.status schema (CI-pinned) -----------------------------------------

# The documented coord.status["slo"] / coord.status["alerts"] sections —
# walked by tests/test_watchdog.py like STATUS_TELEMETRY_SCHEMA, so drift
# breaks CI instead of dashboards. Both sections are ALWAYS dicts (never
# None): the watchdog plane exists the moment a replica does. `age_s` is
# each section's staleness stamp on the telemetry clock — a frozen
# replica is distinguishable from a healthy quiet swarm.
STATUS_WATCHDOG_SCHEMA: Dict[str, Dict[str, type]] = {
    "slo": {
        "schema_version": int,
        "age_s": float,
        "objectives": dict,  # name[.level] -> STATUS_SLO_OBJECTIVE_SCHEMA
    },
    "alerts": {
        "schema_version": int,
        "age_s": float,
        "reporting": int,     # fresh reports that carried a watchdog summary
        "firing": list,       # ALERT_SCHEMA dicts, severity-major order
        "n_firing": int,
        "raised_total": int,  # reporters' lifetime raises + replica-local
        "cleared_total": int,
        "by_kind": dict,      # kind -> firing count
    },
}
# Value schema for one objective row. `value` may be None before the
# metric has ever been computable (e.g. no health reporters yet).
STATUS_SLO_OBJECTIVE_SCHEMA: Dict[str, tuple] = {
    "metric": (str,),
    "bound": (float, int),
    "direction": (str,),
    "target": (float, int),
    "value": (float, int, type(None)),
    "ticks": (int,),
    "burn_fast": (float, int),
    "burn_slow": (float, int),
    "burning": (bool,),
    "window_fast_s": (float, int),
    "window_slow_s": (float, int),
}
# One firing alert as served in coord.status["alerts"]["firing"].
ALERT_SCHEMA: Dict[str, tuple] = {
    "kind": (str,),
    "key": (str,),
    "severity": (str,),
    "peer": (str,),
    "value": (float, int),
    "baseline": (float, int),
    "since": (float, int),
}

_SEV_ORDER = {SEV_PAGE: 0, SEV_WARN: 1, SEV_INFO: 2}


class SwarmWatchdog:
    """Replica-side watchdog: SLO burn rates over the merged rollup, the
    swarm-level detectors no single volunteer can see (cross-zone mixing
    stall), and the alert rollup joining every reporter's firing set.

    One per control-plane replica; :meth:`evaluate` runs once per replica
    tick (and lazily on status serves, spacing-guarded so a status storm
    cannot inflate the burn windows)."""

    MIN_TICK_SPACING_S = 0.25

    def __init__(
        self,
        clock: Callable[[], float] = time.time,
        slos: Tuple[SLO, ...] = DEFAULT_SLOS,
        recorder=None,
        peer_id: str = "",
    ):
        self.clock = clock
        self.slos = tuple(slos)
        self.recorder = recorder
        self.peer_id = peer_id or "coordinator"
        self._trackers: Dict[Tuple[str, str], BurnRateTracker] = {}
        self.stall = StallDetector(
            "mixing_stall", severity=SEV_WARN,
            description="cross-zone sketch dispersion stopped converging",
        )
        self._firing: Dict[Tuple[str, str], dict] = {}
        self.raised_total = 0
        self.cleared_total = 0
        self._last_eval: Optional[float] = None
        self._state: Dict[str, Any] = {}

    # -- evaluation context --------------------------------------------------

    def _context(
        self, fresh: List[dict], multigroup: Optional[dict],
        health: Optional[dict], now: float,
    ) -> Dict[str, Any]:
        ctx: Dict[str, Any] = {}
        # Committed-round rate: the multigroup rollup's windowed rate when
        # present; otherwise a counter delta over the reporters' telemetry
        # round-span counts (covers single-group swarms).
        if multigroup and multigroup.get("commits_per_min") is not None:
            ctx["commit_rate_per_min"] = float(multigroup["commits_per_min"])
        else:
            total = 0
            latest = 0.0
            seen = False
            for m in fresh:
                t = m.get("telemetry")
                if isinstance(t, dict):
                    rec = (t.get("spans") or {}).get("round")
                    if isinstance(rec, dict):
                        total += int(rec.get("count") or 0)
                        seen = True
                        rt = m.get("recv_t")
                        if isinstance(rt, (int, float)):
                            latest = max(latest, float(rt))
            if seen:
                prev = self._state.get("round_total")
                prev_latest = self._state.get("round_latest")
                # Rate over REPORT refreshes, not evaluation ticks: an
                # eval landing between two report beats would otherwise
                # read a zero delta and log a spurious "0 commits/min"
                # bad tick against the SLO (observed live: beat/tick
                # aliasing burned the budget on a healthy swarm).
                if latest and (prev_latest is None or latest > prev_latest):
                    self._state["round_total"] = total
                    self._state["round_latest"] = latest
                    if prev is not None and prev_latest and latest > prev_latest:
                        delta = max(total - prev, 0)
                        ctx["commit_rate_per_min"] = (
                            delta / (latest - prev_latest) * 60.0
                        )
        # p99 round wall per level, merged from the reporters' shared-
        # bucket histograms riding the report beat.
        merged: Dict[str, List[int]] = {}
        for m in fresh:
            wd = m.get("watchdog")
            if not isinstance(wd, dict):
                continue
            for level, h in (wd.get("round_wall") or {}).items():
                buckets = h.get("buckets")
                if not isinstance(buckets, list):
                    continue
                acc = merged.setdefault(str(level), [0] * len(buckets))
                if len(acc) == len(buckets):
                    for i, c in enumerate(buckets):
                        acc[i] += int(c)
        ctx["round_wall_p99"] = {
            level: hist_quantile(counts, 0.99) for level, counts in merged.items()
        }
        if health:
            v = (health.get("mass") or {}).get("committed_frac_min")
            if isinstance(v, (int, float)):
                ctx["mass_committed_frac"] = float(v)
        # Shard-recovery latency: worst recent recovery across reporters
        # carrying a ``sharding`` section (zone-sharded swarms only —
        # absent everywhere leaves the metric None and the SLO untouched).
        lat = [
            (m.get("sharding") or {}).get("recent_recovery_latency_s")
            for m in fresh
            if isinstance(m.get("sharding"), dict)
        ]
        lat = [float(v) for v in lat if isinstance(v, (int, float))]
        if lat:
            ctx["shard_recovery_latency_s"] = max(lat)
        recvs = [
            m.get("recv_t") for m in fresh
            if isinstance(m.get("recv_t"), (int, float))
        ]
        if recvs:
            self._state["last_recv"] = max(
                self._state.get("last_recv", 0.0), max(recvs)
            )
        # Freshness from the newest report EVER seen, not just the
        # currently-fresh set: during a total reporter outage the fresh
        # set empties (the replica's FRESH_S filter), and computing age
        # only from it would make the freshness objective go blind — and
        # its firing alert auto-clear — on exactly the severest outage.
        last = self._state.get("last_recv")
        if last:
            ctx["status_age_s"] = max(0.0, now - last)
        return ctx

    # -- evaluation ----------------------------------------------------------

    def evaluate(
        self,
        fresh_reports: List[dict],
        multigroup: Optional[dict] = None,
        health: Optional[dict] = None,
        now: Optional[float] = None,
    ) -> None:
        """One SLO/detector evaluation tick over the merged view. Safe to
        call from both the replica tick and the status path — spacing-
        guarded so double evaluation cannot inflate the burn windows."""
        now = self.clock() if now is None else float(now)
        if self._last_eval is not None and now - self._last_eval < self.MIN_TICK_SPACING_S:
            return
        self._last_eval = now
        try:
            ctx = self._context(fresh_reports, multigroup, health, now)
            events: List[dict] = []
            for slo in self.slos:
                if slo.per_level:
                    pairs = list((ctx.get(slo.metric) or {}).items())
                else:
                    pairs = [("", ctx.get(slo.metric))]
                for level, value in pairs:
                    if value is None:
                        continue
                    tk = (slo.name, level)
                    tr = self._trackers.get(tk)
                    if tr is None:
                        tr = self._trackers[tk] = BurnRateTracker(slo)
                    ok = (
                        value >= slo.bound
                        if slo.direction == "min"
                        else value <= slo.bound
                    )
                    tr.observe(now, ok, value)
            # Raise/clear over ALL trackers, observed this tick or not: a
            # firing burn alert whose metric became uncomputable (health
            # reporters gone, level retired) must still CLEAR as its
            # time-filtered windows drain — the alert plane and the slo
            # section must never contradict each other.
            for (name, level), tr in self._trackers.items():
                res = tr.evaluate(now)
                slo = tr.slo
                akey = ("slo_burn", f"{name}.{level}" if level else name)
                firing = akey in self._firing
                value = tr.value if tr.value is not None else 0.0
                if res["burning"] and not firing:
                    events.append({
                        "action": "alert_raised", "kind": "slo_burn",
                        "key": akey[1], "severity": SEV_PAGE,
                        "value": round(float(value), 6),
                        "baseline": float(slo.bound),
                        "deviation": res["burn_fast"],
                        "since": round(now, 6), "t": round(now, 6),
                    })
                elif firing and not res["burning"] and res["burn_fast"] < 1.0:
                    events.append({
                        "action": "alert_cleared", "kind": "slo_burn",
                        "key": akey[1], "severity": SEV_INFO,
                        "value": round(float(value), 6),
                        "baseline": float(slo.bound),
                        "deviation": res["burn_fast"],
                        "since": self._firing[akey]["since"],
                        "t": round(now, 6),
                    })
            # Cross-zone mixing stall over the health rollup's across-zone
            # sketch dispersion (the signal ROADMAP item 1's controller
            # needs to learn cross_zone_every_k).
            across = ((health or {}).get("mixing") or {}).get("across_zones")
            if isinstance(across, dict) and isinstance(
                across.get("rel"), (int, float)
            ):
                events.extend(self.stall.observe(now, float(across["rel"])))
            self._emit(events)
        except Exception as e:  # noqa: BLE001 — the watchdog must not kill the tick
            log.debug("swarm watchdog evaluation failed: %s", errstr(e))

    def _emit(self, events: Iterable[dict]) -> None:
        for ev in events:
            akey = (ev["kind"], ev["key"])
            action = ev.pop("action")
            if action == "alert_raised":
                self._firing[akey] = {
                    "kind": ev["kind"], "key": ev["key"],
                    "severity": ev["severity"], "value": ev["value"],
                    "baseline": ev["baseline"], "since": ev["since"],
                }
                self.raised_total += 1
            else:
                self._firing.pop(akey, None)
                self.cleared_total += 1
            if self.recorder is not None:
                try:
                    self.recorder.record(
                        action, alert=ev["kind"], key=ev["key"],
                        sev=ev["severity"], value=ev["value"],
                        baseline=ev["baseline"],
                    )
                except Exception:  # noqa: BLE001
                    pass

    # -- status sections -----------------------------------------------------

    def slo_status(self, now: Optional[float] = None) -> dict:
        now = self.clock() if now is None else float(now)
        objectives: Dict[str, dict] = {}
        for (name, level), tr in sorted(self._trackers.items()):
            res = tr.evaluate(now)
            slo = tr.slo
            objectives[f"{name}.{level}" if level else name] = {
                "metric": slo.metric,
                "bound": slo.bound,
                "direction": slo.direction,
                "target": slo.target,
                "value": res["value"],
                "ticks": res["ticks"],
                "burn_fast": res["burn_fast"],
                "burn_slow": res["burn_slow"],
                "burning": res["burning"],
                "window_fast_s": slo.fast_s,
                "window_slow_s": slo.slow_s,
            }
        return {
            "schema_version": WATCHDOG_SCHEMA_VERSION,
            "age_s": round(
                max(0.0, now - self._last_eval) if self._last_eval else -1.0, 3
            ),
            "objectives": objectives,
        }

    def alerts_status(
        self, fresh_reports: List[dict], now: Optional[float] = None
    ) -> dict:
        """The swarm-wide alert rollup: every fresh reporter's firing set
        (riding the report beat) joined with the replica-local swarm-level
        alerts, severity-major."""
        now = self.clock() if now is None else float(now)
        firing: List[dict] = []
        reporting = 0
        raised = self.raised_total
        cleared = self.cleared_total
        for m in fresh_reports:
            wd = m.get("watchdog")
            if not isinstance(wd, dict) or wd.get(
                "schema_version"
            ) != WATCHDOG_SCHEMA_VERSION:
                continue
            reporting += 1
            raised += int(wd.get("raised_total") or 0)
            cleared += int(wd.get("cleared_total") or 0)
            peer = str(m.get("peer", "?"))
            for a in wd.get("firing") or []:
                if isinstance(a, dict):
                    firing.append({**a, "peer": peer})
        for a in self._firing.values():
            firing.append({**a, "peer": self.peer_id})
        firing.sort(
            key=lambda a: (
                _SEV_ORDER.get(a.get("severity"), 9),
                a.get("kind", ""), a.get("peer", ""), a.get("key", ""),
            )
        )
        by_kind: Dict[str, int] = {}
        for a in firing:
            by_kind[a["kind"]] = by_kind.get(a["kind"], 0) + 1
        return {
            "schema_version": WATCHDOG_SCHEMA_VERSION,
            "age_s": round(
                max(0.0, now - self._last_eval) if self._last_eval else -1.0, 3
            ),
            "reporting": reporting,
            "firing": firing,
            "n_firing": len(firing),
            "raised_total": raised,
            "cleared_total": cleared,
            "by_kind": by_kind,
        }
