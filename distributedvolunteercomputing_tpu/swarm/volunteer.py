"""Volunteer lifecycle: join swarm -> collaborative train loop -> leave.

Reference call stack B (SURVEY.md §3): connect to coordinator, DHT join,
announce, build model+optimizer on device, train with periodic averaging,
and on SIGTERM/preemption leave cleanly and flush state.

Threading model: the asyncio loop (swarm services: DHT, heartbeat, averaging
RPC handlers) owns the MAIN thread; the blocking JAX train loop runs in a
worker thread and bridges into the loop per averaging round via
``run_coroutine_threadsafe``. On TPU-VMs the preemption notice arrives as
SIGTERM (BASELINE.json:5) — handled exactly like a user Ctrl-C: stop flag,
final checkpoint, tombstone, exit.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import signal
import threading
import time
import uuid
from typing import Any, Dict, Optional

import jax

from distributedvolunteercomputing_tpu.models import get_model
from distributedvolunteercomputing_tpu.swarm.averager import make_averager
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.membership import SwarmMembership
from distributedvolunteercomputing_tpu.swarm.state_sync import StateSyncService
from distributedvolunteercomputing_tpu.swarm.transport import Transport, read_secret
from distributedvolunteercomputing_tpu.training.trainer import Trainer
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

# Wall-clock cadence the AUTO default resolves to for butterfly params-mode
# swarms (the value both committed A/Bs ran: BASELINE.md config 4b and the
# scale16 butterfly arm).
DEFAULT_BUTTERFLY_INTERVAL_S = 20.0


@dataclasses.dataclass
class VolunteerConfig:
    model: str = "mnist_mlp"
    model_overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # "host:port[,host:port...]" — several = several DHT bootstrap nodes
    # (join works while ANY is alive); None = run standalone.
    coordinator: Optional[str] = None
    host: str = "127.0.0.1"
    port: int = 0
    advertise_host: Optional[str] = None  # dialable address when binding 0.0.0.0
    peer_id: str = ""
    averaging: str = "none"  # none|sync|gossip|butterfly|byzantine
    average_every: int = 10
    # Wall-clock averaging cadence (params mode; 0 = step cadence above).
    # Rounds fire when wall time crosses a multiple of the interval, so
    # clock-synced heterogeneous volunteers rendezvous within ms regardless
    # of step speed; contributions weigh by actual window progress.
    # None = AUTO (the default): butterfly params-mode swarms — the
    # heterogeneous-volunteer config — get the wall-clock cadence at
    # DEFAULT_BUTTERFLY_INTERVAL_S (the step cadence is measured-
    # pathological there at n=4 and n=16: BASELINE.md config 4 vs 4b and
    # the scale16 step-cadence arm); every other mode keeps the step
    # cadence. Pass an explicit 0 to force step cadence anywhere.
    average_interval_s: Optional[float] = None
    average_what: str = "params"  # params (local-SGD) | grads (GradientAverager)
    # Overlap WAN rounds with local compute (params mode; see Trainer). On by
    # default: blocking the device for a whole WAN round is what sinks
    # samples/sec at payload scale (BASELINE.md north-star).
    overlap: bool = True
    max_staleness: int = 0  # steps; 0 = unbounded (rounds self-bound via timeouts)
    wire: str = "f32"  # f32|bf16|q8|topk|powersgd — WAN payload codec
    # wire="topk" fraction: ship only the top |value| fraction of gradient
    # entries per round (error feedback banks the rest). ~50x fewer DCN
    # bytes at 0.01. Grads mode + sync/byzantine only.
    topk_frac: float = 0.01
    # DGC-style sparsity warmup: ramp the kept fraction from dense to
    # topk_frac over the first N successful rounds (0 = off). Early rounds
    # contract init noise and need (nearly) full gradients.
    topk_warmup_rounds: int = 0
    # wire="powersgd" target rank: each >=2D gradient tensor ships as a
    # rank-r (P, Q) pair — (n+m)·r floats instead of n·m — with warm-started
    # power iteration + the same error feedback as topk. Unlike topk it
    # composes with the robust estimators (reconstructions are dense), so
    # byzantine mode keeps its guarantees. Grads mode + sync/byzantine only.
    powersgd_rank: int = 4
    min_group: int = 2
    max_group: int = 16
    # Multi-group round scheduling (Moshpit-style): partition the live
    # swarm into many groups of ~this size per round via a rotating seeded
    # hash grid over the DHT keyspace, instead of one group per epoch —
    # swarm-wide sync throughput stops being capped by one leader's NIC,
    # and group averages still mix globally in O(log N) rounds because the
    # grid re-seeds every rotation. 0 = off (classic single-group
    # rendezvous). Gather-style modes only (sync/byzantine/butterfly).
    group_size: int = 0
    # Rotation cadence of the group schedule, seconds. 0 = AUTO: the
    # wall-clock averaging interval when one is set (one fresh grid per
    # round boundary), else 15s. Every member of a prospective group must
    # land in the same rotation window to rendezvous, so wall-cadence
    # swarms (clock-synced) are the natural fit.
    group_rotation_s: float = 0.0
    # Locality zone this volunteer advertises in its membership record
    # (e.g. "dc-eu1", "home-us"): volunteers in the same zone share fast
    # links. "" = unzoned. Advertised regardless of scheduling mode; the
    # hierarchical schedule below consumes it.
    zone: str = ""
    # Hierarchical two-level scheduling cadence: with a group schedule and
    # >= 2 advertised zones live, every k-th rotation runs the zone-blind
    # CROSS-zone mixing grid and the rest stay INTRA-zone (groups never
    # span a zone boundary, so those rounds move zero cross-zone bytes).
    # 0 = flat single-level grid. Degrades to flat automatically while
    # fewer than two zones are advertised (mixed-version swarms).
    cross_zone_every_k: int = 0
    # Zone-sharded training (swarm/sharding.py): partition the averaged
    # parameter tree into K zone-local shards — this volunteer holds its
    # HRW-assigned shard(s), advertises its primary shard so cross-zone
    # rotations rendezvous same-shard holders (~1/K wire bytes/round),
    # and runs the fenced re-shard + hedged-recovery autopilot on zone
    # churn. 0 = unsharded (full replica).
    zone_shards: int = 0
    batch_size: int = 32  # samples per optimizer step (across accum microbatches)
    # Scan up to N steps inside one compiled call between cadence points
    # (host-loop amortization; params mode, no mesh). 1 = off.
    steps_per_call: int = 1
    accum_steps: int = 1  # gradient-accumulation microbatches inside the step
    data_path: Optional[str] = None  # .npz real-data file; None = synthetic
    optimizer: str = "adam"
    lr: float = 1e-3
    seed: int = 0  # per-volunteer: data order + step rng
    init_seed: int = 0  # TASK-constant: shared initial params (see Trainer)
    param_dtype: Optional[str] = None  # e.g. "bfloat16" for bf16 training
    steps: int = 1000
    target_loss: Optional[float] = None
    # "stop" ends the run at the target; "record" trains the full --steps
    # and reports when the target was first crossed (time-to-target-loss).
    target_mode: str = "stop"
    eval_every: int = 0  # 0 = no held-out evaluation
    eval_batches: int = 4
    metrics_path: Optional[str] = None
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 200
    heartbeat_ttl: float = 15.0
    join_timeout: float = 10.0
    gather_timeout: float = 20.0
    method: str = "mean"  # robust aggregation estimator for byzantine mode
    # Estimator keyword overrides (krum/bulyan n_byzantine, trimmed_mean
    # trim, centered_clip clip_tau/iters, ...) — passed straight through to
    # ops/robust.aggregate. None = each estimator's defaults.
    method_kw: Optional[Dict[str, Any]] = None
    # Adaptive round deadlines (EWMA of successful rounds; see AveragerBase):
    # a dead peer costs seconds instead of the full gather budget.
    adaptive_timeout: bool = False
    # Resilience layer (swarm/resilience.py + swarm/failure_detector.py):
    # phi-accrual liveness feeding straggler pre-exclusion at group
    # formation, plus the adaptive policy engine (learned round deadlines,
    # failure backoff, runtime robust-estimator escalation). Opt-in — the
    # deadline-bounded COMMIT machinery itself is always on (rounds commit
    # with the contributions that arrived by the budget), this flag adds
    # the adaptive/learning layer on top.
    resilience: bool = False
    # phi at/above which a peer counts as suspected (8 ~ one-in-1e8 under
    # the fitted heartbeat model — the classic accrual-detector default).
    phi_threshold: float = 8.0
    # Closed-loop adaptive controller (swarm/controller.py): reads the
    # telemetry plane and retunes, live and epoch-fenced, the averaging
    # topology / dense wire / cross-zone cadence / per-level deadlines /
    # hedge regime. Rides the resilience layer (needs its policy and
    # evidence), so it engages only with --resilience; --no-adapt pins
    # today's static behavior end-to-end — no controller is constructed
    # and no controller bytes ride the report beat.
    adapt: bool = True
    # Static wall-clock budget per averaging round, seconds (0 = use the
    # gather timeout; the resilience policy, when on, supersedes both with
    # its learned deadline). The leader stamps clock()+budget into the
    # round begin; the whole group commits at that instant with whatever
    # contributions arrived, re-weighting the mean over the subset.
    round_deadline_s: float = 0.0
    # DiLoCo-style outer optimizer over params-mode rounds (see Trainer):
    # Nesterov momentum on the per-round aggregate delta instead of adopting
    # the raw mean — convergence-per-round at the same WAN byte budget.
    outer_optimizer: str = "none"  # none | nesterov
    outer_lr: float = 0.7
    outer_momentum: float = 0.9
    # In-slice mesh: "dp=2,tp=2"-style spec over THIS volunteer's local
    # devices (a TPU slice); empty = single-device step. The WAN tier still
    # sees one volunteer either way. ``fsdp`` shards params+optimizer over
    # the mesh's dp axis (ZeRO-3); ``seq_sharded`` turns on ring attention
    # over its sp axis.
    mesh: str = ""
    # On-mesh swarm data path (ops.mesh_codec): run the bf16 wire codec,
    # PowerSGD matmuls, and the leader's tile folds on this volunteer's
    # local device mesh. "auto" selects mesh on TPU silicon and host on
    # CPU platforms; "mesh"/"host" force. Selected once at startup,
    # surfaced in stats()["mesh_codec"], degrades to host on slice failure.
    mesh_codec: str = "auto"
    # Fused ring reduce pipeline for the leader's mean folds
    # (ops/mesh_collective.py): decode + fold + neighbor-forward in one
    # pallas grid step over the codec mesh. "auto" selects ring on TPU
    # silicon with >= 2 codec devices; "ring"/"off" force. Rides the
    # mesh codec's degraded-slice contract.
    mesh_collective: str = "auto"
    fsdp: bool = False
    seq_sharded: bool = False
    sp_impl: str = "ring"  # ring | ulysses (all-to-all seq<->heads)
    # Host a control-plane replica on this volunteer (swarm/control_plane.py):
    # the process serves coord.status / batched cp.exchange heartbeat
    # traffic and becomes an election candidate for the replicated,
    # key-range-sharded control plane — with a few of these in the swarm,
    # coordinator death is a non-event (volunteers fail their control
    # traffic over to a surviving replica within one heartbeat).
    host_replica: bool = False
    # Shared-secret frame authentication (transport-level HMAC): path to a
    # file holding the swarm secret. Every member (coordinator included)
    # must use the same secret; peers without it can't join, spoof
    # identities, or inject contributions. A file, not a flag value —
    # secrets in argv leak via process listings.
    secret_file: Optional[str] = None
    # Byzantine mode + the topk wire is a trap: topk forces method='mean'
    # (robust estimators over sparse supports collapse to zero), so the run
    # would carry the name "byzantine" with ZERO robustness. Refused unless
    # this flag says the caller understands that trade.
    allow_unrobust_topk: bool = False
    # Telemetry plane (swarm/telemetry.py): round tracing, unified metrics
    # registry, flight recorder, and the telemetry.* debug RPCs. On by
    # default (the record paths are ring-buffer appends; the overhead smoke
    # in tests/test_telemetry.py bounds the cost at <5% of commit latency);
    # --no-telemetry turns every record path into a no-op.
    telemetry: bool = True
    # Training-health layer (swarm/health.py): post-round parameter
    # sketches (live mixing error), gradient-mass accounting, per-peer
    # contribution quality, codec distortion. On by default (the health
    # overhead smoke in tests/test_health.py bounds the cost at <5% of
    # commit latency); --no-health-probe disables the sketch computation
    # and every health tally end-to-end — no sketch bytes ride the
    # heartbeat report — while the rest of the telemetry plane stays on.
    # --no-telemetry disables both.
    health_probe: bool = True
    # Swarm watchdog (swarm/watchdog.py): streaming anomaly detectors
    # (commit-rate collapse, per-level round-wall inflation, mass-fraction
    # drops, bandwidth collapse, control-plane beat failure streaks,
    # quality-flag alerts) with hysteresis + cooldown, riding the report
    # beat as a compact firing set. On by default; --no-watchdog disables
    # every detector end-to-end — no alert bytes ride the heartbeat —
    # while tracing/health stay on. --no-telemetry disables everything.
    watchdog: bool = True
    # Tail-optimal hedged recovery (ISSUE 14, docs/PERFORMANCE.md): when
    # this volunteer LEADS a streaming round, predicted-late peers'
    # missing tile ranges are re-requested over a second stream ahead of
    # the deadline (sync.refetch, idempotent per tile). On by default —
    # it spends idle gather wait, never the deadline; --no-hedge restores
    # pure deadline-drop.
    hedge: bool = True
    # Optional summand redundancy: each contribution's last-k% tiles ride
    # XOR-coded on the ring successor's sidecar, decodable by the leader
    # iff the original misses commit. 0.0 = off (costs one extra k%-sized
    # member-to-member transfer per round when on).
    tail_redundancy_frac: float = 0.0
    # Local Prometheus text endpoint (GET /metrics) for stock scrapers:
    # 0 = off (the telemetry.prom debug RPC always answers on the swarm
    # transport regardless).
    metrics_port: int = 0

    def __post_init__(self):
        if not self.peer_id:
            self.peer_id = f"vol-{uuid.uuid4().hex[:8]}"
        if self.average_interval_s is None:
            # AUTO cadence (VERDICT r5 #5): butterfly is the heterogeneous-
            # swarm config, and both committed cadence A/Bs (config 4 vs 4b
            # at n=4; scale16 butterfly arms at n=16) show the step cadence
            # parking fast peers / never aligning there. Wall-clock default
            # for butterfly params mode; step cadence everywhere else.
            self.average_interval_s = (
                DEFAULT_BUTTERFLY_INTERVAL_S
                if self.averaging == "butterfly" and self.average_what == "params"
                else 0.0
            )
        if self.average_interval_s < 0:
            raise ValueError(
                f"average_interval_s must be >= 0, got {self.average_interval_s}"
            )
        if self.round_deadline_s < 0:
            raise ValueError(
                f"round_deadline_s must be >= 0, got {self.round_deadline_s}"
            )
        if self.phi_threshold <= 0:
            raise ValueError(
                f"phi_threshold must be > 0, got {self.phi_threshold}"
            )
        if not (0 <= self.metrics_port <= 65535):
            raise ValueError(
                f"metrics_port must be in [0, 65535] (0 = off), got "
                f"{self.metrics_port}"
            )
        if self.group_rotation_s < 0:
            raise ValueError(
                f"group_rotation_s must be >= 0, got {self.group_rotation_s}"
            )
        if self.cross_zone_every_k < 0:
            raise ValueError(
                f"cross_zone_every_k must be >= 0 (0 = flat), got "
                f"{self.cross_zone_every_k}"
            )
        if self.cross_zone_every_k and not self.group_size:
            # Fail at config time (the method/wire validation policy): the
            # hierarchy is a property of the group schedule — without one
            # the flag would silently do nothing for the whole run.
            raise ValueError(
                "--cross-zone-every-k requires --group-size (the hierarchy "
                "schedules the multi-group grid; single-group swarms have "
                "no grid to layer)"
            )
        if self.zone_shards < 0:
            raise ValueError(
                f"zone_shards must be >= 0 (0 = unsharded), got "
                f"{self.zone_shards}"
            )
        if self.zone_shards:
            # Fail at config time (the method/wire validation policy): the
            # shard domain IS the zone, and the shard-scoped rendezvous
            # lives in the group schedule — without either the flag would
            # silently train a full replica.
            if not self.zone:
                raise ValueError(
                    "--zone-shards requires --zone (the zone is the shard "
                    "domain: shards are held within a zone and replicated "
                    "across zones)"
                )
            if self.averaging != "none" and not self.group_size:
                raise ValueError(
                    "--zone-shards with averaging requires --group-size "
                    "(same-shard holders rendezvous through the shard-"
                    "scoped group schedule)"
                )
        if self.group_size:
            # Fail at config time (the method/wire validation policy): the
            # schedule only makes sense for round-structured gather-style
            # modes — gossip has no rounds to group and "none" no averaging.
            if self.group_size < 2:
                raise ValueError(
                    f"group_size must be >= 2 (or 0 = off), got {self.group_size}"
                )
            if self.averaging not in ("sync", "byzantine", "butterfly"):
                raise ValueError(
                    "--group-size requires --averaging sync, byzantine, or "
                    "butterfly (gossip is pairwise — there is no round-"
                    "structured group to schedule)"
                )
            if self.group_size < self.min_group:
                raise ValueError(
                    f"group_size {self.group_size} < min_group "
                    f"{self.min_group}: every scheduled group would be "
                    "refused at formation"
                )
            if self.group_size > self.max_group:
                raise ValueError(
                    f"group_size {self.group_size} > max_group "
                    f"{self.max_group}: the leader freezes at max_group, so "
                    "the surplus members of every scheduled group would "
                    "join-retry until the deadline and skip the round"
                )
        if self.average_interval_s > 0:
            if self.average_what != "params":
                raise ValueError(
                    "--average-interval-s requires --average-what params "
                    "(gradient rounds are per-step by definition)"
                )
            if self.averaging == "none":
                raise ValueError("--average-interval-s requires an averaging mode")
        if self.param_dtype:
            import jax.numpy as jnp

            try:
                dt = jnp.dtype(self.param_dtype)
            except TypeError:
                raise ValueError(
                    f"unknown --param-dtype {self.param_dtype!r}"
                ) from None
            if not jnp.issubdtype(dt, jnp.floating):
                # int8 would truncate weights at the cast and TypeError in
                # jax.grad at step 1 — fail here, not after transport binds.
                raise ValueError(
                    f"--param-dtype must be a floating dtype, got {dt}"
                )
        # Fail at config time, not per round: an unknown method (or kwarg)
        # would raise inside every averaging round, be swallowed by the
        # round-failure containment, and leave the volunteer training solo
        # forever with only warnings in the log (r4 advisor: the kwarg
        # validation below used to silently no-op on a typo'd method name —
        # the exact failure it existed to prevent).
        from distributedvolunteercomputing_tpu.ops import robust

        if self.method not in robust.AGGREGATORS:
            raise ValueError(
                f"unknown --method {self.method!r}; "
                f"known: {sorted(robust.AGGREGATORS)}"
            )
        if self.method_kw:
            import inspect

            fn = robust.AGGREGATORS[self.method]
            allowed = set(inspect.signature(fn).parameters) - {"stack", "weights"}
            unknown = set(self.method_kw) - allowed
            if unknown:
                raise ValueError(
                    f"--method-kw keys {sorted(unknown)} are not accepted "
                    f"by method {self.method!r} (accepts: {sorted(allowed)})"
                )
        if self.outer_optimizer != "none":
            if self.average_what != "params":
                raise ValueError("--outer-optimizer requires --average-what params")
            if self.averaging not in ("sync", "byzantine"):
                # The outer step's math assumes every member adopts a COMMON
                # aggregate each round (anchor - average is the swarm's
                # consensus delta). Gossip averages are pairwise — per-round
                # momentum would push each volunteer 1.33x past a DIFFERENT
                # partner's midpoint (lr 0.7, mu 0.9), amplifying
                # disagreement; butterfly degrades to subset averages under
                # churn with the same issue. Only the gather-style modes,
                # where all members adopt one aggregate, are validated
                # (experiments/outer_opt.py).
                raise ValueError(
                    "--outer-optimizer requires --averaging sync or byzantine "
                    "(gossip/butterfly rounds are pairwise/subset averages, "
                    "not a common aggregate — momentum over them amplifies "
                    "disagreement)"
                )
        if self.wire == "powersgd":
            # Fail at config time (same policy as topk below). Low-rank of a
            # parameter tree would truncate the model itself, and pairwise
            # protocols compound truncation per hop — but robust estimators
            # are FINE: reconstructions are dense vectors.
            if self.average_what != "grads":
                raise ValueError("wire='powersgd' requires --average-what grads")
            if self.averaging not in ("sync", "byzantine"):
                raise ValueError(
                    "wire='powersgd' requires --averaging sync or byzantine"
                )
            if self.powersgd_rank < 1:
                raise ValueError(
                    f"powersgd_rank must be >= 1, got {self.powersgd_rank}"
                )
        if self.wire == "sign":
            # Same config-time policy as topk/powersgd: 1-bit EF-signSGD is
            # a gradient compressor for gather-style protocols. Robust
            # estimators ARE allowed (dense ±scale reconstructions).
            if self.average_what != "grads":
                raise ValueError("wire='sign' requires --average-what grads")
            if self.averaging not in ("sync", "byzantine"):
                raise ValueError(
                    "wire='sign' requires --averaging sync or byzantine"
                )
        if self.wire == "topk":
            # Fail at config time, before the transport binds or membership
            # announces anything. Top-k of a parameter tree would zero most
            # of the model; pairwise protocols compound truncation per hop;
            # robust estimators over sparse supports aggregate to zero.
            if self.average_what != "grads":
                raise ValueError("wire='topk' requires --average-what grads")
            if self.averaging not in ("sync", "byzantine"):
                raise ValueError(
                    "wire='topk' requires --averaging sync or byzantine"
                )
            if self.topk_warmup_rounds < 0:
                raise ValueError(
                    f"topk_warmup_rounds must be >= 0, got {self.topk_warmup_rounds}"
                )
            if self.averaging == "byzantine":
                if self.method != "mean":
                    raise ValueError("wire='topk' requires --method mean")
                if not self.allow_unrobust_topk:
                    raise ValueError(
                        "--averaging byzantine --wire topk runs a plain "
                        "weighted mean (topk forces method='mean'), i.e. NO "
                        "Byzantine tolerance; use --averaging sync with "
                        "topk, or pass --allow-unrobust-topk if you want "
                        "byzantine's full-mesh/first-write-wins transport "
                        "properties without a robust estimator"
                    )


def _parse_addrs(spec: Optional[str]) -> list:
    """``host:port[,host:port...]`` -> [(host, port), ...]. Several
    coordinators = several DHT bootstrap nodes: a volunteer can join (and a
    rejoiner can re-bootstrap) as long as ANY of them is alive."""
    if not spec:
        return []
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"bad coordinator address {part!r} in {spec!r}: expected host:port"
            )
        out.append((host, int(port)))
    return out


class Volunteer:
    def __init__(self, cfg: VolunteerConfig):
        self.cfg = cfg
        # Telemetry plane: one bundle per volunteer process, shared by the
        # averager, membership, resilience policy, and mesh codec. Built
        # first so every later subsystem can register into it; adopts the
        # ClockSync-corrected clock once one exists (start()).
        from distributedvolunteercomputing_tpu.swarm.telemetry import Telemetry

        self.telemetry = Telemetry(
            peer_id=cfg.peer_id, enabled=cfg.telemetry,
            health_enabled=cfg.telemetry and cfg.health_probe,
            watchdog_enabled=cfg.telemetry and cfg.watchdog,
        )
        self._metrics_server = None
        # Structured-log identity: with DVC_LOG_JSON=1 every line this
        # process emits carries who/where, join-able against traces.
        # First volunteer wins — the fields are process-global, and in a
        # multi-volunteer test process a later construction must not
        # relabel earlier volunteers' lines (round-scoped lines always
        # carry the exact peer via the averager's ambient log_context).
        from distributedvolunteercomputing_tpu.utils.logging import (
            current_log_context,
            set_log_fields,
        )

        if "peer" not in current_log_context():
            set_log_fields(peer=cfg.peer_id, zone=cfg.zone or None)
        self.transport = Transport(
            cfg.host, cfg.port, advertise_host=cfg.advertise_host,
            secret=read_secret(cfg.secret_file),
        )
        self.dht = DHTNode(self.transport)
        self.membership: Optional[SwarmMembership] = None
        self.control_plane = None  # ControlPlaneClient (failover routing)
        self.replica = None        # ControlPlaneReplica when host_replica
        self.clocksync = None
        self.failure_detector = None
        self.resilience_policy = None
        self.controller = None
        self.averager = None
        self.shard_manager = None  # ShardManager when zone_shards
        self.state_sync: Optional[StateSyncService] = None
        self.trainer: Optional[Trainer] = None
        self._stop = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.summary: Dict[str, float] = {}

    # -- averager bridge (called from the trainer thread) ------------------

    def _averager_callback(self, params, step: int):
        if self.averager is None or self._stop.is_set():
            return None
        # Fault-injection hook (SURVEY.md §5): DVC_CHAOS_CONTRIB_SCALE=<x>
        # turns this volunteer BYZANTINE — it contributes its real tree
        # scaled by x (well-formed frames, garbage values; the case CRCs
        # can't catch and robust aggregation exists for). Test-only; unset
        # in production.
        chaos_scale = float(os.environ.get("DVC_CHAOS_CONTRIB_SCALE", "0") or 0.0)
        if chaos_scale:
            import numpy as np

            params = jax.tree_util.tree_map(
                lambda x: np.asarray(x, np.float32) * chaos_scale, params
            )
        # Weight = samples behind this contribution: one batch for a
        # gradient round; for a parameter round, the trainer's actual
        # steps-since-last-merge (== average_every on the happy step-cadence
        # path, more after failed rounds, and the per-volunteer window
        # progress under --average-interval-s — heterogeneous peers weigh
        # by what they really computed).
        if self.cfg.average_what == "grads":
            per_round = 1
        else:
            per_round = max(
                1,
                getattr(self.trainer, "steps_since_merge", self.cfg.average_every),
            )
        samples_since = self.cfg.batch_size * per_round
        fut = asyncio.run_coroutine_threadsafe(
            self.averager.average(params, round_no=step, weight=float(samples_since)),
            self._loop,
        )
        try:
            return fut.result(timeout=self.cfg.join_timeout + self.cfg.gather_timeout + 15.0)
        except Exception as e:
            log.warning("averaging at step %d failed: %s", step, errstr(e))
            return None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        from distributedvolunteercomputing_tpu.utils.asyncio_debug import maybe_enable_from_env

        # DVC_ASYNC_DEBUG=1: loop stall/race detectors (stopped at teardown)
        self._loop_monitor = maybe_enable_from_env()
        await self.transport.start()
        # Debug/collection surface: telemetry.scrape / telemetry.trace /
        # telemetry.flight / telemetry.prom answer on this volunteer's
        # transport (operators and experiments/trace_report.py dial them
        # directly).
        self.telemetry.register_rpcs(self.transport)
        if self.cfg.metrics_port:
            # Local Prometheus endpoint: any stock scraper can watch this
            # volunteer without the coordinator (or the swarm transport).
            from distributedvolunteercomputing_tpu.swarm.telemetry import (
                MetricsHTTPServer,
            )

            # Loopback ONLY: the swarm transport binds cfg.host (often
            # 0.0.0.0) with MAC-covered frames, but this endpoint is
            # plain unauthenticated HTTP serving the full registry — the
            # documented contract is a LOCAL scrape shim, so it must not
            # ride the volunteer's public bind address.
            self._metrics_server = MetricsHTTPServer(
                self.telemetry, "127.0.0.1", self.cfg.metrics_port
            )
            await self._metrics_server.start()
        bootstrap = _parse_addrs(self.cfg.coordinator) or None
        await self.dht.start(bootstrap=bootstrap)
        from distributedvolunteercomputing_tpu.swarm.control_plane import (
            ControlPlaneClient,
            ControlPlaneReplica,
        )

        # Control-plane failover client: discovers the elected replica set
        # from DHT soft state and routes this volunteer's batched
        # heartbeat/report traffic to its key-range shard owner, failing
        # over on conn failure (fast-fail + bounded AIMD backoff). Always
        # constructed — it costs nothing until a replica answers, and the
        # direct DHT path remains the fallback every beat.
        self.control_plane = ControlPlaneClient(
            self.transport, self.dht, self.cfg.peer_id
        )
        if self.cfg.host_replica:
            # This volunteer is an election candidate for the replicated
            # control plane: it serves status/exchange traffic and owns a
            # key range when elected into the active set.
            self.replica = ControlPlaneReplica(
                self.transport, self.dht, telemetry=self.telemetry
            )
            await self.replica.start()
        self._build_resilience_layer()
        extra_info = {
            "model": self.cfg.model,
            # Full averaging namespace (model/average_what): gossip picks
            # partners from membership records (no rendezvous key), so the
            # record must carry the same string the averagers namespace
            # their rounds by — a params-mode peer must never gossip with
            # a grads-mode peer on the same model.
            "avg_ns": f"{self.cfg.model}/{self.cfg.average_what}",
        }
        if self.cfg.zone:
            # Locality advertisement for the hierarchical schedule; absent
            # on unzoned volunteers so mixed-version swarms degrade to
            # flat scheduling instead of treating "" as a real zone name.
            extra_info["zone"] = self.cfg.zone
        self.membership = SwarmMembership(
            self.dht, self.cfg.peer_id, ttl=self.cfg.heartbeat_ttl,
            failure_detector=self.failure_detector,
            extra_info=extra_info,
            # Measured up/down bandwidth rides every heartbeat (refreshed
            # from the transport's bulk-transfer throughput EWMAs; stale
            # estimates age out to absent fields): the input to
            # bandwidth-weighted leader election.
            bandwidth_source=self.transport.bandwidth_advertisement,
            # Batched control plane: announce + metrics report + peers
            # snapshot coalesce into one cp.exchange per heartbeat interval
            # while any replica is reachable (direct DHT fallback per beat).
            control_plane=self.control_plane,
            report_source=self._build_report,
            telemetry=self.telemetry,
        )
        await self.membership.join()
        if self.cfg.average_interval_s > 0:
            # Wall-cadence rendezvous no longer assumes NTP: peer-to-peer
            # clock-offset estimation corrects this volunteer's boundary
            # clock onto swarm-consensus time (swarm/clocksync.py).
            # DVC_CLOCK_SKEW_S injects artificial skew so the e2e suite can
            # prove rendezvous under multi-second skew.
            from distributedvolunteercomputing_tpu.swarm.clocksync import ClockSync

            skew = float(os.environ.get("DVC_CLOCK_SKEW_S", "0") or "0")
            clock = (lambda: time.time() + skew) if skew else time.time
            self.clocksync = ClockSync(self.transport, self.membership, clock=clock)
            # First estimate immediately: the first boundary this volunteer
            # arms must already be on swarm time.
            await self.clocksync.estimate()
            self.clocksync.start(interval_s=max(self.cfg.heartbeat_ttl, 15.0))
            # Span timestamps align to swarm-consensus time: cross-volunteer
            # traces stitch even when volunteer clocks are skewed.
            self.telemetry.set_clock(self.clocksync.now)
        if self.cfg.averaging != "none":
            kw = dict(
                min_group=self.cfg.min_group,
                max_group=self.cfg.max_group,
                join_timeout=self.cfg.join_timeout,
                gather_timeout=self.cfg.gather_timeout,
                wire=self.cfg.wire,
                topk_frac=self.cfg.topk_frac,
                topk_warmup_rounds=self.cfg.topk_warmup_rounds,
                powersgd_rank=self.cfg.powersgd_rank,
                adaptive_timeout=self.cfg.adaptive_timeout,
                # Deadline-bounded rounds: leaders stamp clock()+budget into
                # the begin on the consensus clock when one exists (wall-
                # cadence swarms), else local wall time — the same clock the
                # whole group's members compare the deadline against.
                clock=self.clocksync.now if self.clocksync is not None else None,
                round_deadline_s=self.cfg.round_deadline_s or None,
                resilience=self.resilience_policy,
                failure_detector=self.failure_detector,
                # Closed-loop controller (None under --no-adapt / without
                # --resilience): the averager is both its evidence feed
                # and its actuator.
                controller=self.controller,
                # Matchmaking rendezvous reads ride the replicated control
                # plane's micro-cache when a replica answers (direct DHT
                # fallback otherwise).
                control_plane=self.control_plane,
                # Shared telemetry bundle: round spans, the unified metrics
                # registry, and the flight recorder all live here.
                telemetry=self.telemetry,
                # Tail-optimal hedged recovery (docs/PERFORMANCE.md):
                # soft-deadline re-requests for predicted-late tile ranges
                # when this node leads a streaming round, plus the optional
                # last-k% summand redundancy ring.
                hedge=self.cfg.hedge,
                tail_redundancy_frac=self.cfg.tail_redundancy_frac,
            )
            if self.cfg.group_size:
                from distributedvolunteercomputing_tpu.swarm.matchmaking import (
                    GroupSchedule,
                )

                # Rotation rides the consensus clock when one exists: every
                # member of a prospective group must land in the same
                # window or they rendezvous under different keys.
                kw["group_schedule"] = GroupSchedule(
                    target_size=self.cfg.group_size,
                    rotation_s=self.cfg.group_rotation_s
                    or (self.cfg.average_interval_s or 15.0),
                    clock=self.clocksync.now
                    if self.clocksync is not None
                    else time.time,
                    min_size=self.cfg.min_group,
                    cross_zone_every_k=self.cfg.cross_zone_every_k,
                )
            if self.cfg.averaging == "byzantine" and (
                self.cfg.method != "mean" or self.cfg.wire == "topk"
            ):
                # Passing "mean" explicitly matters for topk: without it the
                # ByzantineAverager defaults to trimmed_mean, which the topk
                # wire (validated in __post_init__) must not run under.
                kw["method"] = self.cfg.method
            if self.cfg.method_kw:
                kw["method_kw"] = dict(self.cfg.method_kw)
            # Namespace rounds by model AND by what is averaged: a grads-mode
            # peer must never rendezvous with a params-mode peer on the same
            # model — averaging a gradient tree against a parameter tree
            # would silently destroy both.
            kw["namespace"] = f"{self.cfg.model}/{self.cfg.average_what}"
            self.averager = make_averager(
                self.cfg.averaging, self.transport, self.dht, self.membership, **kw
            )
        bundle = get_model(self.cfg.model, **self.cfg.model_overrides)
        on_step = None
        if self.cfg.checkpoint_dir and self.cfg.checkpoint_every > 0:
            from distributedvolunteercomputing_tpu.training.checkpoint import save_async

            ckpt_dir, every = self.cfg.checkpoint_dir, self.cfg.checkpoint_every

            def on_step(trainer, step_no):
                # Periodic snapshot: a kill -9 between saves loses at most
                # checkpoint_every steps, not the whole run. Async: the D2H
                # copy happens here, the file write on a background thread —
                # the device never idles on disk I/O.
                if step_no % every == 0:
                    save_async(trainer, ckpt_dir)

        # Heterogeneity injection (test/experiment hook, like
        # DVC_CHAOS_CONTRIB_SCALE below): DVC_STEP_DELAY_MS=<x> slows THIS
        # volunteer's step rate by x ms/step — on a shared localhost core,
        # batch-size spreads don't produce real step-rate skew (per-step
        # overhead dominates), so heterogeneous-cadence experiments need an
        # explicit clock. Unset in production.
        delay_ms = float(os.environ.get("DVC_STEP_DELAY_MS", "0") or 0.0)
        if delay_ms > 0:
            prev_on_step = on_step

            def on_step(trainer, step_no, _prev=prev_on_step):  # noqa: F811
                time.sleep(delay_ms / 1e3)
                if _prev is not None:
                    _prev(trainer, step_no)

        data = None
        eval_data = None
        if self.cfg.data_path:
            import zlib

            from distributedvolunteercomputing_tpu.training.data import npz_batch_iter

            # Seeded per-peer so volunteers shard the shuffle order, not the
            # data: every volunteer sees the full file in a different order.
            # crc32, not hash(): PYTHONHASHSEED randomization would make the
            # per-peer order non-reproducible across restarts.
            data_seed = zlib.crc32(self.cfg.peer_id.encode()) & 0x7FFFFFFF
            data = npz_batch_iter(self.cfg.data_path, self.cfg.batch_size, seed=data_seed)
            if self.cfg.eval_every:
                # Independent shuffled stream over the same file: eval draws
                # never perturb the training order (matches the synthetic
                # path's separate-rng held-out semantics).
                eval_data = npz_batch_iter(
                    self.cfg.data_path, self.cfg.batch_size, seed=data_seed ^ 0x5EED
                )
        mesh = None
        if self.cfg.mesh:
            from distributedvolunteercomputing_tpu.parallel.mesh import (
                make_mesh,
                parse_mesh_spec,
            )

            mesh = make_mesh(**parse_mesh_spec(self.cfg.mesh))
        # Select THIS volunteer's swarm data-path backend now that the
        # local mesh exists (the averager resolves the process default
        # lazily, so configuring here covers the averager built earlier).
        from distributedvolunteercomputing_tpu.ops import mesh_codec as mesh_codec_mod

        codec = mesh_codec_mod.configure(
            mesh=mesh,
            backend=self.cfg.mesh_codec,
            collective=self.cfg.mesh_collective,
        )
        # Slice-loss degrades land in this volunteer's flight recorder.
        codec.recorder = self.telemetry.recorder
        log.info(
            "swarm data path: %s backend (mesh=%s)",
            codec.backend, self.cfg.mesh or "single-device",
        )
        self.trainer = Trainer(
            bundle,
            data=data,
            mesh=mesh,
            fsdp=self.cfg.fsdp,
            seq_sharded=self.cfg.seq_sharded,
            sp_impl=self.cfg.sp_impl,
            batch_size=self.cfg.batch_size,
            optimizer=self.cfg.optimizer,
            lr=self.cfg.lr,
            seed=self.cfg.seed,
            init_seed=self.cfg.init_seed,
            param_dtype=self.cfg.param_dtype,
            accum_steps=self.cfg.accum_steps,
            average_every=self.cfg.average_every,
            average_interval_s=self.cfg.average_interval_s,
            wall_clock=self.clocksync.now if self.clocksync is not None else None,
            steps_per_call=self.cfg.steps_per_call,
            # The checkpoint cadence lives inside on_step where chunk
            # sizing can't see it — declare it so scan chunks end there.
            # The step-delay injection hook also sleeps inside on_step, so
            # scan chunks would dilute it N-fold (and hide it from the
            # interval-cadence step-time EMA): a cadence of 1 forces
            # per-step chunks whenever the hook is active.
            chunk_cadences=(
                ((self.cfg.checkpoint_every,)
                 if self.cfg.checkpoint_dir and self.cfg.checkpoint_every > 0
                 else ())
                + ((1,) if delay_ms > 0 else ())
            ),
            averager=self._averager_callback if self.averager else None,
            average_what=self.cfg.average_what,
            overlap=self.cfg.overlap,
            max_staleness=self.cfg.max_staleness,
            metrics_path=self.cfg.metrics_path,
            volunteer_id=self.cfg.peer_id,
            total_steps=self.cfg.steps,
            on_step=on_step,
            eval_every=self.cfg.eval_every,
            eval_batches=self.cfg.eval_batches,
            eval_data=eval_data,
            outer_optimizer=self.cfg.outer_optimizer,
            outer_lr=self.cfg.outer_lr,
            outer_momentum=self.cfg.outer_momentum,
        )
        if self.averager is not None:
            # Checkpoint sidecars persist the averager's compressor state
            # (EF residual + PowerSGD warm Q) across preemption; the
            # checkpoint module reaches it through this handle.
            self.trainer._wire_averager = self.averager
        if self.cfg.checkpoint_dir:
            from distributedvolunteercomputing_tpu.training.checkpoint import maybe_restore

            maybe_restore(self.trainer, self.cfg.checkpoint_dir)
        if self.cfg.averaging != "none":
            # Peer-pull state sync: catch up to the swarm BEFORE the first
            # step, so a (re)joining volunteer's first averaging round
            # contributes swarm-current weights, not a cold init (or a
            # checkpoint from before a long absence).
            self.state_sync = StateSyncService(
                self.transport, self.dht, self.cfg.peer_id, namespace=self.cfg.model,
                # Serve state over the averaging wire's codec (bf16 halves,
                # q8 quarters a rejoin transfer); topk is grads-only, so
                # such volunteers serve plain f32 snapshots.
                wire=self.cfg.wire if self.cfg.wire in ("bf16", "q8") else "f32",
            )

            # State sync ships the bundle's SYNC SUBTREE (avg_select):
            # identity for full models, adapters-only for LoRA — the frozen
            # base is reconstructed bit-identically from init_seed, so
            # shipping it (~1000x the adapters at llama2_7b scale) would be
            # pure waste. The provider reads the trainer's HOST snapshot,
            # never the live TrainState: the jitted step donates its input
            # buffers, so touching state.params from this (asyncio) thread
            # mid-training would hit deleted arrays.
            def provider():
                step, params = self.trainer.host_snapshot()
                tree = bundle.avg_select(params)
                # Fault-injection hook (SURVEY.md §5), the state-sync twin of
                # DVC_CHAOS_CONTRIB_SCALE: "lie,scale" makes this volunteer a
                # BYZANTINE state provider — it announces/serves step+lie
                # (pull targets the freshest provider, so a big lie attracts
                # every rejoiner) and serves its real tree scaled by `scale`:
                # IN-RANGE garbage the puller's sanity guard cannot catch
                # (finite, bounded), the exact case where the rejoiner's only
                # defense is its next robust averaging round (state_sync.py
                # trust model). Test-only; unset in production.
                poison = os.environ.get("DVC_CHAOS_STATE_POISON")
                if poison:
                    import jax
                    import numpy as np

                    lie, scale = (float(x) for x in poison.split(","))
                    tree = jax.tree_util.tree_map(
                        lambda a: np.asarray(a, np.float32) * scale, tree
                    )
                    step = int(step + lie)
                return step, tree

            self.state_sync.set_provider(provider)
            pulled = await self.state_sync.pull(
                bundle.avg_select(self.trainer.state.params),
                int(self.trainer.state.step),
            )
            if pulled is not None:
                step, subtree = pulled
                self.trainer.adopt_params(
                    bundle.avg_merge(self.trainer.state.params, subtree), step=step
                )
            await self.state_sync.announce()
            if self.cfg.averaging == "gossip" and self.cfg.average_what == "params":
                # Publish the post-state-sync params so exchanges from
                # faster peers succeed BEFORE our first averaging point —
                # without this, startup skew (one peer compiling while the
                # other trains) can burn both peers' entire runs against
                # each other's unpublished window (GossipAverager.publish).
                _, snap = self.trainer.host_snapshot()
                self.averager.publish(bundle.avg_select(snap))
        if self.cfg.zone_shards:
            # Zone-sharded training autopilot: this volunteer holds its
            # HRW shard(s) of the averaged subtree, advertises its primary
            # shard (the shard-scoped rendezvous reads it like a zone),
            # seeds the held shards from the post-state-sync params, and
            # runs the maintenance beat — churn triggers a fenced re-shard
            # + hedged recovery with no operator in the loop.
            import numpy as np

            from distributedvolunteercomputing_tpu.swarm.sharding import (
                ShardManager,
                shard_slice,
            )

            _, snap = self.trainer.host_snapshot()
            leaves = jax.tree_util.tree_leaves(bundle.avg_select(snap))
            flat = np.concatenate(
                [np.asarray(a, np.float32).ravel() for a in leaves]
            ) if leaves else np.zeros(0, np.float32)
            self.shard_manager = ShardManager(
                self.transport, self.dht, self.membership, self.cfg.peer_id,
                n_elems=flat.size, k=self.cfg.zone_shards,
                namespace=f"{self.cfg.model}/{self.cfg.average_what}",
                zone=self.cfg.zone,
                telemetry=self.telemetry,
                resilience=self.resilience_policy,
                controller=self.controller,
            )
            sm = self.shard_manager
            await sm.reshard(recover=False)
            for s in sm.owned():
                sm.store.put(s, shard_slice(flat, sm.ranges, s).copy())
            await sm.announce()
            if self.averager is not None:
                self.averager.shard_manager = sm
                self.telemetry.registry.source("sharding", sm.summary)
            sm.start_maintenance(
                interval_s=max(self.cfg.heartbeat_ttl / 3.0, 2.0)
            )
            log.info(
                "zone-sharded: k=%d zone=%s own=%s (%d/%d elems, gen %d)",
                sm.k, sm.zone, sm.owned(),
                sum(hi - lo for lo, hi in
                    (sm.ranges[s] for s in sm.owned())),
                sm.n_elems, sm.map.gen,
            )
        if self.telemetry.watchdog.enabled:
            # Watchdog probes over the surfaces built above: commit-rate,
            # mass-fraction, per-peer bandwidth EWMAs, control-plane beat
            # outcomes, quality flags (per-level round walls feed via the
            # tracer hook). Ticked once per report beat (_build_report).
            transport = self.transport

            def _peer_bandwidths(max_age_s: float = 120.0) -> Dict[str, float]:
                cutoff = time.monotonic() - max_age_s
                return {
                    f"{host}:{port}": float(st.bw_down_ewma)
                    for (host, port), st in transport._peer_stats.items()
                    if st.bw_down_ewma is not None and st.bw_down_t >= cutoff
                }

            self.telemetry.watchdog.wire_volunteer(
                averager=self.averager,
                control_plane=self.control_plane,
                health=self.telemetry.health,
                bandwidths=_peer_bandwidths,
            )
        log.info(
            "volunteer %s up on %s:%d (model=%s averaging=%s)",
            self.cfg.peer_id, *self.transport.addr, self.cfg.model, self.cfg.averaging,
        )

    def _build_resilience_layer(self) -> None:
        """Construct the resilience layer (phi detector + adaptive policy)
        and, with ``adapt`` on, the closed-loop controller over it.
        Synchronous and side-effect-free beyond the three attributes, so
        the --no-adapt plumbing tests can exercise it without a full
        start(). No-op without --resilience. Called from start() BEFORE
        membership so the very first observed peer records start the
        heartbeat distributions."""
        if not self.cfg.resilience:
            return
        from distributedvolunteercomputing_tpu.swarm.failure_detector import (
            PhiAccrualDetector,
        )
        from distributedvolunteercomputing_tpu.swarm.resilience import (
            ResiliencePolicy,
        )

        self.failure_detector = PhiAccrualDetector(
            threshold=self.cfg.phi_threshold,
            # Heartbeats arrive at the announce cadence (ttl/3, see
            # SwarmMembership.join): seed the bootstrap gap there so a
            # peer heard from once accrues suspicion on the right scale.
            bootstrap_s=max(self.cfg.heartbeat_ttl / 3.0, 1.0),
        )
        self.resilience_policy = ResiliencePolicy(
            max_deadline_s=self.cfg.gather_timeout,
            # A tight-LAN --gather-timeout below the stock 2s deadline
            # floor must not trip the ctor's range check at startup.
            min_deadline_s=min(2.0, float(self.cfg.gather_timeout)),
            initial_deadline_s=self.cfg.round_deadline_s or None,
            failure_detector=self.failure_detector,
            # Escalation/backoff transitions land in the flight recorder.
            recorder=self.telemetry.recorder,
        )
        if self.cfg.adapt and self.cfg.averaging in ("sync", "byzantine"):
            # Closed-loop controller over the policy + telemetry: the
            # averager feeds it evidence and applies its epoch-fenced
            # decisions. Round-structured gather modes only — gossip has
            # no rounds to fence a decision against.
            from distributedvolunteercomputing_tpu.swarm.controller import (
                SwarmController,
            )

            self.controller = SwarmController(
                policy=self.resilience_policy,
                telemetry=self.telemetry,
            )

    def _build_report(self) -> dict:
        """This volunteer's metrics report (the coord.report payload).
        Piggybacked on every batched control-plane exchange by the
        membership heartbeat loop, and sent standalone by the legacy
        report loop while no replica is reachable. May raise when the
        trainer's buffers are donated mid-step — callers skip that report
        rather than die."""
        report = {
            "peer": self.cfg.peer_id,
            "step": int(self.trainer.state.step) if self.trainer else 0,
            "samples_per_sec": self.trainer.metrics.samples_per_sec()
            if self.trainer
            else 0.0,
            **{k: v for k, v in self.summary.items()},
        }
        if self.averager is not None and self.averager._agg_gauges:
            # Live leader-aggregation pipeline gauges (peak bytes
            # held, early/deadline tiles, busy fraction) — reported
            # mid-run so coord.status sees them before the final
            # summary lands.
            report["aggregation"] = dict(self.averager._agg_gauges)
        if self.averager is not None:
            # On-mesh data-path backend + degrade evidence: a slice
            # failure mid-run shows up in coord.status as
            # backend=host/configured=mesh while training continues.
            report["mesh_codec"] = self.averager.mesh_codec.stats()
        if self.telemetry.enabled:
            # Compact telemetry summary (schema version, per-span count/sum
            # pairs, flight-recorder high-water): rides the batched
            # cp.exchange beat via report_source and is rolled up by the
            # control-plane replicas into coord.status["telemetry"].
            report["telemetry"] = self.telemetry.summary()
        wd = self.telemetry.watchdog
        if wd.enabled:
            # One watchdog evaluation pass per report beat (the probes
            # sample commit counters, mass fractions, bandwidth EWMAs,
            # beat outcomes), then the compact firing set rides the same
            # batched cp.exchange the rest of the report does. Absent
            # entirely — no alert bytes on the heartbeat — under
            # --no-watchdog / --no-telemetry.
            wd.tick()
            summary = wd.summary()
            if summary is not None:
                report["watchdog"] = summary
        if self.controller is not None:
            # Closed-loop controller rollup (current policy per level /
            # zone-pair, last transition + reason, transitions/hour):
            # rides the batched beat; replicas roll it into
            # coord.status["controller"]. Absent entirely — no controller
            # bytes on the heartbeat — under --no-adapt (the
            # --no-health-probe pattern).
            report["controller"] = self.controller.summary()
        health = self.telemetry.health.summary()
        if health is not None:
            # Training-health summary (post-round parameter sketch, mass
            # accounting, per-peer quality, codec distortion): rides the
            # same batched beat; replicas roll it into
            # coord.status["health"]. None — and therefore absent, no
            # sketch bytes on the heartbeat — under --no-health-probe.
            report["health"] = health
        if (
            self.averager is not None
            and getattr(self.averager, "group_schedule", None) is not None
        ):
            # Multi-group schedule gauges (current rotation/group,
            # per-group round counters): coord.status rolls these
            # up per group swarm-wide instead of silently averaging
            # across groups.
            report["groups"] = self.averager.group_stats()
        sm = self.shard_manager or getattr(self.averager, "shard_manager", None)
        if sm is not None:
            # Zone-sharded training gauges (map generation, owned/missing
            # shards, recovery latency window): the watchdog's
            # shard_recovery_latency SLO reads this section off the
            # merged fleet view — absent entirely on unsharded swarms.
            report["sharding"] = sm.summary()
        failover_stats = getattr(self.averager, "failover_stats", None)
        if failover_stats is not None:
            fo = failover_stats()
            if (
                fo["leaders_deposed"]
                or fo["rounds_recovered"]
                or fo["recoveries_failed"]
            ):
                # Leader-failover gauges (depositions, recovered
                # rounds, recovery latency): reported mid-run —
                # recovery is exactly the event an operator wants
                # to see from coord.status while it happens.
                report["failover"] = fo
        return report

    async def _report_loop(self) -> None:
        caddrs = _parse_addrs(self.cfg.coordinator)
        caddr = caddrs[0] if caddrs else None
        while not self._stop.is_set():
            await asyncio.sleep(5.0)
            if self.state_sync is not None:
                try:
                    # Re-announce our step so rejoining peers can find the
                    # freshest provider (TTL'd, like heartbeats).
                    await self.state_sync.announce()
                except Exception:
                    pass
            if caddr is None:
                continue
            if self.membership is not None and self.membership.last_beat_batched:
                # The LAST heartbeat went through a replica carrying our
                # report — a standalone coord.report here would double the
                # message cost back up. Gated on the last beat, not the
                # lifetime counter: a volunteer that loses the batched path
                # (asymmetric reachability, replica churn) must resume
                # legacy reports or its metrics age out of coord.status.
                continue
            try:
                # Built INSIDE the try: reading trainer.state from this
                # thread can hit a donated (deleted) buffer mid-step on a
                # real accelerator — that must skip one report, not kill
                # the loop (which also carries the announce() refresh).
                report = self._build_report()
                # Fast-fail dial: a dead coordinator costs the connect
                # budget, never the generic call timeout (the heartbeat
                # loop has its own AIMD-backed fast path; this legacy loop
                # must not lag behind it).
                await self.transport.call(
                    caddr, "coord.report", report, timeout=5.0,
                    connect_timeout=1.5,
                )
            except Exception:
                # Coordinator reachability is not correctness-critical; with
                # several bootstrap coordinators, rotate to the next one so
                # metrics survive a coordinator death.
                if len(caddrs) > 1:
                    caddrs = caddrs[1:] + caddrs[:1]
                    caddr = caddrs[0]

    def _train_blocking(self) -> Dict[str, float]:
        assert self.trainer is not None
        result = self.trainer.run(
            steps=self.cfg.steps,
            target_loss=self.cfg.target_loss,
            target_mode=self.cfg.target_mode,
            stop_flag=self._stop.is_set,
        )
        if self.cfg.checkpoint_dir:
            from distributedvolunteercomputing_tpu.training.checkpoint import (
                latest_step,
                save,
                wait_pending_saves,
            )

            # Final save is SYNCHRONOUS (preemption-safe), after draining any
            # in-flight periodic write so it can't race an older write to the
            # same path. Skip it only when the drained async save covers the
            # current state EXACTLY — same step AND same mutation count; the
            # end-of-run overlap drain can merge averaged params at an
            # unchanged step number, and that merge must not be lost.
            drained = wait_pending_saves(self.trainer)
            # Evaluate AFTER the drain: latest_step only reflects the
            # in-flight write once it has landed.
            current_id = (
                int(self.trainer.state.step),
                getattr(self.trainer, "mutation_counter", 0),
            )
            already_saved = (
                getattr(self.trainer, "_ckpt_snapshot_id", None) == current_id
                and latest_step(self.cfg.checkpoint_dir) == current_id[0]
            )
            if drained and not already_saved:
                save(self.trainer, self.cfg.checkpoint_dir)
        return result

    async def run(self) -> Dict[str, float]:
        await self.start()
        report_task = asyncio.create_task(self._report_loop())
        try:
            self.summary = await asyncio.to_thread(self._train_blocking)
            if self.averager is not None:
                self.summary.update(self.averager.stats())
            # WAN accounting: every byte this volunteer moved over DCN
            # (averaging payloads dominate; DHT/heartbeat traffic is noise).
            # rpcs/connects expose the pooling win directly: pre-pool these
            # were equal (one dial per RPC); pooled, connects stays at
            # ~one-per-peer while rpcs keeps counting.
            if self.shard_manager is not None:
                # Zone-sharding outcome gauges on the done line: the e2e
                # kill matrix asserts recovery happened WITHOUT an epoch
                # restart from exactly these.
                sm = self.shard_manager
                self.summary["shard_gen"] = float(
                    sm.map.gen if sm.map is not None else -1
                )
                self.summary["shard_reshardings"] = float(sm.resharding_count)
                self.summary["shard_recoveries"] = float(sm.recoveries)
                self.summary["shard_recoveries_failed"] = float(
                    sm.recoveries_failed
                )
                self.summary["shard_missing"] = float(len(sm.missing()))
            self.summary["wan_bytes_sent"] = self.transport.bytes_sent
            self.summary["wan_bytes_received"] = self.transport.bytes_received
            self.summary["wan_rpcs"] = self.transport.rpcs_sent
            self.summary["wan_connects"] = self.transport.connects
            return self.summary
        finally:
            self._stop.set()
            report_task.cancel()
            if self.clocksync is not None:
                self.clocksync.stop()
            if self.shard_manager is not None:
                try:
                    await self.shard_manager.stop()
                except Exception:
                    pass
            try:
                await self.membership.leave()
            except Exception:
                pass
            if self.replica is not None:
                try:
                    # Graceful exit of a replica-hosting volunteer: the
                    # retiring tombstone makes the rest of the swarm
                    # re-resolve the active set immediately.
                    await self.replica.retire(grace=0.0)
                except Exception:
                    pass
            await self.dht.stop()
            if self._metrics_server is not None:
                try:
                    await self._metrics_server.close()
                except Exception:
                    pass
            if getattr(self, "_loop_monitor", None) is not None:
                await self._loop_monitor.stop()
            await self.transport.close()

    def install_signal_handlers(self) -> None:
        """SIGTERM == TPU-VM preemption notice; SIGINT == operator stop."""

        def _on_signal(signum, frame):
            log.info("signal %d: stopping after current step (preemption-safe)", signum)
            self._stop.set()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)


def run_volunteer(cfg: VolunteerConfig) -> Dict[str, float]:
    vol = Volunteer(cfg)
    vol.install_signal_handlers()
    return asyncio.run(vol.run())
