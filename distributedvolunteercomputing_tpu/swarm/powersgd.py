"""PowerSGD low-rank gradient compression for the WAN wire.

Vogels et al., "PowerSGD: Practical Low-Rank Gradient Compression for
Distributed Optimization" (NeurIPS 2019): a gradient matrix M [n, m] is
shipped as the rank-r pair (P = MQ orthonormalized, Q' = MᵀP) — (n+m)·r
floats instead of n·m — with one warm-started power iteration per round, and
the truncation error handled by the same error-feedback residual the top-k
wire uses (``AveragerBase._commit_ef``).

Fit to this framework (reference parity: the GradientAverager's compressed
wire, SURVEY.md §2):

- The averager's WAN payloads are ONE flat f32 buffer per tree
  (utils/pytree.flatten_to_buffer). The codec re-views each >=2D leaf as a
  matrix (leading dims flattened), compresses those worth compressing, and
  ships small/1D leaves dense — self-describing container format, so the
  decoder needs no out-of-band schema.
- Unlike the original all-reduce formulation (which shares one Q across
  workers and averages P — brittle under volunteer churn, where a rejoiner
  has no synchronized Q), every contribution carries its own (P, Q') pair
  and the receiver reconstructs the DENSE rank-r estimate before
  aggregation. Linearity is not required, so this composes with the
  byzantine-robust estimators: reconstructions are dense vectors, exactly
  what krum/trimmed-mean/bulyan expect — something the sparse top-k wire
  cannot offer (robust stats over near-disjoint supports collapse to zero;
  see the averager's topk validation).
- Warm start: each encoder keeps its own Q per tensor across rounds; the
  power iteration then tracks the slowly-rotating top singular subspace of
  the gradient stream, which is what makes rank-4 usable in practice.

Host-side numpy throughout: WAN payload prep is host work by design (the
averager runs it off the event loop in worker threads), and n·m·r matmuls
at WAN cadence are BLAS-cheap next to the round's network time.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Container magic. Bump the suffix on any layout change: the magic is the
# only cross-peer versioning (payloads also sit behind the averager's
# schema hash, which folds in the wire tag + rank).
MAGIC = b"PSG1"
_DENSE = 0
_LOWRANK = 1


def _orthonormalize(a: np.ndarray) -> np.ndarray:
    """Thin-QR orthonormal basis of a's columns (f32, [n, r])."""
    q, _ = np.linalg.qr(a.astype(np.float32, copy=False))
    return np.ascontiguousarray(q, dtype=np.float32)


class PowerSGDCodec:
    """Stateful encoder / stateless decoder for one averager's buffers.

    ``specs`` is the averager's TensorSpec list (shapes of the flat
    buffer's leaves, in order). ``rank`` is the target rank; tensors where
    low-rank wouldn't save bytes (1D leaves, tiny matrices) ship dense.
    """

    def __init__(self, specs: Sequence, rank: int = 4, seed: int = 0, mesh_codec=None):
        if rank < 1:
            raise ValueError(f"powersgd rank must be >= 1, got {rank}")
        self.rank = int(rank)
        self.seed = int(seed)
        # On-mesh power iteration (ops.mesh_codec): the per-tensor
        # QR(M·Q) / MᵀP matmuls run on the volunteer's local device mesh
        # when the codec is active; None/inactive keeps host BLAS.
        self.mesh_codec = mesh_codec
        # Per-leaf plan: (offset, size, (n, m, r_eff) | None). A leaf is
        # compressed as [n=prod(shape[:-1]), m=shape[-1]] when that strictly
        # saves floats at its effective rank.
        self.plan: List[Tuple[int, int, Optional[Tuple[int, int, int]]]] = []
        off = 0
        for spec in specs:
            size = spec.size
            lowrank = None
            if len(spec.shape) >= 2 and size > 0:
                m = int(spec.shape[-1])
                n = size // m
                r = min(self.rank, n, m)
                if (n + m) * r < n * m:
                    lowrank = (n, m, r)
            self.plan.append((off, size, lowrank))
            off += size
        self.total = off
        self._warm_q: Dict[int, np.ndarray] = {}

    # -- encode ------------------------------------------------------------

    def _init_q(self, idx: int, m: int, r: int) -> np.ndarray:
        q = self._warm_q.get(idx)
        if q is not None and q.shape == (m, r):
            return _orthonormalize(q)
        rng = np.random.default_rng((self.seed * 1_000_003 + idx) & 0x7FFFFFFF)
        return _orthonormalize(rng.standard_normal((m, r)).astype(np.float32))

    def encode(self, buf: np.ndarray) -> bytes:
        """One warm-started power iteration per planned tensor; updates the
        warm Q state. Returns the self-describing container."""
        if buf.size != self.total:
            raise ValueError(f"buffer size {buf.size} != plan total {self.total}")
        parts = [MAGIC, struct.pack("<I", len(self.plan))]
        for idx, (off, size, lowrank) in enumerate(self.plan):
            chunk = buf[off : off + size]
            if lowrank is None:
                parts.append(struct.pack("<BI", _DENSE, size))
                parts.append(np.ascontiguousarray(chunk, np.float32).tobytes())
                continue
            n, m, r = lowrank
            mat = chunk.reshape(n, m)
            q = self._init_q(idx, m, r)
            mc = self.mesh_codec
            if mc is not None and mc.active:
                p, q_new = mc.low_rank_iterate(mat, q)
            else:
                p = _orthonormalize(mat @ q)  # [n, r]
                q_new = mat.T @ p  # [m, r] — NOT orthonormalized (carries scale)
            self._warm_q[idx] = q_new
            parts.append(struct.pack("<BIIH", _LOWRANK, n, m, r))
            parts.append(p.tobytes())
            parts.append(np.ascontiguousarray(q_new, np.float32).tobytes())
        return b"".join(parts)

    def encode_dense(self, buf: np.ndarray) -> bytes:
        """The same container with every tensor dense — used for round
        RESULTS, which must carry no extra truncation error (no error
        feedback exists on the result path; mirrors the top-k wire's
        dense-results policy)."""
        if buf.size != self.total:
            raise ValueError(f"buffer size {buf.size} != plan total {self.total}")
        return b"".join(
            [
                MAGIC,
                struct.pack("<I", 1),
                struct.pack("<BI", _DENSE, buf.size),
                np.ascontiguousarray(buf, np.float32).tobytes(),
            ]
        )


def _parse_entries(
    payload: bytes, max_floats: Optional[int] = None
) -> List[Tuple[int, tuple]]:
    """[(kind, data)] per entry: dense -> (values,), lowrank -> (n, m, r, P, Q).

    Raises ValueError on ANY malformation (including short reads, which
    struct/numpy report as their own exception types) — the averagers'
    round error containment catches ValueError, and a malicious payload
    must never escape it.

    ``max_floats`` bounds the CUMULATIVE dense-reconstruction size of the
    parsed entries — a low-rank entry counts as its n·m expansion, not its
    (n+m)·r wire floats — and it is enforced HERE, per entry as the walk
    advances, so every consumer of the parse (decode's reconstruction,
    merge's Q·Rᵀ densification) inherits the same resource-exhaustion
    guard. A hostile entry past the cap is rejected before any n·m
    intermediate exists."""
    if len(payload) < 8 or payload[:4] != MAGIC:
        raise ValueError("not a powersgd payload (bad magic)")
    out: List[Tuple[int, tuple]] = []
    total = 0
    try:
        (count,) = struct.unpack_from("<I", payload, 4)
        off = 8
        for _ in range(count):
            (kind,) = struct.unpack_from("<B", payload, off)
            if kind == _DENSE:
                (size,) = struct.unpack_from("<I", payload, off + 1)
                off += 5
                total += size
                if max_floats is not None and total > max_floats:
                    raise ValueError(
                        f"powersgd payload reconstructs to >{max_floats} floats "
                        f"(resource-exhaustion guard)"
                    )
                out.append(
                    (kind, (np.frombuffer(payload, np.float32, count=size, offset=off),))
                )
                off += size * 4
            elif kind == _LOWRANK:
                n, m, r = struct.unpack_from("<IIH", payload, off + 1)
                off += 11
                total += n * m
                if max_floats is not None and total > max_floats:
                    raise ValueError(
                        f"powersgd payload reconstructs to >{max_floats} floats "
                        f"(resource-exhaustion guard)"
                    )
                p = np.frombuffer(payload, np.float32, count=n * r, offset=off).reshape(n, r)
                off += n * r * 4
                q = np.frombuffer(payload, np.float32, count=m * r, offset=off).reshape(m, r)
                off += m * r * 4
                out.append((kind, (n, m, r, p, q)))
            else:
                raise ValueError(f"unknown powersgd entry kind {kind}")
    except struct.error as err:  # short read past the payload end
        raise ValueError(f"malformed powersgd payload: {err}") from err
    except ValueError as err:  # numpy short frombuffer, bad kind, bad reshape
        raise ValueError(f"malformed powersgd payload: {err}") from err
    if off != len(payload):
        raise ValueError(f"trailing bytes in powersgd payload ({len(payload) - off})")
    return out


# Absolute reconstruction ceiling when the receiver doesn't yet know its
# schema (early pushes before the first pack): 2^29 floats = 2 GiB, matching
# the transport's MAX_PAYLOAD for a dense f32 frame. A low-rank entry
# RECONSTRUCTS to n*m floats from only (n+m)*r on the wire, so without a cap
# a 400 KB container declaring n=m=50000 would allocate 10 GB on decode.
MAX_DECODE_FLOATS = 1 << 29


def decode(
    payload: bytes, max_floats: int = MAX_DECODE_FLOATS, mesh_codec=None
) -> np.ndarray:
    """Reconstruct the flat f32 buffer. Self-describing: no specs needed,
    so receivers can decode contributions that arrive before their own
    first pack (the averager accepts early pushes by design).

    ``max_floats`` bounds the TOTAL reconstruction size — callers that know
    their schema pass the exact expected size, so an attacker can't buy a
    multi-GB allocation with a few-KB container (low-rank entries expand
    (n+m)*r wire floats into n*m). The bound is enforced inside
    ``_parse_entries``, per entry, BEFORE any reconstruction intermediate
    is allocated. ``mesh_codec`` (ops.mesh_codec, when active) runs the
    P·Qᵀ reconstruction matmuls on the local device mesh."""
    entries = _parse_entries(payload, max_floats)
    out: List[np.ndarray] = []
    for kind, data in entries:
        if kind == _DENSE:
            out.append(data[0].copy())
        else:
            _, _, _, p, q = data
            if mesh_codec is not None and mesh_codec.active:
                out.append(mesh_codec.lowrank_reconstruct(p, q))
            else:
                out.append((p @ q.T).ravel())
    return np.concatenate(out) if out else np.zeros((0,), np.float32)


def merge(
    weighted_payloads: Sequence[Tuple[float, bytes]],
    max_floats: int = MAX_DECODE_FLOATS,
) -> bytes:
    """The EXACT weighted mean of powersgd payloads, as a powersgd payload.

    By linearity, mean_i(w_i · P_i Q_iᵀ) == P_cat Q_catᵀ where P_cat stacks
    the (w_i/Σw)-scaled P_i columns and Q_cat stacks the Q_i columns — so a
    sync leader can serve the round RESULT in factored form with no new
    truncation error (the dense-results policy exists to avoid uncorrected
    error; a factored EXACT mean needs no such correction). Per tensor, the
    factored form is kept only while it beats dense bytes (concatenated rank
    k·r approaches n·m at large groups); dense entries and oversized
    concatenations are merged densely. Only meaningful for method='mean' —
    robust estimators are nonlinear, and the caller keeps dense results.

    ``max_floats`` bounds EACH payload's dense-reconstruction size (the
    mixed-kind fallback below densifies low-rank entries via P·Qᵀ): the
    sync leader merges containers received from the wire, so an entry
    declaring a huge n·m must be rejected at parse, exactly as in decode.
    """
    if not weighted_payloads:
        raise ValueError("merge of zero payloads")
    total_w = float(sum(w for w, _ in weighted_payloads))
    if total_w <= 0:
        raise ValueError(f"non-positive total weight {total_w}")
    parsed = [
        (w / total_w, _parse_entries(p, max_floats)) for w, p in weighted_payloads
    ]
    n_entries = len(parsed[0][1])
    if any(len(entries) != n_entries for _, entries in parsed):
        raise ValueError("powersgd merge: payloads disagree on entry count")
    parts = [MAGIC, struct.pack("<I", n_entries)]
    for i in range(n_entries):
        col = [(w, entries[i]) for w, entries in parsed]
        if all(kind == _LOWRANK for _, (kind, _) in col):
            n, m = col[0][1][1][0], col[0][1][1][1]
            if any((d[0], d[1]) != (n, m) for _, (_, d) in col):
                raise ValueError("powersgd merge: lowrank shape mismatch")
            r_cat = sum(d[2] for _, (_, d) in col)
            if (n + m) * r_cat < n * m and r_cat <= 0xFFFF:
                p_cat = np.concatenate(
                    [np.float32(w) * d[3] for w, (_, d) in col], axis=1
                )
                q_cat = np.concatenate([d[4] for _, (_, d) in col], axis=1)
                parts.append(struct.pack("<BIIH", _LOWRANK, n, m, r_cat))
                parts.append(np.ascontiguousarray(p_cat, np.float32).tobytes())
                parts.append(np.ascontiguousarray(q_cat, np.float32).tobytes())
                continue
        # Mixed kinds / dense / oversized concat: weighted-sum densely.
        acc = None
        for w, (kind, d) in col:
            dense = d[0].astype(np.float32) if kind == _DENSE else (d[3] @ d[4].T).ravel()
            acc = np.float32(w) * dense if acc is None else acc + np.float32(w) * dense
        parts.append(struct.pack("<BI", _DENSE, acc.size))
        parts.append(np.ascontiguousarray(acc, np.float32).tobytes())
    return b"".join(parts)
