"""Zone-sharded training state: the swarm outgrows one volunteer's mesh.

Every volunteer so far held a full model replica, so the largest trainable
model was capped by one volunteer's memory. This module shards the
parameter/optimizer tree into K contiguous element ranges of the flattened
buffer and assigns each range to a holder WITHIN a zone (the PR-8 zone is
the shard domain): fat intra-zone links carry the gather/scatter legs, and
cross-zone rounds average only your own shard's gradients — cutting each
volunteer's WAN bytes per round by ~K (the HSDP trade: shard inside the
datacenter, replicate across them).

Three deliberate design rules keep churn survivable:

- **Shard RANGES depend only on (n_elems, K)** — never on membership. A
  join/leave re-assigns holders but never re-cuts the buffer, so the
  cross-zone per-shard averaging schema (and therefore the wire schema
  hash every group member validates) is stable through arbitrary churn.
- **Holder assignment is an HRW (rendezvous) hash** over the zone's
  members per shard. Minimal disruption by construction: a departed
  member's shards move, everyone else's stay put — a modulo assignment
  would reshuffle nearly every shard on every membership change and turn
  each churn event into a zone-wide state migration.
- **Every shard move is membership-fenced** exactly like leader failover
  (PR 4), except the fence token is a CONTENT digest of the map (domain,
  K, sorted member set) rather than a counter: every ``shard.fetch``
  carries the requester's (domain, fence), and both ends reject a
  same-domain mismatch — so a deposed holder's late serve (or a stale
  puller's adoption) can never mix an old map's bytes into a newer one.
  Two peers agree on the fence iff they adopted the SAME membership,
  even when their local ``gen`` counters disagree (a late joiner or
  restarted volunteer starts at gen 0 while incumbents are at gen N; a
  slow peer collapses two quick churn events into one reshard) — the
  generation is a purely local version number kept for logs and flight
  events, never compared across peers. The cross-zone rung (an
  independent domain) is instead guarded by the ADOPTER-side fence: the
  puller's own map must be unchanged through the pull, or the bytes are
  discarded.

Recovery ladder on holder loss (PR 13's hedged-fetch shape):

1. the shard's PREVIOUS holder (alive on a graceful leave/re-zone — the
   freshest copy, one intra-zone hop);
2. the zone REPLICA (the HRW runner-up keeps a copy refreshed at commits;
   a SIGKILLed holder's shard is served from here);
3. any SAME-zone peer announcing the shard — including a demoted
   ex-holder still LINGERING the bytes: a holder demoted below
   runner-up at a reshard keeps its copy for a grace window instead of
   dropping it immediately, so a joiner-heavy churn event cannot strand
   the zone's only copy before the new holder has pulled it;
4. any CROSS-zone holder of the same shard (discovered via the DHT shard
   announce — the other zones replicate the full tree collectively).

Candidates are raced hedged: the first is dialed immediately, the next
joins after a soft deadline (``ResiliencePolicy.hedge_params`` when
attached), first success wins. An in-flight round that loses its holder
commits through the loss via the degraded-slice pattern: the leader falls
back to the zone's replicated copy and the gradient-mass accounting books
the slot as recovered/excluded — balanced, never silently dropped
(``health.mass_by_shard`` splits the buckets per shard domain).

Flight events: ``shard_lost`` (warn) when a holder departs with its shard,
``shard_recovered`` (info) with the recovery source + latency,
``shard_fence_rejected`` (warn) on a stale serve/pull attempt, and
``shard_recovery_failed`` (page) when the whole ladder came up empty. The
watchdog's ``shard_recovery_latency`` SLO burns on the recent-window
latency riding the report beat (``summary()``).
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import os
import signal
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.transport import Addr, RPCError, Transport
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)


def shard_ranges(n_elems: int, k: int) -> List[Tuple[int, int]]:
    """K contiguous [lo, hi) element ranges covering an ``n_elems`` flat
    buffer, sizes differing by at most one element. A pure function of
    (n_elems, k) — the schema-stability rule in the module doc rides on
    membership never entering this cut."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if n_elems < 0:
        raise ValueError(f"n_elems must be >= 0, got {n_elems}")
    base, rem = divmod(n_elems, k)
    out: List[Tuple[int, int]] = []
    lo = 0
    for s in range(k):
        hi = lo + base + (1 if s < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


def shard_slice(buf: np.ndarray, ranges: List[Tuple[int, int]], s: int) -> np.ndarray:
    """View of shard ``s``'s element range of a flat buffer."""
    lo, hi = ranges[s]
    return buf[lo:hi]


@dataclasses.dataclass(frozen=True)
class ShardMap:
    """One fenced version of the zone's shard→holder assignment.

    Immutable: a re-shard builds a NEW map at generation+1 (the fenced
    handoff), so concurrent readers can never observe a half-updated
    assignment. ``domain`` scopes the HRW hash (zone + namespace), so two
    zones sharding the same model never compute correlated rankings."""

    members: Tuple[str, ...]
    k: int
    gen: int
    domain: str = ""

    def __post_init__(self):
        object.__setattr__(self, "members", tuple(sorted(set(self.members))))
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {self.k}")
        if self.gen < 0:
            raise ValueError(f"gen must be >= 0, got {self.gen}")

    @property
    def fence(self) -> str:
        """The fencing token: a content digest of (domain, K, members).
        Two peers compute the same fence iff they adopted the same
        membership — unlike ``gen``, which is a purely local counter
        that skews across peers who observed a different number of
        churn events (a late joiner starts at 0, an incumbent is at N);
        comparing gens across peers would wedge in-zone recovery
        forever on such skew."""
        h = hashlib.blake2b(
            f"{self.domain}|k{self.k}|{'|'.join(self.members)}".encode(),
            digest_size=8,
        )
        return h.hexdigest()

    @staticmethod
    def _rank(domain: str, shard: int, pid: str) -> int:
        h = hashlib.blake2b(
            f"{domain}|s{shard}|{pid}".encode(), digest_size=8
        ).digest()
        return int.from_bytes(h, "big")

    def ranking(self, shard: int) -> List[str]:
        """Members by HRW weight for ``shard`` (holder first, replica
        second, then the rest of the failover order — every member
        computes the same list with no coordination)."""
        return sorted(
            self.members,
            key=lambda pid: self._rank(self.domain, shard, pid),
            reverse=True,
        )

    def holder_of(self, shard: int) -> Optional[str]:
        r = self.ranking(shard)
        return r[0] if r else None

    def replica_of(self, shard: int) -> Optional[str]:
        r = self.ranking(shard)
        return r[1] if len(r) > 1 else None

    def shards_of(self, pid: str) -> List[int]:
        return [s for s in range(self.k) if self.holder_of(s) == pid]

    def replica_shards_of(self, pid: str) -> List[int]:
        return [s for s in range(self.k) if self.replica_of(s) == pid]

    def primary_shard_of(self, pid: str) -> Optional[int]:
        """The shard a peer GROUPS under for shard-aware matchmaking (its
        lowest owned shard; None for a member holding none — possible
        when the zone has more members than shards)."""
        owned = self.shards_of(pid)
        return owned[0] if owned else None

    def version(self) -> dict:
        return {
            "domain": self.domain,
            "gen": self.gen,
            "k": self.k,
            "members": list(self.members),
        }


class ShardStore:
    """Held shard buffers (own + replica), with a byte high-water mark.

    ``peak_bytes`` is THE memory claim of the whole subsystem: the
    acceptance test asserts a sharded volunteer's persistent high-water
    stays a ~1/K sliver of the full replica it could never hold."""

    def __init__(self):
        self._own: Dict[int, np.ndarray] = {}
        self._replica: Dict[int, np.ndarray] = {}
        self.peak_bytes = 0

    def _note(self) -> None:
        self.peak_bytes = max(self.peak_bytes, self.bytes())

    def bytes(self) -> int:
        return sum(a.nbytes for a in self._own.values()) + sum(
            a.nbytes for a in self._replica.values()
        )

    def put(self, shard: int, arr: np.ndarray, *, replica: bool = False) -> None:
        arr = np.ascontiguousarray(arr, np.float32)
        if replica:
            self._replica[shard] = arr
        else:
            self._own[shard] = arr
            # One buffer per shard per role: a promotion replaces the
            # replica copy rather than double-holding it.
            self._replica.pop(shard, None)
        self._note()

    def get(self, shard: int, *, allow_replica: bool = True) -> Optional[np.ndarray]:
        arr = self._own.get(shard)
        if arr is None and allow_replica:
            arr = self._replica.get(shard)
        return arr

    def promote(self, shard: int) -> bool:
        """Replica copy → owned (the zero-RPC rung of the recovery ladder:
        the HRW runner-up already holds the bytes)."""
        arr = self._replica.pop(shard, None)
        if arr is None:
            return False
        self._own[shard] = arr
        self._note()
        return True

    def drop(self, shard: int, *, replica: bool = False) -> None:
        (self._replica if replica else self._own).pop(shard, None)

    def held(self) -> List[int]:
        return sorted(self._own)

    def replicas(self) -> List[int]:
        return sorted(self._replica)


class ShardManager:
    """One volunteer's half of the zone's shard protocol: holds its
    shards, serves fenced ``shard.fetch``, re-shards on churn, and runs
    the hedged recovery ladder for shards it newly owns.

    The manager is deliberately NOT on the averaging hot path: the
    cross-zone per-shard rounds run through the ordinary averager (the
    shard slice is just that averager's tree, the shard-scoped group ids
    come from the schedule's ``shards`` map), and the manager only moves
    state when membership does."""

    FETCH_TIMEOUT = 30.0
    CONNECT_TIMEOUT = 2.0
    # Round budget the hedge soft-deadline fraction applies to (the
    # recovery ladder's analog of the averaging round budget).
    FETCH_BUDGET_S = 6.0
    ANNOUNCE_TTL = 30.0
    # Grace window a demoted ex-holder keeps (lingers) its old copy for
    # after a reshard, so the new holder — possibly a joiner with no
    # prior map — can still pull the zone's only copy instead of
    # falling back to a cold checkpoint restore.
    DEMOTED_LINGER_S = 60.0
    # Consecutive maintain() beats a changed membership snapshot must
    # persist before it triggers a fenced reshard: a peer whose beat is
    # merely delayed past the snapshot max-age must not cost the zone a
    # gen bump, shard_lost events, and a round of recovery pulls.
    RESHARD_DEBOUNCE_BEATS = 2
    # Recent-window for the SLO metric riding the report beat: a recovery
    # slower than the bound must burn for a while, not forever.
    RECENT_WINDOW_S = 120.0
    MAX_LATENCIES = 256
    # Sanity bound for adopted shard values (state_sync's guard: trained
    # params live in O(1); beyond this is garbage, not a model).
    MAX_ABS_VALUE = 1e4

    # The instrumented re-shard phase point (the kill-at-phase matrix's
    # fourth column, next to the averager's three leader phases).
    SHARD_PHASES = ("mid_resharding",)

    def __init__(
        self,
        transport: Transport,
        dht: DHTNode,
        membership,
        peer_id: str,
        *,
        n_elems: int,
        k: int,
        namespace: str = "",
        zone: Optional[str] = None,
        telemetry=None,
        resilience=None,
        controller=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.transport = transport
        self.dht = dht
        self.membership = membership
        self.peer_id = peer_id
        self.n_elems = int(n_elems)
        self.k = int(k)
        self.namespace = namespace
        self._zone = zone
        self.telemetry = telemetry
        self.resilience = resilience
        self.controller = controller
        self.clock = clock
        self.ranges = shard_ranges(self.n_elems, self.k)
        self.map: Optional[ShardMap] = None
        self.store = ShardStore()
        self.recoveries = 0
        self.recoveries_failed = 0
        self.resharding_count = 0
        self.fence_rejections = 0
        self._recovery_lat: Deque[Tuple[float, float]] = deque(
            maxlen=self.MAX_LATENCIES
        )
        self._last_recovery_lat: Optional[float] = None
        self._recovering: set = set()
        # shard -> holder under the PREVIOUS map: the recovery ladder's
        # first rung (a graceful leaver still serves for a grace period).
        self._prev_holders: Dict[int, str] = {}
        # shard -> (bytes, expiry): copies this peer was demoted out of
        # at a reshard, lingering until the new holder has pulled them.
        self._demoted: Dict[int, Tuple[np.ndarray, float]] = {}
        # maintain()'s reshard debounce: the candidate member list, how
        # many consecutive beats it has been observed unchanged, and how
        # many consecutive beats the map has disagreed with the snapshot
        # at all (the backstop against a flapping view never settling).
        self._pending_members: Optional[List[str]] = None
        self._pending_beats = 0
        self._stale_beats = 0
        self._phase_hooks: Dict[str, Callable[[], Any]] = {}
        self._maint_task: Optional[asyncio.Task] = None
        self._announced_t = float("-inf")
        transport.register("shard.fetch", self._rpc_fetch)

    # -- identity ----------------------------------------------------------

    @property
    def zone(self) -> str:
        if self._zone is not None:
            return self._zone
        return str(
            getattr(self.membership, "extra_info", {}).get("zone") or ""
        )

    @property
    def domain(self) -> str:
        """HRW scope: zone + namespace, so two zones (or two models) never
        compute correlated holder rankings."""
        return f"{self.zone}|{self.namespace}"

    @property
    def announce_key(self) -> str:
        """DHT key the cross-zone recovery rung discovers holders under —
        deliberately NOT zone-scoped: the other zones ARE the rung."""
        return f"shard/{self.namespace or '~'}"

    def primary_shard(self) -> Optional[int]:
        return self.map.primary_shard_of(self.peer_id) if self.map else None

    def owned(self) -> List[int]:
        return self.map.shards_of(self.peer_id) if self.map else []

    def missing(self) -> List[int]:
        held = set(self.store.held())
        return [s for s in self.owned() if s not in held]

    def advertise(self) -> None:
        """Stamp the shard assignment into the membership record so the
        next heartbeat carries it — the group schedule's ``shards`` map
        (shard-aware cross-rotation grouping) reads peers' advertised
        primary shard exactly like it reads zones."""
        extra = getattr(self.membership, "extra_info", None)
        if extra is None:
            return
        p = self.primary_shard()
        if p is None:
            extra.pop("shard", None)
        else:
            extra["shard"] = int(p)

    def _prune_demoted(self, now: Optional[float] = None) -> None:
        """Expire lingering demoted copies whose grace window closed."""
        now = self.clock() if now is None else now
        for s in [s for s, (_, exp) in self._demoted.items() if exp <= now]:
            del self._demoted[s]

    # -- chaos instrumentation ---------------------------------------------

    async def _phase(self, name: str) -> None:
        """Instrumented re-shard phase point (mirrors the averager's
        leader phases). No-op in production; chaos installs hooks, and
        DVC_CHAOS_SHARD_DIE_PHASE makes a subprocess holder SIGKILL
        itself exactly like a preempted volunteer."""
        hook = self._phase_hooks.get(name)
        if hook is not None:
            res = hook()
            if asyncio.iscoroutine(res):
                await res
        if os.environ.get("DVC_CHAOS_SHARD_DIE_PHASE") == name:
            log.warning("chaos: shard holder dying at phase %r (SIGKILL)", name)
            os.kill(os.getpid(), signal.SIGKILL)

    # -- flight/controller plumbing ----------------------------------------

    def _record(self, kind: str, **fields) -> None:
        rec = getattr(self.telemetry, "recorder", None)
        if rec is None:
            return
        try:
            rec.record(kind, **fields)
        except Exception as e:  # noqa: BLE001 — observability must not fail state moves
            log.debug("flight record %s failed: %s", kind, errstr(e))

    def health(self) -> str:
        """Shard-domain health, the controller's regime input: "degraded"
        while an owned shard has no bytes (a loss the ladder has not
        closed), "recovering" while pulls are in flight, else "ok"."""
        if self.map is None:
            return "ok"
        if self.missing():
            return "recovering" if self._recovering else "degraded"
        return "ok"

    def feed_controller(self) -> None:
        """Report shard-domain health into the closed-loop controller: a
        degraded shard zone widens deadlines / tightens cadence for the
        intra level (the gather/scatter plane the loss actually sits on)
        through the same regime model every other signal feeds."""
        c = self.controller
        if c is None:
            return
        try:
            c.observe_shard_health(level="intra", ok=self.health() == "ok")
        except Exception as e:  # noqa: BLE001
            log.debug("controller shard-health feed failed: %s", errstr(e))

    # -- serving (fenced) ---------------------------------------------------

    async def _rpc_fetch(self, args: dict, payload: bytes):
        """Fenced shard serve. The requester names the map it is
        recovering INTO via the content fence (domain + K + member set);
        any same-domain mismatch is rejected on this side (and the reply
        fence is re-validated on the puller side), so bytes can only
        ever move between two peers that adopted the SAME membership —
        the leader-failover fencing rule, applied to state. Generations
        are deliberately NOT compared across peers: they are local
        counters and skew under uneven churn observation (a late joiner
        is at gen 0 while an incumbent is at gen N). A legacy request
        naming no fence falls back to strict generation equality.

        The fence is DOMAIN-scoped: a cross-zone rung pull (different
        ``domain``) is served at whatever this zone currently holds —
        the ranges are schema-stable by construction, and the puller's
        adopter-side fence (map unchanged through the pull) is what
        guards that path.

        A shard no longer assigned here may still be served from the
        lingering demoted copy (grace window after a reshard): that is
        exactly the path a joiner-promoted holder pulls through."""
        if self.map is None:
            raise RPCError("no shard map yet")
        shard = int(args["shard"])
        gen = int(args.get("gen", -1))
        dom = args.get("domain")
        req_fence = args.get("fence")
        if dom is None or dom == self.domain:
            stale = (
                req_fence != self.map.fence
                if req_fence is not None
                else gen != self.map.gen
            )
            if stale:
                self.fence_rejections += 1
                self._record(
                    "shard_fence_rejected",
                    shard=shard,
                    got_gen=gen,
                    have_gen=self.map.gen,
                    got_fence=req_fence,
                    have_fence=self.map.fence,
                    requester=str(args.get("peer", "?")),
                )
                raise RPCError(
                    f"shard fencing mismatch: requester fence {req_fence}"
                    f"/gen {gen} vs map fence {self.map.fence}"
                    f"/gen {self.map.gen}"
                )
        arr = self.store.get(shard)
        if arr is None:
            ent = self._demoted.get(shard)
            if ent is not None and ent[1] > self.clock():
                arr = ent[0]
        if arr is None:
            raise RPCError(f"shard {shard} not held here")
        return (
            {
                "shard": shard,
                "gen": self.map.gen,
                "fence": self.map.fence,
                "total": int(arr.nbytes),
                "wire": "f32",
            },
            arr.tobytes(),
        )

    # -- discovery ----------------------------------------------------------

    async def announce(self) -> None:
        """Publish (addr, zone, gen, shards, lingering) under the shard
        key — the announce-rung candidate source, both same-zone
        (demoted lingering copies included, so a joiner-promoted holder
        can find the ex-holder's grace copy) and cross-zone. Call on the
        heartbeat cadence (the volunteer's announce loop); TTL'd like
        peer records."""
        if self.map is None:
            return
        await self.dht.store(
            self.announce_key,
            {
                "addr": list(self.transport.addr),
                "zone": self.zone,
                "gen": self.map.gen,
                "shards": self.owned(),
                "lingering": sorted(self._demoted),
            },
            subkey=self.peer_id,
            ttl=self.ANNOUNCE_TTL,
        )

    async def _announced_candidates(
        self, shard: int
    ) -> Tuple[List[Tuple[str, Addr]], List[Tuple[str, Addr]]]:
        """(same_zone, cross_zone) peers announcing ``shard`` — owned or
        lingering. The same-zone list is the ladder rung that reaches a
        demoted ex-holder a joiner has no previous map to name; the
        cross-zone list is the last rung."""
        try:
            records = await self.dht.get(self.announce_key)
        except Exception as e:  # noqa: BLE001 — discovery hiccup: rung is empty
            log.debug("shard announce lookup failed: %s", errstr(e))
            return [], []
        same: List[Tuple[str, Addr]] = []
        cross: List[Tuple[str, Addr]] = []
        for pid, rec in (records or {}).items():
            if pid == self.peer_id or not isinstance(rec, dict):
                continue
            if shard not in (rec.get("shards") or []) and shard not in (
                rec.get("lingering") or []
            ):
                continue
            addr = rec.get("addr")
            if not (isinstance(addr, (list, tuple)) and len(addr) == 2):
                continue
            dst = (str(addr[0]), int(addr[1]))
            if str(rec.get("zone") or "") == self.zone:
                same.append((pid, dst))
            else:
                cross.append((pid, dst))
        return same, cross

    # -- re-shard (fenced handoff) ------------------------------------------

    async def reshard(
        self,
        members: Optional[List[str]] = None,
        *,
        reason: str = "churn",
        recover: bool = True,
    ) -> dict:
        """Adopt a new zone membership: build the generation+1 map, emit
        ``shard_lost`` for shards whose holder departed, drop what we no
        longer hold, and (by default) run the recovery ladder for shards
        we newly own. Idempotent on an unchanged member set."""
        if members is None:
            members = await self._zone_members()
        members = sorted(set(members) | {self.peer_id})
        old = self.map
        if old is not None and list(old.members) == members:
            return {"gen": old.gen, "changed": False}
        new = ShardMap(
            members=tuple(members),
            k=self.k,
            gen=(old.gen + 1) if old is not None else 0,
            domain=self.domain,
        )
        lost: List[int] = []
        if old is not None:
            self._prev_holders = {
                s: old.holder_of(s) for s in range(self.k)
            }
            for s in range(self.k):
                h_old = old.holder_of(s)
                if h_old is not None and h_old not in new.members:
                    lost.append(s)
                    self._record(
                        "shard_lost",
                        shard=s,
                        holder=h_old,
                        gen=new.gen,
                        reason=reason,
                    )
        self.map = new
        self.resharding_count += 1
        self.advertise()
        log.info(
            "re-shard gen %d (%s): %d members, own %s%s",
            new.gen, reason, len(members), new.shards_of(self.peer_id),
            f", lost holders for {lost}" if lost else "",
        )
        if old is not None:
            # The phase point instruments the fenced HANDOFF between two
            # live maps; the gen-0 initial adoption has no predecessor
            # (and a DVC_CHAOS_SHARD_DIE_PHASE subprocess must die at a
            # real re-shard, not at its own startup).
            await self._phase("mid_resharding")
        # Demote shards neither owned nor replicated under the new map —
        # AFTER the phase point, so a mid-resharding kill leaves the old
        # copies for the survivors' ladders. Demoted bytes are NOT
        # dropped: they linger for a grace window so the new holder
        # (possibly a joiner with no copy anywhere in the zone yet) can
        # still pull them through the fenced fetch path — dropping at
        # reshard would strand the zone's only copy whenever a holder is
        # demoted below runner-up by joiners.
        now = self.clock()
        self._prune_demoted(now)
        owned = set(new.shards_of(self.peer_id))
        repl = set(new.replica_shards_of(self.peer_id))
        for s in self.store.held():
            if s not in owned:
                arr = self.store.get(s, allow_replica=False)
                if s in repl:
                    if arr is not None:
                        self.store.put(s, arr, replica=True)
                elif arr is not None:
                    self._demoted[s] = (arr, now + self.DEMOTED_LINGER_S)
                self.store.drop(s)
        for s in self.store.replicas():
            if s not in repl and s not in owned:
                arr = self.store.get(s)
                if arr is not None:
                    self._demoted.setdefault(
                        s, (arr, now + self.DEMOTED_LINGER_S)
                    )
                self.store.drop(s, replica=True)
        self.feed_controller()
        summary = {"gen": new.gen, "changed": True, "lost": lost}
        if recover:
            summary["recovered"] = await self.ensure_shards()
        return summary

    async def _zone_members(self) -> List[str]:
        """Same-zone, same-namespace live peers (the shard domain), from
        the membership snapshot at heartbeat resolution."""
        try:
            peers = await self.membership.alive_peers(
                include_self=True, max_age=self.membership.ttl / 3.0
            )
        except Exception as e:  # noqa: BLE001
            log.debug("zone member lookup failed: %s", errstr(e))
            return [self.peer_id]
        out = []
        for pid, rec in peers.items():
            if pid == self.peer_id:
                out.append(pid)
                continue
            if str(rec.get("zone") or "") != self.zone:
                continue
            ns = rec.get("avg_ns")
            if self.namespace and ns is not None and ns != self.namespace:
                continue
            out.append(pid)
        return out

    # -- autopilot maintenance ----------------------------------------------

    async def maintain(self) -> dict:
        """One autopilot beat: adopt zone churn (fenced re-shard + the
        recovery ladder), close any still-missing shards, refresh
        runner-up replicas, and re-announce before the DHT record
        expires. The volunteer runs this on a background cadence so a
        SIGKILLed holder's shards come back WITHOUT anyone restarting
        the epoch — the live form of the explicit reshard() the tests
        drive."""
        out: Dict[str, Any] = {"resharded": False, "recovered": [],
                               "replicas": []}
        self._prune_demoted()
        members = sorted(set(await self._zone_members()) | {self.peer_id})
        reshard_now = self.map is None
        if self.map is not None and list(self.map.members) != members:
            # Debounce: membership snapshots flap at heartbeat
            # resolution (a merely-delayed beat looks like a departure
            # for one beat, then un-looks like one). Require the changed
            # member set to persist across consecutive beats before
            # paying for a fenced reshard — gen churn both moves shard
            # bytes for peers that never died and re-fences in-flight
            # pulls.
            self._stale_beats += 1
            if members == self._pending_members:
                self._pending_beats += 1
            else:
                self._pending_members, self._pending_beats = members, 1
            # The backstop (2x the debounce) covers a view flapping
            # BETWEEN values every beat: the candidate never stabilizes,
            # but the map must not stay stale forever.
            reshard_now = (
                self._pending_beats >= self.RESHARD_DEBOUNCE_BEATS
                or self._stale_beats >= 2 * self.RESHARD_DEBOUNCE_BEATS
            )
        elif self.map is not None:
            self._pending_members, self._pending_beats = None, 0
            self._stale_beats = 0
        if reshard_now:
            self._pending_members, self._pending_beats = None, 0
            self._stale_beats = 0
            res = await self.reshard(members=members)
            out["resharded"] = bool(res.get("changed"))
            out["recovered"] = res.get("recovered", [])
        elif self.missing():
            out["recovered"] = await self.ensure_shards()
        out["replicas"] = await self.refresh_replicas()
        now = self.clock()
        if now - self._announced_t >= self.ANNOUNCE_TTL / 3.0:
            await self.announce()
            self._announced_t = now
        return out

    def start_maintenance(self, interval_s: float = 5.0) -> None:
        """Run maintain() every ``interval_s`` until stop()."""
        if self._maint_task is None or self._maint_task.done():
            self._maint_task = asyncio.get_event_loop().create_task(
                self._maint_loop(float(interval_s))
            )

    async def _maint_loop(self, interval_s: float) -> None:
        while True:
            await asyncio.sleep(interval_s)
            try:
                await self.maintain()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — one bad beat must not kill the loop
                log.debug("shard maintenance beat failed: %s", errstr(e))

    async def stop(self) -> None:
        t, self._maint_task = self._maint_task, None
        if t is not None:
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    # -- recovery ladder -----------------------------------------------------

    async def ensure_shards(self) -> List[int]:
        """Recover every owned-but-missing shard; returns the recovered
        list. Shards run concurrently (distinct sources), each through
        its own hedged ladder."""
        missing = self.missing()
        if not missing or self.map is None:
            return []
        # return_exceptions: one shard's unexpected failure (an
        # exception type the hedge loop doesn't anticipate) must not
        # cancel every sibling shard's in-flight recovery and abort the
        # whole beat.
        results = await asyncio.gather(
            *(self._recover_shard(s) for s in missing),
            return_exceptions=True,
        )
        self.feed_controller()
        got: List[int] = []
        for s, res in zip(missing, results):
            if isinstance(res, BaseException):
                log.warning(
                    "shard %d recovery raised unexpectedly: %s",
                    s, errstr(res),
                )
            elif res:
                got.append(s)
        return got

    async def _recover_shard(self, shard: int) -> bool:
        assert self.map is not None
        gen = self.map.gen
        fence = self.map.fence
        t0 = self.clock()
        self._recovering.add(shard)
        try:
            # Rung 0, zero RPCs: we were the shard's replica — promote.
            if self.store.promote(shard):
                self._note_recovered(shard, gen, "local_replica", t0)
                return True
            # Rung 0.5, still zero RPCs: we held this shard before a
            # demotion and the lingering copy has not expired (the
            # A->B->A membership wobble on a single-zone swarm).
            ent = self._demoted.pop(shard, None)
            if ent is not None and ent[1] > self.clock():
                self.store.put(shard, ent[0])
                self._note_recovered(shard, gen, "lingering_local", t0)
                return True
            cands: List[Tuple[str, str]] = []
            prev = self._prev_holders.get(shard)
            if prev and prev != self.peer_id:
                cands.append(("prev_holder", prev))
            rep = self.map.replica_of(shard)
            if rep and rep != self.peer_id and rep != prev:
                cands.append(("zone_replica", rep))
            targets: List[Tuple[str, str, Addr]] = []
            for src, pid in cands:
                rec = self.membership.peer_record(pid) or {}
                addr = rec.get("addr")
                if isinstance(addr, (list, tuple)) and len(addr) == 2:
                    targets.append((src, pid, (str(addr[0]), int(addr[1]))))
            same, cross = await self._announced_candidates(shard)
            seen = {pid for _, pid, _ in targets}
            for pid, addr in same:
                if pid not in seen:
                    targets.append(("zone_announce", pid, addr))
            for pid, addr in cross:
                targets.append(("cross_zone", pid, addr))
            arr, src = await self._hedged_fetch(shard, gen, fence, targets)
            if arr is None:
                self.recoveries_failed += 1
                self._record(
                    "shard_recovery_failed",
                    shard=shard,
                    gen=gen,
                    candidates=len(targets),
                )
                log.warning(
                    "shard %d recovery failed at gen %d (%d candidates)",
                    shard, gen, len(targets),
                )
                return False
            if self.map is None or self.map.fence != fence:
                # The map moved under us mid-pull (another churn event):
                # adopting would mix memberships — the fencing rule's
                # adopter half. The NEXT reshard's ladder runs fresh.
                self._record(
                    "shard_fence_rejected",
                    shard=shard,
                    got_gen=gen,
                    have_gen=self.map.gen if self.map else -1,
                    got_fence=fence,
                    have_fence=self.map.fence if self.map else None,
                    requester=self.peer_id,
                )
                return False
            self.store.put(shard, arr)
            self._note_recovered(shard, gen, src, t0)
            return True
        finally:
            self._recovering.discard(shard)

    def _note_recovered(self, shard: int, gen: int, src: str, t0: float) -> None:
        dt = max(self.clock() - t0, 0.0)
        self.recoveries += 1
        self._last_recovery_lat = dt
        self._recovery_lat.append((self.clock(), dt))
        self._record(
            "shard_recovered", shard=shard, gen=gen, src=src,
            dt_s=round(dt, 4),
        )
        log.info(
            "shard %d recovered from %s in %.3fs (gen %d)", shard, src, dt, gen
        )

    async def _hedged_fetch(
        self,
        shard: int,
        gen: int,
        fence: Optional[str],
        targets: List[Tuple[str, str, Addr]],
    ) -> Tuple[Optional[np.ndarray], str]:
        """Race the ladder: first target dialed immediately, the next
        joins after the hedge soft deadline, first success wins (losers
        cancelled). The soft deadline comes from the resilience policy's
        learned hedge operating point when one is attached, so shard
        recovery and tile recovery share one tail model."""
        if not targets:
            return None, ""
        soft_frac, max_inflight = 0.5, 2
        if self.resilience is not None:
            try:
                soft_frac, max_inflight = self.resilience.hedge_params("intra")
            except Exception:  # noqa: BLE001 — policy is advisory here
                pass
        soft_s = max(0.2, float(soft_frac) * self.FETCH_BUDGET_S)
        pending: Dict[asyncio.Task, str] = {}
        idx = 0
        try:
            while True:
                while idx < len(targets) and len(pending) < max(1, max_inflight):
                    src, pid, addr = targets[idx]
                    idx += 1
                    t = asyncio.create_task(
                        self._fetch_from(
                            addr, shard, gen, fence=fence,
                            cross_domain=(src == "cross_zone"),
                        )
                    )
                    pending[t] = src
                    if len(pending) == 1 and idx < len(targets):
                        break  # let the first run alone until the soft deadline
                if not pending:
                    return None, ""
                done, _ = await asyncio.wait(
                    set(pending),
                    timeout=soft_s if idx < len(targets) else None,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                for t in done:
                    src = pending.pop(t)
                    try:
                        arr = t.result()
                    except Exception as e:  # noqa: BLE001 — any one rung's failure just advances the ladder
                        log.debug(
                            "shard %d fetch via %s failed: %s",
                            shard, src, errstr(e),
                        )
                        continue
                    return arr, src
                if not done and idx >= len(targets) and not pending:
                    return None, ""
        finally:
            for t in pending:
                t.cancel()

    async def _fetch_from(
        self,
        addr: Addr,
        shard: int,
        gen: int,
        *,
        fence: Optional[str] = None,
        cross_domain: bool = False,
    ) -> np.ndarray:
        args = {
            "shard": shard,
            "gen": gen,
            "peer": self.peer_id,
            "domain": self.domain,
        }
        if fence is not None:
            args["fence"] = fence
        ret, payload = await self.transport.call(
            addr,
            "shard.fetch",
            args,
            timeout=self.FETCH_TIMEOUT,
            connect_timeout=self.CONNECT_TIMEOUT,
            # Bulk transfer: keep it out of the failure detector's
            # control-plane latency EWMA (state_sync's rule).
            record_latency=False,
        )
        # A cross-domain serve reports the SERVING zone's map — an
        # independent sequence, so equality is meaningless there; the
        # adopter-side fence in _recover_shard (our map unchanged through
        # the pull) is the guard on that rung. Same-domain replies are
        # held to the content fence when we named one (a deposed
        # holder's stale serve reports a stale fence), and to gen
        # equality on the legacy gen-only path.
        if not cross_domain:
            if fence is not None:
                if ret.get("fence") != fence:
                    raise RPCError(
                        "shard fencing mismatch in reply: fence "
                        f"{ret.get('fence')} != {fence}"
                    )
            elif int(ret.get("gen", -1)) != gen:
                raise RPCError(
                    f"shard fencing mismatch in reply: gen {ret.get('gen')} != {gen}"
                )
        lo, hi = self.ranges[shard]
        arr = np.frombuffer(bytes(payload), np.float32)
        if arr.size != hi - lo:
            raise RPCError(
                f"shard {shard} payload {arr.size} elems != range {hi - lo}"
            )
        if arr.size:
            vlo = float(np.min(arr))
            vhi = float(np.max(arr))
            if not (-self.MAX_ABS_VALUE < vlo <= vhi < self.MAX_ABS_VALUE):
                raise RPCError("shard payload failed the sanity guard")
        return arr.copy()

    # -- replica refresh -----------------------------------------------------

    async def refresh_replicas(self) -> List[int]:
        """Pull a copy of every shard this peer is the HRW runner-up for
        (best-effort, off the round's critical path — call after commits,
        the way the redundancy shares refresh). This is what makes rung 1
        of a SIGKILLed holder's ladder land: the replica was refreshed at
        the last commit, so recovery costs replay-from-replica, not an
        epoch restart."""
        if self.map is None:
            return []
        got: List[int] = []
        for s in self.map.replica_shards_of(self.peer_id):
            if self.store.get(s, allow_replica=False) is not None:
                continue  # we own it; no separate replica copy needed
            holder = self.map.holder_of(s)
            if holder is None or holder == self.peer_id:
                continue
            rec = self.membership.peer_record(holder) or {}
            addr = rec.get("addr")
            if not (isinstance(addr, (list, tuple)) and len(addr) == 2):
                continue
            try:
                arr = await self._fetch_from(
                    (str(addr[0]), int(addr[1])), s, self.map.gen,
                    fence=self.map.fence,
                )
            except (RPCError, OSError, asyncio.TimeoutError, ValueError) as e:
                log.debug("replica refresh of shard %d failed: %s", s, errstr(e))
                continue
            self.store.put(s, arr, replica=True)
            got.append(s)
        return got

    def degraded_copy(self, shard: int) -> Optional[np.ndarray]:
        """The zone's replicated copy of ``shard`` if this peer holds one
        — the degraded-slice commit source when a round's holder died
        mid-stream (the leader folds this + replay instead of aborting
        the epoch; the mass accounting books the slot recovered)."""
        arr = self.store.get(shard, allow_replica=True)
        if arr is None:
            ent = self._demoted.get(shard)
            if ent is not None and ent[1] > self.clock():
                arr = ent[0]
        return None if arr is None else arr.copy()

    # -- report surface ------------------------------------------------------

    def recent_recovery_latency_s(self) -> Optional[float]:
        now = self.clock()
        vals = [
            dt for t, dt in self._recovery_lat
            if now - t <= self.RECENT_WINDOW_S
        ]
        return round(max(vals), 4) if vals else None

    def summary(self) -> dict:
        """The ``sharding`` section of the volunteer report beat: the
        watchdog's ``shard_recovery_latency`` SLO reads
        ``recent_recovery_latency_s`` (None = no recent recovery = no
        tick), the doctor joins the counters with the flight events, and
        the campaign artifact snapshots the whole dict."""
        m = self.map
        return {
            "k": self.k,
            "gen": m.gen if m else None,
            "fence": m.fence if m else None,
            "zone": self.zone,
            "members": len(m.members) if m else 0,
            "owned": self.owned(),
            "replica": self.store.replicas(),
            "lingering": sorted(self._demoted),
            "missing": self.missing(),
            "health": self.health(),
            "bytes": self.store.bytes(),
            "peak_bytes": self.store.peak_bytes,
            "recoveries": self.recoveries,
            "recoveries_failed": self.recoveries_failed,
            "resharding_count": self.resharding_count,
            "fence_rejections": self.fence_rejections,
            "last_recovery_latency_s": (
                round(self._last_recovery_lat, 4)
                if self._last_recovery_lat is not None
                else None
            ),
            "recent_recovery_latency_s": self.recent_recovery_latency_s(),
        }
