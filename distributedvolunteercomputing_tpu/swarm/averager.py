"""GradientAverager family: the four WAN averaging modes of the reference.

Reference parity (BASELINE.json:5,7-11):
- ``SyncAverager``      — "synchronous GradientAverager" (config 2)
- ``GossipAverager``    — "async gossip averaging" (config 3)
- ``ButterflyAverager`` — "butterfly allreduce across heterogeneous
                          volunteers" (config 4, Moshpit-style)
- ``ByzantineAverager`` — "Byzantine-tolerant aggregation under volunteer
                          churn" (config 5)

Two-tier TPU design (BASELINE.json:5): gradients are ALREADY reduced across
the chips of one slice by ``jax.lax.psum`` inside the compiled train step
(parallel/train_step.py) — what crosses here is one float32 buffer per
volunteer SLICE, exchanged over the DCN Transport and averaged on host.

Churn rules (SURVEY.md §7 hard part a): every tensor message carries the
round EPOCH from matchmaking; stale/foreign messages are dropped; any
timeout degrades the round (skip stage / aggregate the subset / return None)
instead of wedging — a dead peer costs one timeout, never a hang.

Deadline-bounded rounds (OptiReduce genre, PAPERS.md): every gather-style
round carries an absolute wall-clock DEADLINE on the consensus clock
(stamped by the leader at begin, from swarm/clocksync.py time). The round
COMMITS at the deadline with whatever contributions arrived — the weighted
mean re-normalizes over the subset, excluded peers are recorded and served
back in the fetch meta — instead of blocking on the slowest participant.
A straggler therefore costs the round its contribution, never the round
its deadline. Paired with the phi-accrual failure detector
(swarm/failure_detector.py) and the adaptive resilience policy
(swarm/resilience.py), which set the budget and pre-exclude likely
stragglers from formation in the first place.

Leader failover (sync mode): the gather leader used to be the round's last
single point of failure — a dead leader failed everyone's fetch and the
round was skipped, discarding every member's streamed contribution. Sync
rounds now carry a FENCING GENERATION alongside the matchmaking epoch
(Group.gen; 0 for the original leader). A member that observes the leader
die at the connection level (refused dial, reset socket), lose its round
state, or trip phi-accrual suspicion mid-fetch DEPOSES it: the
deterministic successor — the next live member in epoch order, skipping
peers the local policy suspects — re-leads a RECOVERY round over the same
epoch at generation+1, re-collecting the contributions members retained in
compressed wire form (nothing is recompressed, so error-feedback state
cannot double-apply). Handlers check the generation on every
sync.contribute/sync.fetch, so a deposed or partitioned ex-leader's late
serve — and a member's stale push — is rejected instead of mixing into the
newer round (Moshpit's restructure-around-the-failure applied to the
leader itself; see docs/RESILIENCE.md).
"""

from __future__ import annotations

import asyncio
import copy
import hashlib
import os
import random
import signal
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from distributedvolunteercomputing_tpu import native
from distributedvolunteercomputing_tpu.ops import mesh_codec as mesh_codec_mod
from distributedvolunteercomputing_tpu.ops import robust
from distributedvolunteercomputing_tpu.swarm.agg_stream import (
    StreamingAggregator,
    encode_wire_elems,
)
from distributedvolunteercomputing_tpu.swarm.agg_stream import (
    wire_geometry as agg_wire_geometry,
)
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.matchmaking import (
    Group,
    GroupAssignment,
    GroupSchedule,
    Matchmaker,
)
from distributedvolunteercomputing_tpu.swarm.membership import SwarmMembership
from distributedvolunteercomputing_tpu.swarm import health as health_mod
from distributedvolunteercomputing_tpu.swarm import telemetry as telemetry_mod
from distributedvolunteercomputing_tpu.swarm.transport import (
    Addr,
    RPCError,
    StreamPayload,
    Transport,
)
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger, log_context
from distributedvolunteercomputing_tpu.utils.pytree import flatten_to_buffer, unflatten_from_buffer

log = get_logger(__name__)

# Sign-wire result-leg tag: a round result over the sign wire is q8 bytes
# behind this magic, so the receive path can tell it from a 1-bit
# contribution (SG1) by construction (raw q8's leading u64 count could
# collide with SG1 for unlucky model sizes).
_SIGN_RESULT_MAGIC = b"SQ8"


class _Streamed:
    """Sentinel "buffer" for a contribution that was folded into the round's
    StreamingAggregator on arrival: the leader never held its dense copy, so
    there is nothing to stack — the aggregator owns that mass."""

    __slots__ = ()

    def __repr__(self) -> str:
        return "<streamed>"


STREAMED = _Streamed()


class _LeaderDown(Exception):
    """Member-side verdict that the round's leader is gone: connection-level
    failure on the push/fetch leg, lost round state, or phi-accrual
    suspicion mid-fetch. Internal control flow only — `_member_round`
    converts it into a recovery attempt, never lets it escape."""


class _Round:
    """Leader-side state for one gather round."""

    def __init__(self, expected: List[str]):
        self.expected = set(expected)
        # byzantine: peer -> (weight, buf); sync: (peer, token) -> (weight, buf).
        self.contribs: Dict[Any, Tuple[float, np.ndarray]] = {}
        # sync leader sets this to its issued-token table so the early "all
        # contributions in" check can't be tripped by forged entries.
        self.tokens: Optional[Dict[str, str]] = None
        self.full = asyncio.Event()
        # powersgd only: raw wire payloads per contribution key, kept so the
        # sync leader can serve the EXACT factored mean (concatenated
        # weighted factor pairs) instead of a dense result — by linearity
        # decode(merge(payloads)) == weighted mean of the decoded denses.
        self.payloads: Dict[Any, bytes] = {}
        self.result: Optional[np.ndarray] = None
        self.result_wire: bytes = b""  # encoded once; served to every fetch
        self.result_ready = asyncio.Event()
        # Peer ids whose contributions actually entered the aggregate —
        # served back in sync.fetch meta so a member with a pending top-k
        # error-feedback residual knows whether its shipped mass landed
        # (a degraded round may have dropped its late push).
        self.included: List[str] = []
        # Expected peers whose contributions did NOT make the deadline —
        # recorded at commit, served in fetch meta, and fed to the
        # resilience policy as this round's absent set.
        self.excluded: List[str] = []
        # Streaming leader aggregation (f32/bf16 wires, armed by the LEADER
        # when it enters the round): contribution chunks decode and fold as
        # they arrive instead of materializing per-peer dense buffers. None
        # on member side, parked rounds, and non-elementwise wires.
        self.stream: Optional[StreamingAggregator] = None
        # Leader-side round prologue ran (tokens fixed, estimator chosen,
        # stream armed): _prepare_lead_round is idempotent through this.
        self.armed = False
        self.method: Optional[str] = None
        self.kw_fn: Optional[Callable[[int], dict]] = None
        # (peer, token) -> weight for pushes the transport's request sink
        # folded COMPLETELY into the stream (its close(ok=True) ran); the
        # contribute handler and the commit adopt these into ``contribs``.
        self.stream_done: Dict[Any, float] = {}
        # Fencing generation this round state serves (Group.gen): 0 for the
        # original leader, bumped per failover recovery. Armed handlers
        # reject contribute/fetch traffic carrying any other generation.
        self.gen = 0
        # Tail-optimal recovery: XOR redundancy sidecars received for this
        # round (pred peer -> (succ peer, pred weight, xor bytes, t0 tile))
        # and the number of hedged re-requests this round issued.
        self.redund: Dict[str, tuple] = {}
        self.hedges_issued = 0
        self.t0 = time.monotonic()


class AveragerBase:
    """Shared packing, schema guard, and round bookkeeping."""

    mode = "base"

    def __init__(
        self,
        transport: Transport,
        dht: DHTNode,
        membership: SwarmMembership,
        *,
        min_group: int = 2,
        max_group: int = 16,
        gather_timeout: float = 20.0,
        join_timeout: float = 10.0,
        method: str = "mean",
        method_kw: Optional[dict] = None,
        namespace: str = "",
        wire: str = "f32",
        topk_frac: float = 0.01,
        topk_warmup_rounds: int = 0,
        powersgd_rank: int = 4,
        adaptive_timeout: bool = False,
        clock: Optional[Callable[[], float]] = None,
        round_deadline_s: Optional[float] = None,
        resilience=None,
        failure_detector=None,
        mesh_codec=None,
        group_schedule: Optional[GroupSchedule] = None,
        control_plane=None,
        telemetry=None,
        hedge: bool = True,
        tail_redundancy_frac: float = 0.0,
        controller=None,
        shard_manager=None,
    ):
        if wire not in ("f32", "bf16", "q8", "topk", "powersgd", "sign"):
            raise ValueError(f"unknown wire dtype {wire!r}")
        if wire == "sign":
            # 1-bit EF-signSGD is a GRADIENT compressor for gather-style
            # protocols (the topk reasoning: pairwise mixing compounds the
            # quantization per hop with no error feedback; sign of a
            # parameter tree is meaningless). Unlike topk it composes with
            # the robust estimators — reconstructions are DENSE ±scale
            # vectors, ordinary rows to krum/trimmed/bulyan.
            if self.mode not in ("sync", "byzantine"):
                raise ValueError(
                    f"wire='sign' is not supported for {self.mode} averaging "
                    "(gather-style sync/byzantine only)"
                )
        if wire == "powersgd":
            # Low-rank is a GRADIENT compressor for gather-style protocols,
            # same reasoning as topk below — but unlike topk it composes
            # with the robust estimators (reconstructions are DENSE, so
            # krum/trimmed/bulyan see ordinary vectors): any method is fine.
            if self.mode not in ("sync", "byzantine"):
                raise ValueError(
                    f"wire='powersgd' is not supported for {self.mode} averaging "
                    "(gather-style sync/byzantine only)"
                )
            if powersgd_rank < 1:
                raise ValueError(f"powersgd_rank must be >= 1, got {powersgd_rank}")
        if wire == "topk":
            # Top-k is a GRADIENT compressor for gather-style protocols:
            # pairwise mixing (gossip/butterfly) compounds the truncation at
            # every hop with no error feedback, and top-k of a parameter
            # tree is meaningless (it would zero most of the model).
            if self.mode not in ("sync", "byzantine"):
                raise ValueError(
                    f"wire='topk' is not supported for {self.mode} averaging "
                    "(gather-style sync/byzantine only)"
                )
            if method != "mean":
                # Coordinate-wise robust statistics over near-disjoint sparse
                # supports collapse to ~zero (at most coordinates the values
                # are {x, 0, 0, ...} and the median/trim keeps the zeros):
                # training would silently stall. Only the weighted mean is
                # sound over sparse contributions.
                raise ValueError(
                    f"wire='topk' requires method='mean' (got {method!r}): "
                    "robust estimators over sparse supports aggregate to zero"
                )
            if not 0.0 < topk_frac <= 1.0:
                raise ValueError(f"topk_frac must be in (0, 1], got {topk_frac}")
            if topk_warmup_rounds < 0:
                raise ValueError(
                    f"topk_warmup_rounds must be >= 0, got {topk_warmup_rounds}"
                )
        self.topk_frac = topk_frac
        # DGC-style sparsity warmup (Deep Gradient Compression's remedy for
        # early-training divergence under aggressive sparsification, which
        # the measured 80-round comparison shows: topk@1% converges behind
        # dense): over the first N SUCCESSFUL rounds the kept fraction ramps
        # exponentially from 1.0 (dense) to topk_frac, so early rounds — the
        # ones that contract init noise — ship (nearly) everything and the
        # aggressive fraction only applies once training stabilizes.
        self.topk_warmup_rounds = int(topk_warmup_rounds)
        self.powersgd_rank = int(powersgd_rank)
        self._psgd_codec = None  # built lazily: needs _specs from first _pack
        # Error-feedback residual (Deep Gradient Compression): entries a
        # contribution drops are banked and added to the NEXT contribution,
        # so every gradient coordinate eventually ships. The residual is
        # committed only when the round SUCCEEDS (_commit_ef): committing at
        # compression time would lose the shipped top-k mass forever on a
        # failed round (the trainer falls back to its raw local grad).
        self._ef_residual: Optional[np.ndarray] = None
        self._ef_pending: Optional[np.ndarray] = None
        # Checkpointed compressor state (EF residual + PowerSGD warm Q)
        # waiting for the first _pack, which fixes the specs it is
        # validated against. See wire_state()/load_wire_state().
        self._pending_wire_state: Optional[dict] = None
        # Whether the last round's contribution actually entered the
        # aggregate (sync members learn this from fetch meta; see average()).
        self._contribution_included = True
        self.transport = transport
        self.dht = dht
        self.membership = membership
        self.peer_id = membership.peer_id
        # Consensus wall clock (ClockSync.now from the volunteer): round
        # deadlines are ABSOLUTE times on this clock, so every member of a
        # group closes the round at the same instant regardless of skew.
        # Without one (step-cadence swarms) deadlines fall back to raw wall
        # time, which volunteer hardware can skew by more than a whole
        # budget — _deadline_wait then prefers the skew-free local bound.
        self._clock_synced = clock is not None
        self.clock = clock or time.time
        # Static per-round wall budget (seconds); None = the adaptive/
        # configured gather timeout. The resilience policy, when attached,
        # supersedes both with its learned deadline.
        self.round_deadline_s = round_deadline_s
        self.resilience = resilience
        self.failure_detector = failure_detector
        # Straggler pre-exclusion predicate consulted when WE lead group
        # formation: policy (phi + outcome history) when present, raw phi
        # suspicion otherwise.
        if resilience is not None:
            exclude = resilience.should_preexclude
        elif failure_detector is not None:
            exclude = failure_detector.suspect
        else:
            exclude = None
        # Leaders this node deposed via failover recovery (peer -> mono
        # time, TTL'd): consulted by the matchmaker's LEADERSHIP exclusion —
        # a peer that just crashed out of the lead is not handed it again
        # the moment it reappears — and by sync members refusing to join a
        # round such a peer leads while the strike is fresh.
        self._deposed_leaders: Dict[str, float] = {}
        # Replicated control plane (swarm/control_plane.py): matchmaking's
        # rendezvous polls read through a replica's micro-cache when one
        # answers (N members polling one forming round amortize to ~one
        # DHT lookup per cache window), with automatic fallback to direct
        # DHT reads — matchmaking never depends on a coordinator.
        self.control_plane = control_plane
        self.matchmaker = Matchmaker(
            transport, dht, self.peer_id, clock=self.clock, exclude=exclude,
            lead_exclude=self._lead_excluded,
            lead_weight=self._advertised_bw,
            rendezvous_get=(
                control_plane.rendezvous_get if control_plane is not None else None
            ),
        )
        self.min_group = min_group
        self.max_group = max_group
        self.gather_timeout = gather_timeout
        self.join_timeout = join_timeout
        self.method = method
        self.method_kw = method_kw or {}
        self.namespace = namespace
        # Wire codec for WAN payloads: "bf16" halves DCN traffic (the
        # averaging round's dominant cost at param scale) at bf16 rounding
        # error — acceptable for PARAMETER averaging in this genre. Part of
        # the schema hash, so mixed-wire swarms reject each other's rounds
        # instead of mis-decoding bytes.
        self.wire = wire
        # On-mesh data path (ops.mesh_codec): bf16 pack/unpack, PowerSGD
        # matmuls, and the leader's tile folds run on this volunteer's
        # local device mesh when the codec is active; None = the process
        # default, selected once at volunteer startup and surfaced in
        # stats()["mesh_codec"].
        self._mesh_codec = mesh_codec
        # Tail-optimal hedged recovery (OptiReduce, ROADMAP item 2): when
        # this node LEADS a streaming round, predicted-late peers' missing
        # tile ranges are re-requested over a second stream ahead of the
        # deadline (sync.refetch), with duplicates idempotent by (peer,
        # tile, fence). Advisory and leader-local — nothing is negotiated
        # on the wire; hedge=False restores pure deadline-drop.
        self.hedge = bool(hedge)
        # Optional summand redundancy: each member's last-k% tiles ride
        # XOR-coded on its ring successor's sidecar, decodable by the
        # leader iff the original misses commit. 0.0 = off.
        if not 0.0 <= tail_redundancy_frac <= 0.5:
            raise ValueError(
                f"tail_redundancy_frac must be in [0, 0.5], got {tail_redundancy_frac}"
            )
        self.tail_redundancy_frac = float(tail_redundancy_frac)
        # Cumulative hedge counters (stats()["hedge"] / volunteer summary).
        self.hedges_issued = 0
        self.hedges_failed = 0
        self.slots_recovered = 0
        self.redund_decodes = 0
        self._specs = None
        self._treedef = None
        self._schema: Optional[str] = None
        self.rounds_ok = 0
        self.rounds_skipped = 0
        # Adaptive round deadlines (Chameleon-style, PAPERS.md:6): observe
        # successful rounds' wall time and bound the NEXT round's waits by
        # EWMA + 4 deviations instead of the full configured timeout, so a
        # dead peer costs seconds, not the worst-case budget. Off by default
        # (opt-in via --adaptive-timeout); the configured value stays the
        # ceiling and is always used until the first success.
        self.adaptive_timeout = adaptive_timeout
        self._rt_ewma: Optional[float] = None
        self._rt_ewdev = 0.0
        self._round_degraded = False
        # Rounds that COMMITTED at the deadline with a partial group (vs
        # blocking on the slowest peer) — the deadline-bounded commit path.
        self.rounds_degraded = 0
        # Per-peer outcome detail for the round in flight, filled by the
        # paths that know it (leader gather, byzantine mesh) and flushed to
        # the resilience policy once per average() call. The epoch tags
        # which round those outcomes (and the policy's absent/late
        # reconciliation) belong to — late pushes for OLDER epochs are not
        # re-reported (their miss was already counted at their own flush).
        self._last_outcomes: Optional[dict] = None
        self._last_outcomes_epoch: Optional[str] = None
        # Cumulative leader-side aggregation-pipeline gauges (peak bytes
        # held, tiles aggregated early vs at the deadline, aggregate-thread
        # busy fraction) — filled by rounds this node LED with a streaming
        # aggregator; surfaced via stats()/volunteer summary/coord.status.
        self._agg_gauges: Dict[str, Any] = {}
        # Rotating multi-group schedule (Moshpit-style; None = the classic
        # one-group-per-epoch rendezvous). When attached, every round
        # rendezvouses under a group-scoped key — the group id folds into
        # the epoch hash, so fencing/tokens/retained bytes are group-scoped
        # without touching the round protocol itself.
        self.group_schedule = group_schedule
        if group_schedule is not None:
            # The per-round split reads a one-beat-stale membership view
            # (alive_peers(max_age=ttl/3)); keep the snapshot warm from the
            # heartbeat loop so the round path never walks the DHT for it.
            membership.keep_snapshot_fresh = True
        # The assignment of the round IN FLIGHT (reset by _rendezvous);
        # None on the single-group path. _last_seen_assignment persists
        # past the round for stats(). _last_group_expected is the
        # assignment's (pid, addr) set when every member's address was in
        # the membership records — the direct-join fast path's input.
        self._last_group: Optional[GroupAssignment] = None
        self._last_seen_assignment: Optional[GroupAssignment] = None
        self._last_group_expected: List[Tuple[str, Addr]] = []
        # Per-group gauges (schedule-attached nodes only): bounded
        # most-recent map — group ids rotate every window, so an unbounded
        # dict would grow one entry per rotation forever — plus cumulative
        # multigroup totals and a distinct-group counter.
        self._group_recent: Dict[str, dict] = {}
        self._group_totals: Dict[str, Any] = {
            "rounds_ok": 0, "rounds_skipped": 0, "rounds_degraded": 0,
            "rounds_led": 0, "last_commit_t": None,
        }
        self._groups_seen = 0
        # Per-hierarchy-level round counters (flat | intra | cross), only
        # populated on schedule-attached nodes: the observability half of
        # the hierarchical schedule — an operator must be able to see the
        # intra/cross cadence actually happening, per level, not folded
        # into one gauge.
        self._level_totals: Dict[str, Dict[str, int]] = {}
        # Telemetry plane (swarm/telemetry.py): round tracing, the unified
        # metrics registry, and the flight recorder. The volunteer passes a
        # shared per-process bundle (ClockSync-aligned clock, RPCs
        # registered); bare averagers get a private enabled one so the
        # surfaces exist in every test/bench construction.
        self.telemetry = (
            telemetry
            if telemetry is not None
            else telemetry_mod.Telemetry(peer_id=self.peer_id, clock=self.clock)
        )
        self._register_telemetry()
        # Training-health layer (swarm/health.py): sketch seed fixed to the
        # averaging namespace (every peer in a namespace projects into the
        # SAME space), the zone joined from membership for the per-zone
        # dispersion rollup, and quality flags surfaced into the membership
        # record so the swarm can see who this vantage distrusts.
        self.health = getattr(self.telemetry, "health", None)
        if self.health is not None and self.health.enabled:
            self.health.configure(self.namespace)
            self.health.zone_fn = lambda: self.zone
            if self.health.on_flag is None:
                self.health.on_flag = self._surface_quality_flags
        # Closed-loop adaptive controller (swarm/controller.py): reads
        # the telemetry this averager produces and retunes topology /
        # wire / cadence / per-level deadlines / hedge regime, epoch-
        # fenced (decisions apply from the NEXT round — _apply_controller
        # runs before formation). None = every knob stays hand-set (the
        # --no-adapt contract).
        self.controller = controller
        # Zone-sharded training (swarm/sharding.py): when attached, this
        # averager's tree is the volunteer's OWN shard slice and the
        # rendezvous scopes groups to same-shard peers (the ``shards``
        # map below), so cross-zone rounds move ~1/K of the tree. The
        # manager itself stays off the round path — it only moves state
        # when membership does.
        self.shard_manager = shard_manager
        # gates: the transport's measured per-peer downlink EWMA by
        # default. Pluggable because the chaos link model shapes WALL
        # TIME but not measured arrival rates (the documented set_link
        # fidelity limit) — campaigns and benches inject modeled
        # advertisements here, the hierarchy_bench extra_info pattern.
        self.bw_probe = self.transport.peer_bw_down
        if controller is not None:
            controller.attach(
                wire=self.wire, schedule=group_schedule, max_group=max_group,
            )
            self.telemetry.registry.source("controller", controller.summary)
        if shard_manager is not None:
            self.telemetry.registry.source("sharding", shard_manager.summary)
            if getattr(shard_manager, "telemetry", None) is None:
                # shard_lost/shard_recovered/fence events land in this
                # volunteer's flight recorder.
                shard_manager.telemetry = self.telemetry

    def _surface_quality_flags(self, flagged: List[str]) -> None:
        """Carry this vantage's flagged-peer list in the next heartbeat
        record (bounded: the flag set is a few ids)."""
        update = getattr(self.membership, "update_info", None)
        if update is not None:
            update(health_flagged=list(flagged))

    def _health_note_commit(
        self,
        buf: Optional[np.ndarray],
        trace: str,
        mass: Optional[dict] = None,
        quality: Optional[Dict[str, float]] = None,
    ) -> None:
        """One committed round's health bookkeeping (runs off the event
        loop): per-peer quality votes, the balanced mass report, and the
        post-round parameter sketch. Advisory — never fails the round."""
        h = self.health
        if h is None or not h.enabled:
            return
        try:
            if quality:
                h.observe_round_quality(quality, trace=trace)
                if self.controller is not None and buf is not None:
                    # Relative contribution dispersion for the cadence
                    # knob: sqrt(mean per-peer d2) over the aggregate
                    # norm — the leader-local form of the cross-zone
                    # sketch-dispersion trend (only cross rounds feed
                    # the trend; the controller filters by level).
                    den = float(np.linalg.norm(buf))
                    if den > 0:
                        rel = float(
                            np.sqrt(sum(quality.values()) / len(quality))
                        ) / den
                        self.controller.observe_dispersion(
                            self._last_group.level
                            if self._last_group is not None else None,
                            rel,
                        )
            if mass is not None:
                h.note_round_mass(mass, trace=trace)
            if buf is not None:
                h.note_sketch(buf, trace=trace)
        except Exception as e:  # noqa: BLE001 — health must never fail a round
            log.debug("health commit bookkeeping failed: %s", errstr(e))

    def _register_telemetry(self) -> None:
        """Re-register the pre-existing stats() surfaces into the unified
        registry as callback sources: every scrape flattens their numeric
        leaves into gauges under a stable dotted namespace, so the ad-hoc
        dicts PRs 1-9 accreted are all reachable from one scrape without
        rewriting the code that fills them."""
        reg = self.telemetry.registry
        reg.gauge_fn("swarm.rounds_ok", lambda: self.rounds_ok)
        reg.gauge_fn("swarm.rounds_skipped", lambda: self.rounds_skipped)
        reg.gauge_fn("swarm.rounds_degraded", lambda: self.rounds_degraded)
        reg.source("transport", self.transport.stats)
        reg.source("mesh_codec", lambda: self.mesh_codec.stats())
        if self._mesh_codec is not None and getattr(self._mesh_codec, "recorder", None) is None:
            # Slice-loss degrades land in this volunteer's flight recorder.
            # (The lazily-resolved process default is hooked by the
            # volunteer, which configures it.)
            self._mesh_codec.recorder = self.telemetry.recorder
        reg.source("aggregation", lambda: dict(self._agg_gauges))
        if self.group_schedule is not None:
            reg.source("groups", self.group_stats)
        if self.resilience is not None:
            reg.source("resilience", self.resilience.stats)
            if getattr(self.resilience, "recorder", None) is None:
                # Escalation/backoff transitions land in this volunteer's
                # flight recorder (resilience event hooks).
                self.resilience.recorder = self.telemetry.recorder
        mem_stats = getattr(self.membership, "stats", None)
        if mem_stats is not None:
            reg.source("control_plane", mem_stats)

    MAX_GROUP_GAUGES = 16

    @property
    def zone(self) -> str:
        """This volunteer's advertised zone ("" = unzoned), read from the
        membership record fields so the schedule, the stats, and the wire
        advertisement can never disagree."""
        return str(self.membership.extra_info.get("zone") or "")

    def _advertised_bw(self, pid: str) -> Optional[float]:
        """Advertised uplink bandwidth (bytes/s) for a leadership
        candidate, from the cached membership snapshot — the deterministic
        rendezvous input for bandwidth-weighted leader election (no extra
        RPCs; one-heartbeat staleness resolves via begin-wins)."""
        rec = self.membership.peer_record(pid)
        bw = (rec or {}).get("bw_up")
        if isinstance(bw, (int, float)) and not isinstance(bw, bool) and bw > 0:
            return float(bw)
        return None

    async def _rendezvous(self) -> str:
        """Rendezvous key for the NEXT round: the constant per-mode key
        (no schedule, lookup failure, or a swarm too small to split), or
        the group-scoped key from the rotating schedule. Side effect:
        ``self._last_group`` holds the round's assignment for gauges and
        ``self._last_group_expected`` the group's (pid, addr) set when every
        member's address is known — the direct-join formation input."""
        self._last_group = None
        self._last_group_expected = []
        if self.group_schedule is None:
            return self.round_key
        try:
            # One-heartbeat staleness is the membership system's native
            # resolution; accepting it here keeps the iterative DHT lookup
            # off every round's critical path (worst case: a just-dead
            # peer stays expected for one beat and costs a refused dial).
            peers = await self.membership.alive_peers(
                include_self=True, max_age=self.membership.ttl / 3.0
            )
        except Exception as e:  # noqa: BLE001 — a lookup hiccup must not kill rounds
            log.debug("group schedule: membership lookup failed (%s)", errstr(e))
            return self.round_key
        # Same population filter gossip partner-selection applies: only
        # peers averaging the same namespace count toward the split (a
        # record without avg_ns — bare test swarms — is not excluded).
        ids = [
            pid for pid, rec in peers.items()
            if pid == self.peer_id
            or not self.namespace
            or rec.get("avg_ns", self.namespace) == self.namespace
        ]
        # Zone advertisements for the hierarchical split (peers without one
        # — mixed-version swarms — schedule as the "" pseudo-zone; our own
        # zone comes from our record, or the local config if the snapshot
        # predates our join).
        zones = {
            pid: str(peers.get(pid, {}).get("zone") or "") for pid in ids
        }
        zones.setdefault(self.peer_id, self.zone)
        # Shard advertisements (zone-sharded training): peers carrying a
        # "shard" field in their record group only with same-shard peers,
        # and the shard rides in the group id — the round key, and hence
        # the epoch hash and fencing tokens, become shard-scoped. Peers
        # without the advertisement schedule exactly as before.
        shards: Dict[str, int] = {}
        for pid in ids:
            s = (peers.get(pid) or {}).get("shard")
            if isinstance(s, int) and not isinstance(s, bool):
                shards[pid] = s
        if self.shard_manager is not None and self.peer_id not in shards:
            p = self.shard_manager.primary_shard()
            if p is not None:
                shards[self.peer_id] = int(p)
        asg = self.group_schedule.assign(
            ids, self.peer_id, zones=zones, shards=shards or None
        )
        if asg is None:
            return self.round_key
        self._last_group = asg
        self._last_seen_assignment = asg
        # Direct-join needs every expected member's address. A member whose
        # record lacks one (can't happen for records membership itself
        # wrote, but belt-and-braces) is simply not expected — it can still
        # join us via its own view; if WE are the address-less one, the
        # self entry below fixes it (our own transport knows our addr).
        expected: List[Tuple[str, Addr]] = []
        for pid in asg.members:
            if pid == self.peer_id:
                expected.append((pid, self.transport.addr))
                continue
            addr = (peers.get(pid) or {}).get("addr")
            if isinstance(addr, (list, tuple)) and len(addr) == 2:
                expected.append((pid, (str(addr[0]), int(addr[1]))))
        self._last_group_expected = expected
        return f"{self.round_key}/{asg.group_id}"

    async def _form_group(self, round_key: str):
        """Form this round's group: the direct-join fast path when a
        schedule assignment (with addresses) is in hand — the group is
        deterministic, so the generic DHT rendezvous (K-replica store +
        iterative lookup per poll, ~60 DHT RPCs per member-round at N=16)
        collapses to ~4 direct RPCs — else the classic DHT rendezvous."""
        if (
            self._last_group is not None
            and len(self._last_group.members) < max(2, self.min_group)
        ):
            # A scheduled group below the configured floor (a lone peer —
            # or an undersized zone — at an intra rotation): the schedule
            # is deterministic, so the members that could rendezvous under
            # this key can never reach min_group — skip in O(1) instead of
            # burning the whole join timeout, and never run a round
            # beneath the operator's robustness minimum (a byzantine
            # min_group is a breakdown-point guarantee, not a preference).
            # The members keep training locally and re-mix at the next
            # cross rotation.
            log.debug(
                "round %s: scheduled group of %d below min_group %d, "
                "skipping", round_key, len(self._last_group.members),
                self.min_group,
            )
            return None
        if self._last_group is not None and len(self._last_group_expected) >= 2:
            group = await self.matchmaker.form_group_direct(
                round_key, self._last_group_expected,
                self.min_group, self.max_group, self.join_timeout,
                round_budget_s=self._round_budget(),
            )
            if group is None:
                # A scheduled group that never formed is the signature of
                # a stale/divergent membership view (churn, join burst):
                # make the next round's split read fresh.
                self.membership.invalidate_snapshot()
        else:
            group = await self.matchmaker.form_group(
                round_key, self.min_group, self.max_group, self.join_timeout,
                round_budget_s=self._round_budget(),
            )
        if group is not None and self._last_group is not None:
            # Stamp the schedule's group id here, once for every averaging
            # mode — stats and failover logs name the group by it.
            group.group_id = self._last_group.group_id
        return group

    def _note_group_round(
        self,
        ok: Optional[bool],
        *,
        degraded: bool = False,
        led: bool = False,
        size: int = 0,
    ) -> None:
        """Roll one finished round into the per-group gauges (``ok`` None =
        the round never formed — a matchmaking skip). No-op without a
        schedule: single-group stats stay byte-identical to before."""
        if self.group_schedule is None:
            return
        asg = self._last_group
        gid = asg.group_id if asg is not None else "single"
        level = asg.level if asg is not None else "flat"
        rec = self._group_recent.get(gid)
        if rec is None:
            self._groups_seen += 1
            while len(self._group_recent) >= self.MAX_GROUP_GAUGES:
                self._group_recent.pop(next(iter(self._group_recent)))
            rec = self._group_recent[gid] = {
                "rounds_ok": 0, "rounds_skipped": 0, "rounds_degraded": 0,
                "rounds_led": 0, "size": 0, "last_commit_t": None,
                "level": level,
                "zone": asg.zone if asg is not None else "",
            }
        if size:
            rec["size"] = size
        lv = self._level_totals.setdefault(
            level, {"rounds_ok": 0, "rounds_skipped": 0, "rounds_degraded": 0}
        )
        if ok:
            lv["rounds_ok"] += 1
            if degraded:
                lv["rounds_degraded"] += 1
        else:
            lv["rounds_skipped"] += 1
        tot = self._group_totals
        if ok:
            rec["rounds_ok"] += 1
            tot["rounds_ok"] += 1
            t = self.clock()
            rec["last_commit_t"] = t
            tot["last_commit_t"] = t
            if degraded:
                rec["rounds_degraded"] += 1
                tot["rounds_degraded"] += 1
            if led:
                rec["rounds_led"] += 1
                tot["rounds_led"] += 1
        else:
            rec["rounds_skipped"] += 1
            tot["rounds_skipped"] += 1

    def zone_traffic(self) -> dict:
        """WAN bytes split by zone locality, from the transport's per-peer
        counters joined against the membership snapshot's addr -> zone map
        (all traffic to a peer counts — averaging payloads dominate, and
        DHT/heartbeat bytes cross the same links). Peers whose address is
        not in the snapshot (departed, or the coordinator) are uncharged.
        This is the live, per-volunteer form of the hierarchical
        schedule's headline metric: cross-zone bytes, rollable into
        cross_zone_bytes_per_commit at the coordinator."""
        myz = self.zone
        zmap = self.membership.zone_by_addr()
        out = {
            "cross_zone_bytes_sent": 0, "cross_zone_bytes_received": 0,
            "intra_zone_bytes_sent": 0, "intra_zone_bytes_received": 0,
        }
        # Same-package read of the transport's per-peer counters (the
        # public stats() form stringifies the addr key).
        for addr, st in self.transport._peer_stats.items():
            z = zmap.get(addr)
            if z is None:
                continue
            side = "cross" if z != myz else "intra"
            out[f"{side}_zone_bytes_sent"] += st.bytes_sent
            out[f"{side}_zone_bytes_received"] += st.bytes_received
        return out

    def group_stats(self) -> dict:
        """Group-schedule gauges for stats()/volunteer report/coord.status:
        the current assignment (rotation, group id, split), cumulative
        multigroup round counters, and a bounded per-group breakdown so
        dashboards can see per-group commit health instead of one flat
        number silently averaging across groups. Hierarchy-aware: the
        volunteer's zone, the current assignment's level, per-level round
        counters, and the cross/intra-zone byte split ride along so the
        coordinator can roll up per-zone health and cross-zone bytes per
        committed round."""
        sched = self.group_schedule
        out: Dict[str, Any] = {"enabled": sched is not None}
        if sched is None:
            return out
        out["target_size"] = sched.target_size
        out["rotation_s"] = sched.rotation_s
        if sched.cross_zone_every_k:
            out["cross_zone_every_k"] = sched.cross_zone_every_k
        out["zone"] = self.zone
        asg = self._last_seen_assignment
        if asg is not None:
            out["rot"] = asg.rot
            out["group_id"] = asg.group_id
            out["n_groups_view"] = asg.n_groups
            out["n_peers_view"] = asg.n_peers
            out["level"] = asg.level
            if asg.shard is not None:
                out["shard"] = asg.shard
        out.update(self._group_totals)
        out["distinct_groups"] = self._groups_seen
        if self._level_totals:
            out["levels"] = {lv: dict(c) for lv, c in self._level_totals.items()}
        out.update(self.zone_traffic())
        out["recent"] = {g: dict(r) for g, r in self._group_recent.items()}
        return out

    @property
    def round_key(self) -> str:
        """Constant rendezvous key per mode+model — see Matchmaker.form_group.

        The namespace (the model name, set by the Volunteer) keeps volunteers
        training DIFFERENT models from ever rendezvousing into one group:
        without it a bert volunteer could join a gpt2 round and every
        exchange would be a wrong-size buffer.
        """
        ns = f"/{self.namespace}" if self.namespace else ""
        return f"avg/{self.mode}{ns}"

    # Distinct epochs a remote peer can allocate round state under between
    # our own average() calls. Combined with MAX_PARKED_CONTRIBS this bounds
    # attacker-driven memory to ROUNDS x CONTRIBS x payload even if the local
    # trainer never averages again.
    MAX_PARKED_ROUNDS = 32
    # Per-round cap on parked contributions (param-sized buffers under
    # unvalidated peer ids). One bound for every subclass that parks — a
    # per-subclass copy is how the byz path shipped uncapped in round 1.
    MAX_PARKED_CONTRIBS = 64

    def _observe_round_time(self, dt: float) -> None:
        """Feed a COMPLETE round's wall time into the deadline estimate.

        Callers must only report rounds where every expected peer arrived:
        a degraded round (subset aggregated after the deadline fired) takes
        ~the current deadline by construction, and observing it would
        ratchet the estimate geometrically back to the ceiling — defeating
        the feature in exactly the persistent-churn case it targets."""
        if self._rt_ewma is None:
            self._rt_ewma, self._rt_ewdev = dt, dt / 2.0
        else:
            self._rt_ewdev += 0.25 * (abs(dt - self._rt_ewma) - self._rt_ewdev)
            self._rt_ewma += 0.25 * (dt - self._rt_ewma)

    def _observe_round_failure(self) -> None:
        """A FAILED round doubles the estimate toward the configured
        ceiling (AIMD-style): without this, an estimate warmed on a fast
        network can never recover when latency genuinely rises — the peer
        would time out every round forever and silently train solo."""
        if self._rt_ewma is not None:
            self._rt_ewma = min(self._rt_ewma * 2.0, self.gather_timeout)
            self._rt_ewdev = min(self._rt_ewdev * 2.0 + 0.1, self.gather_timeout / 2.0)

    @property
    def effective_gather_timeout(self) -> float:
        if not self.adaptive_timeout or self._rt_ewma is None:
            return self.gather_timeout
        est = self._rt_ewma + 4.0 * self._rt_ewdev + 1.0
        return float(min(self.gather_timeout, max(est, 2.0)))

    # -- deadline-bounded rounds -------------------------------------------

    def _round_budget(self) -> float:
        """Wall-clock budget (seconds) for the NEXT round: the resilience
        policy's learned deadline when attached — PER HIERARCHY LEVEL,
        read off the round-in-flight's assignment, so a cross-zone round
        on a slow WAN runs its own learned budget while intra rounds stay
        tight — else the static ``round_deadline_s``, else the (possibly
        EWMA-adapted) gather timeout. The leader stamps ``clock() +
        budget`` into the begin."""
        if self.resilience is not None:
            level = self._last_group.level if self._last_group is not None else None
            return float(self.resilience.round_budget(level))
        if self.round_deadline_s:
            return float(self.round_deadline_s)
        return self.effective_gather_timeout

    def _deadline_remaining(self, group) -> Optional[float]:
        """Seconds until the group's commit deadline, or None when the
        begin carried none. Skew guard: without a ClockSync the deadline is
        raw wall time, and clocks on volunteer hardware can disagree by
        more than the whole budget — a member running ahead of the leader
        would see every round as already expired and collapse every wait to
        the floor (timing out its own pushes round after round, straight
        into pre-exclusion). The budget counted from when WE learned the
        round is skew-free; we learned it after the stamp, so it errs only
        toward waiting a little longer (the begin fan-out time)."""
        if group is None or group.deadline is None:
            return None
        if group.budget is not None and not self._clock_synced:
            return group.budget - (time.monotonic() - group.formed_mono)
        return group.deadline - self.clock()

    def _deadline_wait(self, group, floor: float = 0.5) -> float:
        """Seconds this node may still wait before the group's deadline.

        Clamped: the floor keeps a round that formed slowly (fan-out spent
        the budget) from committing with nothing at all, and the ceiling
        bounds a crafted/skewed deadline from a foreign leader to what this
        node would have waited anyway."""
        ceiling = max(self.gather_timeout, self._round_budget())
        remaining = self._deadline_remaining(group)
        if remaining is None:
            return min(self._round_budget(), ceiling)
        return float(min(max(remaining, floor), ceiling))

    async def _maybe_backoff(self) -> None:
        """Honor the policy's retry backoff after consecutive failed rounds
        (a partitioned volunteer stops paying full matchmaking cadence)."""
        if self.resilience is not None:
            delay = self.resilience.backoff_s()
            if delay > 0:
                log.info("%s round backoff %.1fs after failures", self.mode, delay)
                self.telemetry.event(
                    "backoff", mode=self.mode, delay_s=round(delay, 3)
                )
                await asyncio.sleep(delay)

    def _flush_round_outcome(self, duration_s: float, ok: bool) -> None:
        """Report the finished round to the resilience policy (once per
        average() call; per-peer detail only where this node observed it)
        and feed the closed-loop controller's evidence stream."""
        level = self._last_group.level if self._last_group is not None else None
        if self.resilience is not None:
            detail = self._last_outcomes or {}
            self.resilience.record_round(
                duration_s=duration_s,
                ok=ok,
                degraded=self._round_degraded,
                group_id=(
                    self._last_group.group_id
                    if self._last_group is not None else None
                ),
                level=level,
                **detail,
            )
        self._last_outcomes = None
        self._feed_controller(level, ok, duration_s)

    def _feed_controller(
        self, level: Optional[str], ok: bool, duration_s: float
    ) -> None:
        """One finished round's evidence for the controller: outcome +
        push size + the group's slowest measured link (the wire gate's
        inputs), and — on cross rounds — the per-zone-pair bandwidth
        floors the cadence knob learns from. Advisory: a controller bug
        must never fail a round."""
        c = self.controller
        if c is None:
            return
        try:
            push_bytes = bw_floor = None
            if self._specs is not None and self.wire in ("f32", "bf16"):
                esz = 4 if self.wire == "f32" else 2
                push_bytes = sum(s.size for s in self._specs) * esz
            expected = self._last_group_expected
            bws = [
                bw for bw in (
                    self.bw_probe(addr)
                    for pid, addr in expected if pid != self.peer_id
                ) if bw
            ]
            if bws:
                bw_floor = min(bws)
            c.observe_round(
                level=level, ok=ok, degraded=self._round_degraded,
                duration_s=duration_s, push_bytes=push_bytes,
                bw_floor=bw_floor, budget_s=self._round_budget(),
            )
            if level == "cross":
                # Zone-pair evidence: my zone against each other zone in
                # the MEMBERSHIP view (not just this round's group — the
                # hashed cross arcs give each vantage a different member
                # mix per rotation, and pair evidence fed only from group
                # composition left different volunteers' cadence gates
                # firing on different rounds, the exact divergence the
                # shared-evidence design exists to avoid). The pair's
                # floor is the slowest probed link to that zone.
                myz = self.zone
                my_addr = (str(self.transport.addr[0]), int(self.transport.addr[1]))
                by_zone: Dict[str, list] = {}
                for addr, z in self.membership.zone_by_addr().items():
                    if addr == my_addr or z == myz:
                        continue
                    by_zone.setdefault(z, []).append(addr)
                for z, addrs in by_zone.items():
                    pair = "|".join(sorted((myz, z)))
                    pbws = [
                        bw for bw in (self.bw_probe(a) for a in addrs) if bw
                    ]
                    c.observe_cross_pair(
                        pair,
                        bw_floor=min(pbws) if pbws else None,
                        ok=ok, degraded=self._round_degraded,
                    )
        except Exception as e:  # noqa: BLE001 — controller evidence is advisory
            log.debug("controller feed failed: %s", errstr(e))

    def _apply_controller(self) -> None:
        """Promote the controller's fenced decisions and apply them to
        the knobs this averager owns: schedule geometry (topology),
        cross-zone cadence, and the dense wire. Called ONCE per
        average() call, BEFORE rendezvous/formation — the epoch-fence
        contract: a decision staged during round N takes effect from
        round N+1 and can never mix two configurations into one round."""
        c = self.controller
        if c is None:
            return
        try:
            if not c.advance():
                return
            sched = self.group_schedule
            if sched is not None:
                ts = c.target_group_size()
                if ts:
                    sched.retune(
                        target_size=min(
                            max(ts, max(2, self.min_group)), self.max_group
                        )
                    )
                k = c.cross_zone_k()
                if k:
                    sched.retune(cross_zone_every_k=k)
            if c.wire in ("f32", "bf16") and c.wire != self.wire:
                self.set_wire(c.wire)
                if self.wire != c.wire:
                    # set_wire refused (chunk-alignment guard): the
                    # controller must adopt the ACTUAL wire or its gate
                    # evidence (push bytes at the wrong element size)
                    # and every future flip decision desync from
                    # reality.
                    c.wire = self.wire
        except Exception as e:  # noqa: BLE001 — a controller bug must not kill rounds
            log.warning("controller apply failed: %s", errstr(e))

    # -- leader failover bookkeeping ---------------------------------------

    # How long a deposed-leader strike keeps a peer out of the lead (and,
    # for sync members, out of rounds it leads). Long enough to cover a
    # crash-loop's restart, short enough that a genuinely-healed peer gets
    # the lead back within a few formation cadences.
    DEPOSED_LEADER_TTL_S = 90.0

    def _recently_deposed(self, pid: str) -> bool:
        t = self._deposed_leaders.get(pid)
        if t is None:
            return False
        if time.monotonic() - t > self.DEPOSED_LEADER_TTL_S:
            del self._deposed_leaders[pid]
            return False
        return True

    def _lead_excluded(self, pid: str) -> bool:
        """Leadership-exclusion predicate handed to the matchmaker: a
        recently-deposed ex-leader, a policy-pre-excluded straggler, or a
        phi/connection-suspected peer should not self-elect (from THIS
        node's vantage; divergent views cost one underfilled round, never
        mixed tensors — see Matchmaker._pick_leader)."""
        if self._recently_deposed(pid):
            return True
        try:
            if self.resilience is not None and self.resilience.should_preexclude(pid):
                return True
            if self.failure_detector is not None and self.failure_detector.suspect(pid):
                return True
        except Exception:  # noqa: BLE001 — a policy bug must not kill rounds
            pass
        return False

    def _effective_method(self, n_peers: int) -> Tuple[str, dict]:
        """(method, kwargs) to aggregate with THIS round. Consults the
        policy's runtime estimator escalation — except on the topk wire,
        where robust statistics over sparse supports are unsound and mean
        is forced at construction time."""
        method = self.method
        if self.resilience is not None and self.wire != "topk":
            method = self.resilience.recommend_method(self.method)
        return method, self._robust_kw(n_peers, method=method)

    def _sweep_rounds(self, rounds: Dict[str, "_Round"], max_age: Optional[float] = None) -> None:
        """Evict stale round state (parked contributions hold param-sized
        buffers; a round nobody finishes must not leak them)."""
        if max_age is None:
            max_age = self.gather_timeout * 3 + 30.0
        now = time.monotonic()
        for epoch in [e for e, st in rounds.items() if now - st.t0 > max_age]:
            del rounds[epoch]

    def _get_or_park_round(self, rounds: Dict[str, "_Round"], epoch: str) -> "_Round":
        """Round state for a remote-initiated epoch, swept + capped.

        Contributions can legitimately arrive before the local peer enters
        the round; but every unknown epoch string allocates a fresh _Round,
        so sweep on each RPC (not only in average()) and refuse once the
        number of remotely-created rounds hits the cap."""
        st = rounds.get(epoch)
        if st is None:
            self._sweep_rounds(rounds)
            parked = sum(1 for s in rounds.values() if not s.expected)
            if parked >= self.MAX_PARKED_ROUNDS:
                raise RPCError("parked round cap reached")
            st = rounds[epoch] = _Round([])
        return st

    # -- packing -----------------------------------------------------------

    def _pack(self, tree: Any) -> np.ndarray:
        buf, specs, treedef = flatten_to_buffer(tree)
        if self._schema is None:
            self._specs, self._treedef = specs, treedef
            self._schema = self._compute_schema()
        self._apply_pending_wire_state()
        return buf

    def _compute_schema(self) -> str:
        """Schema hash over (specs, wire, namespace) — ``self._specs``
        must exist. The namespace is part of the hash: a params tree and
        a grads tree of the same model flatten to IDENTICAL shapes, so
        shapes+dtypes+wire alone can't stop a cross-mode payload from
        being accepted on the receive path (e.g. a gossip push banked
        into the wrong inbox). With the namespace folded in, every
        averager's _check_schema rejects it at the door. The wire is in
        the hash too, which is what makes a controller wire flip safe by
        construction: a peer still on the old wire pushes under the old
        schema and is REJECTED (one excluded contribution), never
        mis-decoded."""
        wire_tag = self.wire
        if self.wire == "topk":
            wire_tag = f"topk:{self.topk_frac}"
        elif self.wire == "powersgd":
            wire_tag = f"powersgd:{self.powersgd_rank}"
        return hashlib.sha1(
            repr(
                [(s.shape, s.dtype) for s in self._specs]
                + [wire_tag, self.namespace]
            ).encode()
        ).hexdigest()[:16]

    def set_wire(self, wire: str) -> None:
        """Adopt a controller-selected DENSE wire (f32 <-> bf16), between
        rounds only (the controller's epoch fence guarantees the call
        site). Restricted to the dense elementwise pair: they share tile
        geometry and carry no compressor state, so the flip re-keys the
        schema hash and changes nothing else. Compressed wires (topk /
        powersgd / sign) carry error-feedback and warm factors whose
        churn would cost real gradient mass — those stay construction-
        time choices (the controller only RANKS them)."""
        if wire == self.wire:
            return
        if wire not in ("f32", "bf16") or self.wire not in ("f32", "bf16"):
            raise ValueError(
                f"live wire switch only supports f32<->bf16, "
                f"got {self.wire!r} -> {wire!r}"
            )
        esz = 4 if wire == "f32" else 2
        if self.transport.chunk_bytes % esz:
            log.warning(
                "wire switch to %s refused: chunk_bytes %d not divisible "
                "by element size %d", wire, self.transport.chunk_bytes, esz,
            )
            return
        old = self.wire
        self.wire = wire
        if self._specs is not None:
            self._schema = self._compute_schema()
        log.info("wire: %s -> %s (schema re-keyed)", old, wire)

    def _unpack(self, buf: np.ndarray) -> Any:
        return unflatten_from_buffer(buf, self._specs, self._treedef)

    # -- checkpointable compressor state -----------------------------------
    # A preempted volunteer on a lossy wire used to rejoin COLD: the
    # error-feedback residual (gradient mass owed to the swarm) and
    # PowerSGD's warm Q factors (which buy the power iteration its accuracy)
    # both lived only in process memory (r4 VERDICT #7; the outer-state
    # sidecar in training/checkpoint.py is the same pattern for the same
    # reason). wire_state() is read on the checkpoint thread while rounds
    # may be in flight — safe because every array in play is REPLACED
    # wholesale (new object assignment), never mutated in place, so a copy
    # taken here is a consistent value from some recent round per tensor.

    def wire_state(self) -> Optional[dict]:
        """Compressor state worth persisting, as a flat npz-able dict, or
        None when there is nothing learned yet (dense wires, or no round
        has run)."""
        if self.wire not in ("topk", "powersgd", "sign"):
            return None
        out: dict = {"wire": np.bytes_(self.wire.encode())}
        ef = self._ef_residual
        if ef is not None:
            out["ef"] = ef.copy()
        codec = self._psgd_codec
        if codec is not None and codec._warm_q:
            out["rank"] = np.int64(codec.rank)
            for idx, q in list(codec._warm_q.items()):
                out[f"q_{idx}"] = q.copy()
        return out if len(out) > 1 else None

    def load_wire_state(self, d: dict) -> None:
        """Adopt checkpointed compressor state. Parked until the first
        ``_pack``: sizes/shapes can only be validated against the specs,
        and a mismatch (different model, different wire, different rank)
        re-seeds LOUDLY — one warning naming the old/new wire+rank+size —
        with the same cold-start semantics as the outer-state sidecar."""
        self._pending_wire_state = {k: v for k, v in d.items()}
        if self._specs is not None:
            self._apply_pending_wire_state()

    def _apply_pending_wire_state(self) -> None:
        d, self._pending_wire_state = self._pending_wire_state, None
        if d is None:
            return
        wire = d.get("wire")
        if wire is not None:
            wire = np.asarray(wire).item()  # npz round-trips scalars as 0-d
            if isinstance(wire, bytes):
                wire = wire.decode()
        total = sum(s.size for s in self._specs)
        if wire != self.wire:
            # LOUD re-seed (VERDICT r5 #6): name exactly what mismatched so
            # a fleet-wide wire/rank change is diagnosable from one line —
            # the silent version cost the EF residual (gradient mass owed
            # to the swarm) with nothing in the logs.
            ef = d.get("ef")
            log.warning(
                "wire-state sidecar mismatch: checkpointed wire=%s rank=%s "
                "ef_size=%s vs configured wire=%s rank=%d schema_size=%d; "
                "re-seeding compressor state (EF residual and warm factors "
                "start cold)",
                wire, int(d.get("rank", -1)) if "rank" in d else None,
                getattr(ef, "size", None), self.wire, self.powersgd_rank, total,
            )
            return
        ef = d.get("ef")
        if ef is not None:
            if ef.size == total:
                self._ef_residual = np.asarray(ef, np.float32).reshape(-1).copy()
            else:
                log.warning(
                    "wire-state sidecar mismatch: checkpointed wire=%s EF "
                    "residual size %d vs configured wire=%s schema size %d; "
                    "re-seeding EF residual", wire, ef.size, self.wire, total,
                )
        if self.wire == "powersgd":
            ckpt_rank = int(d.get("rank", -1))
            if ckpt_rank == self.powersgd_rank:
                codec = self._psgd()
                for k, v in d.items():
                    if not k.startswith("q_"):
                        continue
                    idx = int(k[2:])
                    if idx < len(codec.plan) and codec.plan[idx][2] is not None:
                        _, m, r = codec.plan[idx][2]
                        if v.shape == (m, r):
                            codec._warm_q[idx] = np.asarray(v, np.float32).copy()
            elif ckpt_rank != -1:
                log.warning(
                    "wire-state sidecar mismatch: checkpointed wire=%s "
                    "rank=%d vs configured wire=%s rank=%d (schema size %d); "
                    "re-seeding PowerSGD warm factors (power iteration "
                    "restarts cold)",
                    wire, ckpt_rank, self.wire, self.powersgd_rank, total,
                )

    def _check_schema(self, args: dict) -> bool:
        # Before our first pack we don't know the schema yet — accept and let
        # the buffer-length guard at stack time catch real mismatches (an
        # early-arriving contribution from a faster peer is normal).
        return self._schema is None or args.get("schema") == self._schema

    @property
    def mesh_codec(self) -> mesh_codec_mod.MeshCodec:
        """This averager's on-mesh codec: the injected one, or the process
        default (resolved LAZILY so a volunteer that configures the default
        after constructing its averager is still honored)."""
        mc = self._mesh_codec
        return mc if mc is not None else mesh_codec_mod.get_default()

    def _psgd(self):
        """The PowerSGD codec for this averager's buffers (lazy: the plan
        needs ``_specs``, which exist after the first ``_pack``)."""
        if self._psgd_codec is None:
            from distributedvolunteercomputing_tpu.swarm import powersgd

            self._psgd_codec = powersgd.PowerSGDCodec(
                self._specs, rank=self.powersgd_rank,
                mesh_codec=self.mesh_codec,
            )
        return self._psgd_codec

    def _to_wire(self, buf: np.ndarray) -> bytes:
        if self.wire == "bf16":
            return self.mesh_codec.encode_bf16(buf).tobytes()
        if self.wire == "q8":
            return native.q8_encode(buf)
        if self.wire == "topk":
            # Auto mode: results/other sends keep their full support (or go
            # dense); top-k TRUNCATION is only ever applied to contributions
            # via _compress_contribution, where error feedback catches it.
            return native.topk_encode(buf)
        if self.wire == "powersgd":
            # Results ship dense (in the self-describing container): no
            # error feedback exists on the result path, so low-rank
            # truncation there would be silent, uncorrected error — the
            # same dense-results policy as topk above.
            return self._psgd().encode_dense(buf)
        if self.wire == "sign":
            # Results ship q8, NOT 1-bit: the result path has no error
            # feedback, and a sign-quantized aggregate would hand every
            # member an uncorrected ±scale caricature of the mean. q8 is
            # the same near-exact result fidelity the q8 wire itself runs on
            # (per-chunk scales, idempotent round-trip), at 1/4 the f32
            # bytes — so the sign wire's fetch leg matches the q8 wire and
            # its push leg is 32x. Tagged with its own magic: raw q8 starts
            # with a u64 count whose low bytes CAN collide with SIGN_MAGIC
            # for unlucky model sizes (n % 2^24 == 0x314753), so the two
            # legs must be distinguishable by construction, not probability.
            return _SIGN_RESULT_MAGIC + native.q8_encode(buf)
        return buf.tobytes()

    def _compress_contribution(
        self, buf: np.ndarray
    ) -> Tuple[bytes, Callable[[], np.ndarray]]:
        """(wire bytes, lazy dense-as-peers-see-it) for THIS round's
        contribution.

        For topk: add the error-feedback residual, keep the top k entries,
        and stage the remainder as PENDING — the caller commits it via
        ``_commit_ef(ok)`` once the round's outcome is known. For every other
        codec this is (_to_wire, lazy decode of the same bytes); the dense
        view is lazy because sync members never need it — only the leader
        and the byzantine path stack their own contribution.

        The f32/bf16 wires return a StreamPayload instead of bytes when the
        payload is big: chunks are encoded lazily while the transport is
        already writing earlier chunks (encode/send overlap), and the
        factory re-iterates for the byzantine full-mesh fan-out (one lazy
        encoding per push, none of them materializing the whole buffer)."""
        if self.wire not in ("topk", "powersgd", "sign"):
            self._note_codec_distortion(buf)
            if self.wire == "f32":
                return self._wire_stream(buf), lambda: buf
            if self.wire == "bf16":
                # Dense view via the roundtrip helper, not the wire bytes —
                # the wire may be a lazy stream that is never materialized.
                return self._wire_stream(buf), lambda: self._wire_roundtrip(buf)
            wire = self._to_wire(buf)
            return wire, lambda: self._buf_from_payload(wire)
        # Lossy-truncation codecs share the error-feedback protocol: add the
        # banked residual, truncate, stage (buf - sent) as PENDING until the
        # round's outcome commits or discards it (_commit_ef).
        if self._ef_residual is not None and self._ef_residual.size == buf.size:
            buf = buf + self._ef_residual
        if self.wire == "powersgd":
            from distributedvolunteercomputing_tpu.swarm import powersgd

            wire = self._psgd().encode(buf)
            # Own round-trip: the exact size is known — don't let the
            # anti-abuse default cap reject a legitimately huge model.
            sent = powersgd.decode(
                wire, max_floats=buf.size, mesh_codec=self.mesh_codec
            )
        elif self.wire == "sign":
            wire = native.sign_encode(buf)
            sent = native.sign_decode(wire, max_floats=buf.size)
        else:
            wire = native.topk_encode(buf, frac=self._effective_topk_frac())
            # Own round-trip: exact size known — same anti-abuse-cap
            # exemption as the powersgd branch above.
            sent = native.topk_decode(wire, max_floats=buf.size)
        self._ef_pending = buf - sent
        self._note_codec_distortion(buf, residual=self._ef_pending)
        return wire, lambda: sent

    def _note_codec_distortion(
        self, buf: np.ndarray, residual: Optional[np.ndarray] = None
    ) -> None:
        """Per-round relative compression error for the configured wire
        (training-health layer): the EF-residual norm over the gradient
        norm on the lossy wires — exactly the mass error feedback
        re-stages — and a sampled round-trip estimate on bf16/q8 (f32 is
        exact). The raw material for ranking wire formats by
        convergence-per-byte (ROADMAP item 1)."""
        h = self.health
        if h is None or not h.enabled:
            return
        try:
            if residual is not None:
                den = float(np.linalg.norm(buf))
                rel = float(np.linalg.norm(residual)) / den if den > 0 else 0.0
                h.note_codec_error(self.wire, rel)
                return
            if self.wire == "f32":
                h.note_codec_error("f32", 0.0)
                if self.controller is not None:
                    # Prospective bf16 sample: the controller's f32->bf16
                    # flip is gated on MEASURED bf16 distortion, which a
                    # fleet running f32 would otherwise never produce
                    # (the gauge only samples the active wire). One
                    # 64Ki-slice round-trip per round is the cheap probe
                    # that keeps the flip reachable.
                    p = buf[: min(buf.size, 65_536)]
                    mc = self.mesh_codec
                    prt = mc.decode_bf16(mc.encode_bf16(p))
                    pden = float(np.linalg.norm(p))
                    h.note_codec_error(
                        "bf16",
                        float(np.linalg.norm(prt - p)) / pden if pden > 0 else 0.0,
                    )
                return
            s = buf[: min(buf.size, 65_536)]
            if self.wire == "bf16":
                mc = self.mesh_codec
                rt = mc.decode_bf16(mc.encode_bf16(s))
            elif self.wire == "q8":
                rt = native.q8_decode(native.q8_encode(s))
            else:
                return
            den = float(np.linalg.norm(s))
            rel = float(np.linalg.norm(rt - s)) / den if den > 0 else 0.0
            h.note_codec_error(self.wire, rel)
        except Exception as e:  # noqa: BLE001 — a gauge bug must not fail the encode
            log.debug("codec distortion gauge failed: %s", errstr(e))

    def _robust_kw(self, n_peers: int, method: Optional[str] = None) -> dict:
        """Estimator kwargs adjusted to THIS round's group size — shared by
        the sync and byzantine aggregation paths so neither can regress to
        an unprotected (or crashing) state the other guards against:

        - explicit trim is clamped (with a warning) to the most robustness
          the group admits — never silently zeroed;
        - the DERIVED trim is len//4 floored at 1 once n >= 3: trim=0 under
          a robust method's name is a plain mean that includes an attacker
          at full weight (r5 review — len//4 alone was 0 for the 3..7-peer
          groups real churn produces; n=3 with trim=1 degenerates to the
          coordinate median, strictly more robust);
        - n=2 can't trim at all: trim=0 beats a ValueError killing every
          round (the sync path used to pass the function default trim=1
          straight through — a 2-peer trimmed_mean swarm failed forever)."""
        method = self.method if method is None else method
        kw = dict(self.method_kw) if method == self.method else {}
        if method != "trimmed_mean":
            return kw
        if "trim" in kw:
            trim = int(kw["trim"])
            if trim * 2 >= n_peers:
                feasible = (n_peers - 1) // 2
                log.warning(
                    "trimmed_mean trim=%d infeasible for %d peers; "
                    "clamping to %d this round", trim, n_peers, feasible,
                )
                kw["trim"] = feasible
        else:
            kw["trim"] = max(1, n_peers // 4) if n_peers >= 3 else 0
        return kw

    def _effective_topk_frac(self) -> float:
        """Current kept fraction under the warmup schedule (see __init__);
        the configured topk_frac once warmup completes or when disabled."""
        n = self.topk_warmup_rounds
        if n <= 0 or self.rounds_ok >= n:
            return self.topk_frac
        return float(self.topk_frac ** (self.rounds_ok / n))

    def _commit_ef(self, ok: bool) -> None:
        """Resolve the staged error-feedback residual for the last
        compressed contribution: on success the remainder is banked for the
        next round; on failure the PREVIOUS residual stands (nothing was
        delivered, and the trainer applies its raw local grad instead)."""
        if self._ef_pending is not None:
            if ok:
                self._ef_residual = self._ef_pending
            self._ef_pending = None

    def _wire_roundtrip(self, buf: np.ndarray) -> np.ndarray:
        """The local buffer as PEERS see it after the wire codec. Pairwise
        protocols (butterfly) mix this instead of the raw f32 buffer so both
        sides of a pair operate on identical inputs; idempotent for every
        codec (a round-trip of already-codec'd values is exact: bf16 by
        representability, q8 because the per-chunk scale reconstructs)."""
        if self.wire == "bf16":
            mc = self.mesh_codec
            return mc.decode_bf16(mc.encode_bf16(buf))
        if self.wire == "q8":
            return native.q8_decode(native.q8_encode(buf))
        if self.wire == "topk":
            return native.topk_decode(native.topk_encode(buf), max_floats=buf.size)
        # powersgd: pairwise modes are refused at construction; the only
        # non-contribution sends are dense-container results, an exact
        # round-trip — so the raw buffer IS the as-peers-see-it view.
        return buf

    def _buf_from_payload(self, payload: bytes) -> Optional[np.ndarray]:
        if self.wire == "bf16":
            return self.mesh_codec.decode_bf16(np.frombuffer(payload, np.uint16))
        if self.wire == "q8":
            return native.q8_decode(payload)
        if self.wire == "topk":
            # Same deferral story as powersgd below: the sparse header's n is
            # sender-controlled, so pre-schema the decode is unbounded —
            # park raw and resolve at aggregation; post-schema, cap at the
            # exact expected size.
            if self._specs is None:
                return None
            return native.topk_decode(
                payload, max_floats=sum(s.size for s in self._specs)
            )
        if self.wire == "sign":
            if payload[:3] == native.SIGN_MAGIC:
                # A 1-bit contribution: n is sender-controlled and expands
                # 32x on decode — same pre-schema deferral as topk below.
                if self._specs is None:
                    return None
                return native.sign_decode(
                    payload, max_floats=sum(s.size for s in self._specs)
                )
            if payload[:3] == _SIGN_RESULT_MAGIC:
                # Round RESULT leg: tagged q8 (see _to_wire) — linear 4x
                # expansion, bounded by the payload's own size, no deferral.
                return native.q8_decode(payload[3:])
            raise ValueError("sign-wire payload with unknown leg tag")
        if self.wire == "powersgd":
            # Self-describing container (low-rank contributions AND dense
            # results). The decode is capped at EXACTLY the expected size —
            # a low-rank entry expands (n+m)*r wire floats to n*m, so
            # without the cap a few-KB container could buy a multi-GB
            # allocation. Before our first _pack the expected size is
            # unknown and no generic cap is safe (r4 advisor: 64 parked
            # contribs x 32 rounds x 2 GiB decodes = multi-TiB amplification
            # from MBs of attacker bandwidth) — so pre-schema pushes are NOT
            # decoded here: return the deferred sentinel, park the raw
            # payload (memory then costs the attacker its own bandwidth,
            # bounded by transport MAX_PAYLOAD), and decode at aggregation
            # time when specs exist (see _decode_deferred).
            if self._specs is None:
                return None
            from distributedvolunteercomputing_tpu.swarm import powersgd

            return powersgd.decode(
                payload, max_floats=sum(s.size for s in self._specs),
                mesh_codec=self.mesh_codec,
            )
        return np.frombuffer(payload, np.float32).copy()

    # -- off-loop wrappers for payload-sized work --------------------------
    # Flatten/codec/aggregate over a full param tree is seconds of CPU at
    # GPT-2 scale (measured: q8 of the 498 MB tree ~2.6 s). Run synchronously
    # it stalls the event loop — heartbeats, DHT RPCs, and matchmaking
    # begins all miss their (5 s) deadlines, failing rounds that would
    # otherwise succeed. Same policy as state_sync's _serialize: the loop
    # schedules, worker threads move bytes. Per-averager work stays serial
    # (one average() at a time); RPC-path decodes may run concurrently on
    # distinct payloads, so callers must re-check insert conditions after
    # the await (the loop may have run other handlers meanwhile).

    async def _pack_and_compress(self, tree: Any):
        """(buf, wire_bytes, dense_fn) off the event loop."""

        def work():
            buf = self._pack(tree)
            wire, sent = self._compress_contribution(buf)
            return buf, wire, sent

        return await asyncio.to_thread(work)

    async def _decode_payload(self, payload: bytes) -> Optional[np.ndarray]:
        return await asyncio.to_thread(self._buf_from_payload, payload)

    async def _decode_deferred(self, st: "_Round") -> None:
        """Decode contributions parked BEFORE this node's first ``_pack``
        (powersgd only: ``_buf_from_payload`` defers pre-schema decodes and
        the contribute handlers park the raw payload instead). Runs on the
        aggregation path, where specs are guaranteed — the caller just
        packed its own contribution — so every decode is capped at exactly
        the expected dense size. Entries whose payload is missing or fails
        to decode are dropped, the same fate a size-mismatched buffer meets
        at aggregation."""
        deferred = [k for k, c in st.contribs.items() if c[1] is None]
        for k in deferred:
            pl = st.payloads.get(k)
            buf = None
            if pl is not None:
                try:
                    buf = await self._decode_payload(pl)
                except (ValueError, RPCError):
                    buf = None
            if buf is None:
                st.contribs.pop(k, None)
                st.payloads.pop(k, None)
            elif k in st.contribs:  # re-check: handlers ran during decode
                st.contribs[k] = (st.contribs[k][0], buf)

    async def _encode_wire(self, buf: np.ndarray) -> bytes:
        return await asyncio.to_thread(self._to_wire, buf)

    def _wire_stream(self, buf: np.ndarray):
        """Wire form of ``buf`` as a lazily-encoded StreamPayload when the
        codec is elementwise (f32/bf16: encoding a slice == slice of the
        encoding) and the payload is big enough to chunk. The transport
        pulls each chunk on a worker thread while the previous chunk is
        already on the socket — encode/send overlap — instead of paying a
        full encode before the first byte moves. Other codecs (q8's
        scales-then-data layout, the sparse/low-rank containers) are not
        slice-concatenable and return whole bytes, which the transport
        still chunk-frames on the wire."""
        cb = self.transport.chunk_bytes
        if self.wire == "f32" and buf.nbytes > cb:
            step = cb // 4

            def gen(b=buf, step=step):
                for i in range(0, b.size, step):
                    yield b[i : i + step].tobytes()

            return StreamPayload(buf.size * 4, gen)
        if self.wire == "bf16" and buf.size * 2 > cb:
            step = cb // 2

            def gen(b=buf, step=step):
                mc = self.mesh_codec
                if mc.active:
                    # One whole-buffer device encode, chunks sliced from the
                    # result: the pack is 4-5x the per-chunk host encode, so
                    # paying it up front still beats the chunk cadence, and
                    # the first chunk is ready after one kernel.
                    bits = mc.encode_bf16(b)
                    for i in range(0, bits.size, step):
                        yield bits[i : i + step].tobytes()
                    return
                for i in range(0, b.size, step):
                    yield native.f32_to_bf16(b[i : i + step]).tobytes()

            return StreamPayload(buf.size * 2, gen)
        return self._to_wire(buf)

    async def _encode_wire_stream(self, buf: np.ndarray):
        """``_encode_wire`` that prefers the lazy stream: cheap closure
        creation for f32/bf16 (the encode itself happens chunk-by-chunk off
        the loop during the write), full off-loop encode otherwise."""
        if self.wire in ("f32", "bf16"):
            return self._wire_stream(buf)
        return await self._encode_wire(buf)

    def _result_sink(self):
        """(sink, state) for decode-on-arrival of a round-result fetch on
        the f32/bf16 wires: each verified chunk lands straight in the final
        f32 buffer (f32: a byte copy; bf16: the native widening) while later
        chunks are still in flight — fetch-side decode starts on the FIRST
        chunk, and the full payload is never held as a separate byte
        buffer. Returns (None, None) when the wire or schema doesn't allow
        it; the caller then falls back to the plain payload decode."""
        if self.wire not in ("f32", "bf16") or self._specs is None:
            return None, None
        n = sum(s.size for s in self._specs)
        esz = 4 if self.wire == "f32" else 2
        expect = n * esz
        state: dict = {"filled": 0, "out": None, "expect": expect}
        wire = self.wire

        def sink(off: int, total: int, data: bytes) -> None:
            # Raising rejects the payload at the transport (the call fails
            # with an RPCError; the connection survives) — the same fate a
            # wrong-size result meets in the buffered decode path.
            if total != expect:
                raise ValueError(f"result payload {total}B != schema {expect}B")
            if off % esz or len(data) % esz:
                raise ValueError("result chunk not element-aligned")
            out = state["out"]
            if out is None:
                out = state["out"] = np.empty(n, np.float32)
            if wire == "f32":
                out.view(np.uint8)[off : off + len(data)] = np.frombuffer(
                    data, np.uint8
                )
            else:
                out[off // 2 : (off + len(data)) // 2] = native.bf16_to_f32(
                    np.frombuffer(data, np.uint16)
                )
            state["filled"] += len(data)

        def reset() -> None:
            # The transport's transparent retry re-delivers the response
            # from offset 0: forget anything the dead stream handed us.
            state["filled"] = 0

        sink.reset = reset
        return sink, state

    # -- public API --------------------------------------------------------

    async def average(self, tree: Any, round_no: int, weight: float = 1.0) -> Optional[Any]:
        raise NotImplementedError

    def stats(self) -> dict:
        out = {
            "mode": self.mode,
            "rounds_ok": self.rounds_ok,
            "rounds_skipped": self.rounds_skipped,
            "rounds_degraded": self.rounds_degraded,
            # Per-peer transport counters (bytes in/out, RPC count, connect
            # count, latency EWMA): the WAN-tier evidence operators and
            # experiments read off the volunteer summary.
            "transport": self.transport.stats(),
            # Which data-path backend this volunteer selected at startup
            # (mesh = codec+folds on the local device mesh; host = numpy),
            # plus degrade evidence — the per-volunteer selection the
            # ROADMAP item calls for.
            "mesh_codec": self.mesh_codec.stats(),
        }
        if self._agg_gauges:
            out["aggregation"] = dict(self._agg_gauges)
        if self.group_schedule is not None:
            out["groups"] = self.group_stats()
        if self.resilience is not None:
            out["resilience"] = self.resilience.stats()
        if self.controller is not None:
            out["controller"] = self.controller.summary()
        # Control-plane accounting: messages this node spends per heartbeat
        # interval (the batching headline metric) plus the failover
        # client's replica view — proves the batched path is actually in
        # use and shows where traffic fails over during replica churn.
        cp_stats = self.membership.stats() if hasattr(self.membership, "stats") else None
        if cp_stats is not None and (
            cp_stats.get("beats") or self.control_plane is not None
        ):
            out["control_plane"] = cp_stats
        if self.hedges_issued or self.slots_recovered or self.redund_decodes:
            # Tail-optimal recovery scorecard (cumulative, leader vantage):
            # per-round detail lives in aggregation gauges + mass reports.
            out["hedge"] = {
                "enabled": self.hedge,
                "issued": self.hedges_issued,
                "failed": self.hedges_failed,
                "slots_recovered": self.slots_recovered,
                "redund_decodes": self.redund_decodes,
            }
        out["telemetry"] = self.telemetry.summary()
        # SNAPSHOT semantics: several sub-dicts above are filled in place by
        # background work (round paths, the aggregation worker, heartbeat
        # loops), and before this deep-copy a held stats() reference kept
        # mutating under the reader — a bench could record one number and
        # report another. A stats() return is now frozen at read time.
        return copy.deepcopy(out)

    def _note_agg_round(self, stream: Optional[StreamingAggregator]) -> None:
        """Roll one led round's streaming-aggregation gauges into the
        cumulative counters behind ``stats()['aggregation']``."""
        if stream is None:
            return
        g = stream.gauges()
        agg = self._agg_gauges
        agg["mode"] = g["mode"]
        agg["rounds_streamed"] = agg.get("rounds_streamed", 0) + 1
        agg["peak_bytes_held"] = max(agg.get("peak_bytes_held", 0), g["peak_bytes_held"])
        for k in (
            "tiles_early", "tiles_deadline", "streamed_contribs",
            "dense_contribs", "aborted_contribs", "folder_flushes",
            "tiles_recovered", "hedge_duplicates", "hedge_dropped",
        ):
            agg[k] = agg.get(k, 0) + g[k]
        agg["ring_flushes"] = agg.get("ring_flushes", 0) + g.get("ring_flushes", 0)
        if g.get("folder_kind"):
            agg["folder_kind"] = g["folder_kind"]
        agg["codec_backend"] = g["codec_backend"]
        agg["agg_busy_s"] = round(agg.get("agg_busy_s", 0.0) + g["agg_busy_s"], 6)
        agg["last_busy_frac"] = g["agg_busy_frac"]


class SyncAverager(AveragerBase):
    """Leader-gather allreduce: members push, leader aggregates, members fetch.

    The inter-slice half of the synchronous GradientAverager (config 2). At
    reference swarm scale (2-8 slices) a leader-gather round is one RTT
    cheaper than a ring and churn-safe on both sides: missing contributions
    are dropped at the deadline, and a DEAD LEADER is deposed mid-round —
    the deterministic successor re-leads a fenced recovery round over the
    same retained contributions (generation bump on the epoch), so one
    crashed volunteer costs the group its contribution, not everyone's
    streamed work (see the module doc's leader-failover section).
    """

    mode = "sync"

    # Longest a member waits for a successor's recovery begin after
    # deposing the leader. The successor detects the same death on its own
    # push/fetch leg, so the lag between depositions is connection-error
    # scale (seconds), not deadline scale.
    RECOVERY_BEGIN_WAIT_S = 6.0
    # TTL for a recovery begin that arrived before its member started
    # waiting (the successor can depose faster than a slow member).
    RECOVER_PARKED_TTL_S = 8.0
    # Fencing generations accepted per epoch: one original round plus a
    # bounded failover chain — a runaway (or malicious) recovery cascade
    # stops here.
    MAX_RECOVERY_GEN = 3
    # Bound on per-epoch generation records a remote peer can allocate.
    MAX_EPOCH_GENS = 256

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._rounds: Dict[str, _Round] = {}
        self.transport.register("sync.contribute", self._rpc_contribute)
        self.transport.register("sync.fetch", self._rpc_fetch)
        # Leader-failover recovery plumbing: recovery begins land here
        # (future when a member is already waiting, parked otherwise —
        # the matchmaking begin pattern), and _epoch_gen fences each epoch
        # at the highest generation this node accepted.
        self.transport.register("sync.recover", self._rpc_recover)
        self._recover_futs: Dict[str, asyncio.Future] = {}
        self._recover_parked: Dict[str, Tuple[float, dict]] = {}
        self._epoch_gen: Dict[str, Tuple[float, int]] = {}
        # Failover observability (stats()["failover"], volunteer report,
        # coord.status): depositions this node decided, rounds whose result
        # arrived via a recovery generation, failed recovery attempts, and
        # deposition->recovered-result latency.
        self.leaders_deposed = 0
        self.rounds_recovered = 0
        self.recoveries_failed = 0
        self._recovery_lat_last: Optional[float] = None
        self._recovery_lat_ewma: Optional[float] = None
        # Test/chaos instrumentation: named leader-round phase points fire
        # these hooks (chaos campaigns kill/partition the leader at exact
        # protocol points) and honor DVC_CHAOS_LEADER_DIE_PHASE (subprocess
        # e2e: the leader SIGKILLs itself at the named phase). Production
        # leaves both empty/unset.
        self._phase_hooks: Dict[str, Callable[[], Any]] = {}
        # Streaming leader aggregation: chunked contribute payloads decode
        # and fold into the round's aggregator AS THEY ARRIVE instead of
        # buffering per-peer dense vectors (swarm/agg_stream.py).
        self.transport.register_request_sink(
            "sync.contribute", self._contribute_stream_factory
        )
        # Tail-optimal hedged recovery plumbing. sync.refetch serves tile
        # RANGES of a member's retained (PR-4) contribution back to the
        # round leader over a second stream — re-encoded from the retained
        # dense form, bit-identical for the elementwise wires, so EF can
        # never double-stage. sync.redund_share / sync.redund carry the
        # optional summand-redundancy sidecars (ring neighbor's last-k%
        # tiles, XOR-coded).
        self.transport.register("sync.refetch", self._rpc_refetch)
        self.transport.register("sync.redund_share", self._rpc_redund_share)
        self.transport.register("sync.redund", self._rpc_redund)
        # epoch -> {"gen", "token", "buf" (dense f32), "weight", "group"}:
        # the member-side registry behind sync.refetch, set around the
        # push/fetch leg and dropped when the round resolves.
        self._push_retained: Dict[str, dict] = {}
        # (epoch, pred peer) -> (mono, weight, t0 tile, tail bytes,
        # fence): ring neighbors' redundancy shares, stashed until our
        # own round state for that epoch exists (then XOR-coded to the
        # leader), and retained as the replica-holder refetch source
        # (served by fence+share alone when our own retention is gone).
        self._redund_shares: Dict[Tuple[str, str], tuple] = {}

    # The four instrumented leader-round phases, in protocol order (the
    # kill-at-phase chaos matrix iterates these).
    LEADER_PHASES = ("pre_arm", "mid_stream", "post_partial_commit", "pre_fetch")

    def _phase_armed(self, name: str) -> bool:
        return (
            name in self._phase_hooks
            or os.environ.get("DVC_CHAOS_LEADER_DIE_PHASE") == name
        )

    async def _phase(self, name: str) -> None:
        """Fire the instrumentation hook for a leader-round phase point.
        No-op in production (no hooks registered, env unset)."""
        hook = self._phase_hooks.get(name)
        if hook is not None:
            res = hook()
            if asyncio.iscoroutine(res):
                await res
        if os.environ.get("DVC_CHAOS_LEADER_DIE_PHASE") == name:
            # Subprocess e2e chaos: die EXACTLY like a preempted/crashed
            # volunteer — no cleanup, no tombstone, sockets reset by the
            # kernel. Test-only; unset in production.
            log.warning("chaos: leader dying at phase %r (SIGKILL)", name)
            os.kill(os.getpid(), signal.SIGKILL)

    def failover_stats(self) -> dict:
        return {
            "leaders_deposed": self.leaders_deposed,
            "rounds_recovered": self.rounds_recovered,
            "recoveries_failed": self.recoveries_failed,
            "recovery_latency_s_last": (
                round(self._recovery_lat_last, 3)
                if self._recovery_lat_last is not None else None
            ),
            "recovery_latency_s_ewma": (
                round(self._recovery_lat_ewma, 3)
                if self._recovery_lat_ewma is not None else None
            ),
        }

    def stats(self) -> dict:
        out = super().stats()
        out["failover"] = self.failover_stats()
        return out

    def _contribute_stream_factory(self, args: dict, total: int):
        """Per-request sink for a member's chunked contribution, or None to
        buffer normally. Only an ARMED round streams (the leader entered it:
        tokens and aggregator exist) — pre-arming pushes park as before, and
        every condition a streamed push skips here is re-checked the same
        way the buffered handler would have checked it."""
        if self.wire not in ("f32", "bf16"):
            return None
        epoch = args.get("epoch")
        st = self._rounds.get(epoch) if isinstance(epoch, str) else None
        if st is None or st.stream is None or st.result_ready.is_set():
            return None
        if self._fence_of(args) != st.gen:
            return None  # stale generation: the buffered handler rejects it loudly
        if not self._check_schema(args):
            return None
        peer = args.get("peer")
        token = args.get("token", "")
        key = (peer, token)
        if st.tokens is None or not peer or st.tokens.get(peer) != token:
            return None  # forgery: the buffered handler rejects it loudly
        if key in st.contribs or key in st.stream_done:
            return None  # duplicate/retry: idempotent ack via the handler
        try:
            weight = float(args.get("weight"))
        except (TypeError, ValueError):
            return None

        def on_done(ok: bool) -> None:
            if ok:
                # Sealed BEFORE the handler task runs (the transport closes
                # the sink while still reading the frame), so the handler —
                # and a commit racing it — can adopt the entry.
                st.stream_done[key] = weight

        return st.stream.make_sink(peer, weight, total, on_done=on_done)

    @staticmethod
    def _fence_of(args: dict) -> int:
        """The fencing generation a request carries (0 for legacy/original
        traffic; malformed values read as -1, matching no round)."""
        fence = args.get("fence", 0)
        try:
            return int(fence)
        except (TypeError, ValueError):
            return -1

    def _note_fence_rejected(self, rpc: str, args: dict, have_gen: int) -> None:
        """Flight-record + count one fenced-off request: the post-mortem
        evidence a chaos verdict wants when stale traffic was refused."""
        if not self.telemetry.enabled:
            return  # --no-telemetry: every record path is a no-op
        self.telemetry.event(
            "fence_rejected",
            rpc=rpc,
            epoch=str(args.get("epoch", "?")),
            have_gen=have_gen,
            got_gen=self._fence_of(args),
            peer_from=str(args.get("peer", "?")),
        )
        self.telemetry.registry.counter(
            "swarm.fences_rejected_total", "stale-generation requests refused"
        ).inc(rpc=rpc)

    async def _rpc_contribute(self, args: dict, payload: bytes):
        # Handler-side span: the member's push carried its round trace in
        # the frame meta, so this span stitches into the member's tree —
        # the leader-side evidence of where a push's bytes went. Wrapped
        # here (not inline) so REJECTED pushes record too: the error paths
        # are exactly what a post-mortem wants timed.
        push_sp = self.telemetry.tracer.start(
            "fold.push", role="leader", peer_from=str(args.get("peer", "?"))
        )
        try:
            ret = await self._contribute_inner(args, payload)
        except BaseException:
            if push_sp is not None:
                push_sp.end(ok=False)
            raise
        if push_sp is not None:
            push_sp.end(ok=True)
        return ret

    async def _contribute_inner(self, args: dict, payload: bytes):
        if not self._check_schema(args):
            raise RPCError("schema mismatch")
        # Members can push before the leader enters its round: park it
        # (swept + capped against fabricated-epoch flooding).
        st = self._get_or_park_round(self._rounds, args["epoch"])
        if st.tokens is not None and self._fence_of(args) != st.gen:
            # Epoch fencing: this (armed) round state serves generation
            # st.gen; a push stamped with any other generation is a stale
            # member (or a deposed ex-leader's relayed traffic) and must
            # not mix into this round. Unarmed (parked) rounds skip the
            # check — their entries are re-filtered against the token
            # table at arming anyway.
            self._note_fence_rejected(
                "sync.contribute", args, have_gen=st.gen
            )
            raise RPCError(
                f"fencing mismatch: round epoch is at generation {st.gen}, "
                f"push carries {self._fence_of(args)} (deposed/stale)"
            )
        # Keyed by (peer, token): a push can neither OVERWRITE another entry
        # (no displacement of an honest contribution by a later forgery) nor
        # PRE-BLOCK one (an early forgery under peer P doesn't stop P's real
        # push landing under its correct token). At aggregation the leader
        # keeps only the entry whose token it actually issued to that peer.
        key = (args["peer"], args.get("token", ""))
        if (
            st.result_ready.is_set()
            and self.resilience is not None
            and st.tokens is not None
            and st.tokens.get(key[0]) == key[1]
            # Only for the MOST RECENT round this leader scored: round state
            # outlives its commit by the fetch window, and a push for an
            # older epoch already had its miss counted (absent) at that
            # round's own flush — reporting it late now would double-count
            # one slow round against whatever the peer did since.
            and args.get("epoch") == self._last_outcomes_epoch
        ):
            # Authentic contribution from an expected member, landing AFTER
            # the deadline committed the round: the definition of LATE (the
            # absent set at commit only proves non-arrival; this proves the
            # peer was alive but slow — exactly what the policy tracks).
            self.resilience.record_late_arrival(key[0])
        if st.tokens is not None and st.tokens.get(key[0]) != key[1]:
            # Leader has entered the round, so the issued-token table is
            # known: reject forgeries OUTRIGHT rather than parking them —
            # otherwise 64 fabricated keys fill the cap and pre-block every
            # honest push for the rest of the round.
            raise RPCError("invalid contribution token for this round")
        if key in st.stream_done:
            # The transport's request sink already decoded and folded this
            # push chunk-by-chunk as it arrived (streaming aggregation):
            # record the contribution without a dense copy — there is none.
            st.contribs.setdefault(key, (st.stream_done[key], STREAMED))
            if st.expected and {
                p for p, t in st.contribs
                if st.tokens is None or st.tokens.get(p) == t
            } >= st.expected:
                st.full.set()
            return {"ok": True}, b""
        if st.stream is not None and st.stream.taints(key[0]):
            # An earlier streamed push under this key died AFTER committing
            # tiles into the aggregate; a replacement can't enter the round
            # coherently (its sealed tiles already count, per-tile).
            raise RPCError(
                "contribution partially streamed into committed tiles; "
                "peer sits this round out"
            )
        if key not in st.contribs and len(st.contribs) >= self.MAX_PARKED_CONTRIBS:
            raise RPCError("round contribution cap reached")
        buf = await self._decode_payload(payload)
        # Re-check after the await (other handlers ran while we decoded):
        # a same-key entry landed -> idempotent ack without overwriting
        # (first write wins, retries succeed); cap reached -> refuse.
        if key not in st.contribs:
            if len(st.contribs) >= self.MAX_PARKED_CONTRIBS:
                raise RPCError("round contribution cap reached")
            st.contribs[key] = (float(args["weight"]), buf)
            if (self.wire == "powersgd" and self.method == "mean") or buf is None:
                # Keep the compressed form too: for powersgd+mean the leader
                # serves the round result as the exact factored mean of
                # these (see _Round); for a pre-schema deferred decode
                # (buf None — powersgd or topk) the raw payload IS the
                # contribution until _decode_deferred resolves it at
                # aggregation time.
                st.payloads[key] = payload
            elif (
                st.stream is not None
                and buf is not None
                and buf.size == st.stream.n_elems
                and st.tokens is not None
                and st.tokens.get(key[0]) == key[1]
            ):
                # Round is armed but this payload rode inline (sub-chunk) or
                # the sink declined: fold the dense buffer into the stream
                # and drop the copy — the aggregator owns that mass now. A
                # feed refused (frozen round, tainted slot) keeps the dense
                # entry, which the commit then ignores as late.
                w = float(args["weight"])
                fed = await asyncio.to_thread(st.stream.add_dense, key[0], w, buf)
                if fed and st.contribs.get(key, (None, None))[1] is buf:
                    st.contribs[key] = (w, STREAMED)
        if st.expected:
            valid = {
                p for p, t in st.contribs
                if st.tokens is None or st.tokens.get(p) == t
            }
            if valid >= st.expected:
                st.full.set()
        return {"ok": True}, b""

    # Extra wait beyond the gather deadline for the leader's OFF-LOOP
    # aggregation + encode to land: with aggregation on a worker thread the
    # member-side timers now actually fire on schedule, so the old +3s
    # margin expired mid-aggregation at param scale.
    AGGREGATION_HEADROOM = 30.0

    async def _rpc_fetch(self, args: dict, payload: bytes):
        st = self._rounds.get(args["epoch"])
        if st is None:
            raise RPCError("unknown or finished round epoch")
        if self._fence_of(args) != st.gen:
            # Epoch fencing, BEFORE parking on result_ready: a revived
            # ex-leader (this node, if it was partitioned and healed) must
            # refuse to serve its stale generation-(st.gen) result to a
            # member that has moved on — and refuse fast, not after the
            # gather-deadline wait below.
            self._note_fence_rejected("sync.fetch", args, have_gen=st.gen)
            raise RPCError(
                f"fencing mismatch: round epoch is at generation {st.gen}, "
                f"fetch asks for {self._fence_of(args)} (leader deposed?)"
            )
        # Must outwait the leader's own gather deadline plus its off-loop
        # aggregation, or a member's fetch races the result and loses.
        await asyncio.wait_for(
            st.result_ready.wait(),
            timeout=self.gather_timeout + self.AGGREGATION_HEADROOM,
        )
        if st.result is None:
            raise RPCError("round skipped by leader (too few contributions)")
        # result_wire is encoded ONCE when the result lands (n members
        # fetching must not cost n identical codec passes).
        return (
            {"ok": True, "included": st.included, "excluded": st.excluded},
            st.result_wire,
        )

    # -- tail-optimal hedged recovery ---------------------------------------
    #
    # The leader's soft-deadline pipeline (ROADMAP item 2 / OptiReduce):
    # ahead of the round deadline, peers whose remaining tiles are
    # predicted late (phi-accrual suspicion, transport latency/bandwidth
    # EWMAs, stalled-stream age) get their missing tile ranges re-requested
    # over a second stream — first from the straggler's own retained bytes
    # (sync.refetch), then, when summand redundancy is on, from the ring
    # successor holding the straggler's XOR-coded tail. Duplicate arrivals
    # are idempotent by (peer, tile, fence) in the aggregator, so a hedge
    # and the original can never double-fold.

    REDUND_SHARE_TTL_S = 60.0
    MAX_REDUND_SHARES = 128
    # Hedged re-requests per straggler per round. Each attempt runs under
    # a SHORT per-attempt timeout (a fraction of the round budget, not
    # the whole remainder): tail latency is per-request, so a hedge that
    # itself straggles is cancelled and re-drawn instead of squatting on
    # the in-flight budget until the deadline.
    HEDGE_MAX_PER_PEER = 3
    HEDGE_ATTEMPT_FRAC = 0.35
    HEDGE_POLL_S = 0.2

    def _wire_geometry(self, n_elems: int) -> Tuple[int, int, int, int]:
        """(element size, chunk bytes, tile elems, n tiles) for this wire
        — delegated to agg_stream.wire_geometry, the tiling rule's one
        home, so refetch/sidecar tile addressing can never drift from the
        aggregator's bitmap."""
        return agg_wire_geometry(self.wire, self.transport.chunk_bytes, n_elems)

    def _redund_tiles(self, n_tiles: int) -> int:
        """Tail tiles covered by summand redundancy (0 = off)."""
        if not self.tail_redundancy_frac or self.wire not in ("f32", "bf16"):
            return 0
        return min(n_tiles, max(1, int(round(self.tail_redundancy_frac * n_tiles))))

    def _encode_range(self, buf: np.ndarray, e0: int, e1: int) -> bytes:
        """Element range -> wire bytes, bit-identical to the original
        push's encoding (f32/bf16 are elementwise, so a slice of the
        encoding IS the encoding of the slice; bf16 re-encode of the
        retained f32 form is exact — no second EF staging). One shared
        encoder (agg_stream.encode_wire_elems) guards that invariant."""
        return encode_wire_elems(self.wire, buf[e0:e1])

    async def _rpc_refetch(self, args: dict, payload: bytes):
        """Serve a tile range of a retained contribution back to a round
        leader: our OWN contribution (args peer == us), or — replica-holder
        mode — a ring neighbor's stashed redundancy tail. Authenticated by
        the round token the leader issued to THIS node; fenced by the
        generation the bytes were retained under."""
        epoch = args.get("epoch")
        target = args.get("peer")
        try:
            t0, t1 = int(args.get("t0", -1)), int(args.get("t1", -1))
        except (TypeError, ValueError):
            raise RPCError("malformed refetch range")
        rec = self._push_retained.get(epoch) if isinstance(epoch, str) else None
        if target == self.peer_id:
            if rec is None:
                raise RPCError("no retained contribution for this round epoch")
            if self._fence_of(args) != rec["gen"]:
                raise RPCError(
                    f"fencing mismatch: retained bytes are generation "
                    f"{rec['gen']}, refetch asks for {self._fence_of(args)}"
                )
            if rec["token"] and args.get("token") != rec["token"]:
                raise RPCError("invalid refetch token for this round")
            buf: np.ndarray = rec["buf"]
            esz, cb, tile_elems, n_tiles = self._wire_geometry(buf.size)
            if not 0 <= t0 < t1 <= n_tiles:
                raise RPCError(
                    f"refetch range [{t0}, {t1}) outside 0..{n_tiles}"
                )
            data = await asyncio.to_thread(
                self._encode_range, buf, t0 * tile_elems,
                min(t1 * tile_elems, buf.size),
            )
            return {"ok": True, "weight": rec["weight"]}, data
        # Replica-holder mode: serve the neighbor's stashed tail share.
        # Keyed on the SHARE, not this node's own round state — the whole
        # point of the replica hop is the degraded case, where this
        # node's own round may already have resolved (and dropped its
        # retention) while the leader's is still open. The share carries
        # its own fence; the token check applies when our retention is
        # still around to validate against (residual trust otherwise:
        # the predecessor explicitly shared these bytes for recovery,
        # and they are TTL'd).
        share = self._redund_shares.get((epoch, target)) if target else None
        if share is None:
            raise RPCError(f"no retained bytes for peer {target!r}")
        _, share_w, share_t0, share_bytes, share_fence = share
        if self._fence_of(args) != share_fence:
            raise RPCError(
                f"fencing mismatch: share is generation {share_fence}, "
                f"refetch asks for {self._fence_of(args)}"
            )
        if rec is not None and rec["token"] and args.get("token") != rec["token"]:
            raise RPCError("invalid refetch token for this round")
        cb = self.transport.chunk_bytes
        if t0 < share_t0 or t1 <= t0:
            raise RPCError(
                f"refetch range [{t0}, {t1}) outside share (covers {share_t0}..)"
            )
        off0 = (t0 - share_t0) * cb
        # Clamp the end to the share: the final tile is short, and the
        # leader's add_hedged enforces exact per-tile lengths anyway.
        off1 = min(len(share_bytes), (t1 - share_t0) * cb)
        if off0 >= len(share_bytes):
            raise RPCError("refetch range outside the retained share")
        return {"ok": True, "weight": share_w}, share_bytes[off0:off1]

    def _retain_push(self, group: Group, buf: np.ndarray, weight: float) -> None:
        """Register this member round's dense contribution for sync.refetch
        (and drain any parked ring-neighbor shares now that the round's
        leader/token are known)."""
        self._push_retained[group.epoch] = {
            "gen": group.gen,
            "token": group.token,
            "buf": buf,
            "weight": float(weight),
            "group": group,
        }
        if self.tail_redundancy_frac:
            for (epoch, pred) in list(self._redund_shares):
                if epoch == group.epoch:
                    self._spawn_task(self._send_sidecar(group.epoch, pred))

    def _drop_retained(self, epoch: str) -> None:
        self._push_retained.pop(epoch, None)

    def _sweep_redund_shares(self) -> None:
        now = time.monotonic()
        stale = [
            k for k, (t, *_rest) in self._redund_shares.items()
            if now - t > self.REDUND_SHARE_TTL_S
        ]
        for k in stale:
            self._redund_shares.pop(k, None)
        while len(self._redund_shares) >= self.MAX_REDUND_SHARES:
            self._redund_shares.pop(next(iter(self._redund_shares)), None)

    def _spawn_task(self, coro) -> Optional[asyncio.Task]:
        """Fire-and-forget helper task (redundancy sends): errors are
        logged, never raised — redundancy is strictly best-effort."""
        async def run():
            try:
                await coro
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — advisory path
                log.debug("tail-redundancy task failed: %s", errstr(e))
        try:
            return asyncio.get_running_loop().create_task(run())
        except RuntimeError:
            coro.close()
            return None

    async def _send_redund_share(
        self, group: Group, buf: np.ndarray, weight: float
    ) -> None:
        """Member side: ship our last-k% tiles' wire bytes to the ring
        successor, which XOR-codes them with its own tail into the
        leader-bound sidecar. Best-effort — a lost share just means no
        replica for this round."""
        esz, cb, tile_elems, n_tiles = self._wire_geometry(buf.size)
        r = self._redund_tiles(n_tiles)
        if not r:
            return
        succ = self._ring_successor(group, self.peer_id)
        if succ is None:
            return
        t0 = n_tiles - r
        tail = await asyncio.to_thread(
            self._encode_range, buf, t0 * tile_elems, buf.size
        )
        _, succ_addr = succ
        await self.transport.call(
            succ_addr, "sync.redund_share",
            {
                "epoch": group.epoch, "peer": self.peer_id,
                "weight": float(weight), "t0": t0, "fence": group.gen,
            },
            tail, timeout=5.0, record_latency=False,
        )

    async def _rpc_redund_share(self, args: dict, payload: bytes):
        """A ring predecessor's tail tiles (summand redundancy, member to
        member). Stashed — it becomes our XOR sidecar to the leader the
        moment our own round state for the epoch exists, and the
        replica-holder source for the leader's second hedge."""
        epoch, pred = args.get("epoch"), args.get("peer")
        if not isinstance(epoch, str) or not isinstance(pred, str) or not payload:
            raise RPCError("malformed redundancy share")
        try:
            w = float(args.get("weight"))
            t0 = int(args.get("t0"))
        except (TypeError, ValueError):
            raise RPCError("malformed redundancy share meta")
        self._sweep_redund_shares()
        self._redund_shares[(epoch, pred)] = (
            time.monotonic(), w, t0, bytes(payload), self._fence_of(args),
        )
        if epoch in self._push_retained and self.tail_redundancy_frac:
            self._spawn_task(self._send_sidecar(epoch, pred))
        return {"ok": True}, b""

    async def _send_sidecar(self, epoch: str, pred: str) -> None:
        """XOR our own tail tiles with the stashed predecessor share and
        ship the sidecar to the round leader (decoded there only if the
        original misses commit)."""
        rec = self._push_retained.get(epoch)
        share = self._redund_shares.get((epoch, pred))
        if rec is None or share is None:
            return
        _, pred_w, t0, pred_tail, _fence = share
        group: Group = rec["group"]
        buf: np.ndarray = rec["buf"]
        esz, cb, tile_elems, n_tiles = self._wire_geometry(buf.size)
        if t0 != n_tiles - self._redund_tiles(n_tiles):
            return  # config skew: the share's layout is not ours
        own_tail = await asyncio.to_thread(
            self._encode_range, buf, t0 * tile_elems, buf.size
        )
        if len(own_tail) != len(pred_tail):
            return  # schema mismatch — not our swarm's layout
        xored = (
            np.bitwise_xor(
                np.frombuffer(own_tail, np.uint8),
                np.frombuffer(pred_tail, np.uint8),
            ).tobytes()
        )
        leader_id, leader_addr = group.members[0]
        await self.transport.call(
            leader_addr, "sync.redund",
            {
                "epoch": epoch, "peer": self.peer_id, "pred": pred,
                "fence": group.gen, "token": group.token,
                "pred_weight": pred_w, "t0": t0,
            },
            xored, timeout=5.0, record_latency=False,
        )

    async def _rpc_redund(self, args: dict, payload: bytes):
        """Leader side: accept one XOR redundancy sidecar for an armed
        round (authenticated by the SUCCESSOR's issued token)."""
        epoch = args.get("epoch")
        st = self._rounds.get(epoch) if isinstance(epoch, str) else None
        if st is None or st.tokens is None or st.stream is None:
            raise RPCError("no armed round for this epoch")
        if self._fence_of(args) != st.gen:
            self._note_fence_rejected("sync.redund", args, have_gen=st.gen)
            raise RPCError("fencing mismatch on redundancy sidecar")
        succ, pred = args.get("peer"), args.get("pred")
        if not succ or st.tokens.get(succ) != args.get("token"):
            raise RPCError("invalid redundancy token")
        if not isinstance(pred, str) or pred not in st.stream.slot_index:
            raise RPCError("redundancy sidecar names an unknown peer")
        try:
            pred_w = float(args.get("pred_weight"))
            t0 = int(args.get("t0"))
        except (TypeError, ValueError):
            raise RPCError("malformed redundancy sidecar meta")
        if t0 != st.stream.n_tiles - st.stream.tail_keep_tiles:
            raise RPCError("redundancy sidecar layout mismatch")
        if len(st.redund) < 64:  # bounded per round
            st.redund[pred] = (succ, pred_w, bytes(payload), t0)
        return {"ok": True}, b""

    def _decode_redundancy(self, st: _Round) -> int:
        """Decode XOR sidecars for peers still missing tail tiles — called
        right before the freeze, so recovered tiles fold into the commit.
        pred_tile = sidecar XOR succ's own delivered tile (retained by the
        aggregator's tail-byte window). Idempotent through add_hedged."""
        stream = st.stream
        if stream is None or not st.redund:
            return 0
        folded = 0
        board = stream.scoreboard()
        # Snapshot: this runs on a worker thread while late sync.redund
        # handlers may still insert on the loop thread — iterating the
        # live dict would crash the round with RuntimeError.
        for pred, (succ, pred_w, xbytes, t0) in list(st.redund.items()):
            rec = board.get(pred)
            if rec is None or rec["sealed"] or rec["aborted"]:
                continue
            cb = stream.chunk_bytes
            total = stream.n_elems * stream.esz
            for tile in range(t0, stream.n_tiles):
                seg0 = (tile - t0) * cb
                seg_len = min(cb, total - tile * cb)
                if seg0 + seg_len > len(xbytes):
                    break  # malformed sidecar: stop, never mis-slice
                succ_bytes = stream.tail_bytes(succ, tile)
                if succ_bytes is None or len(succ_bytes) != seg_len:
                    continue  # successor's own copy of this tile missing
                data = np.bitwise_xor(
                    np.frombuffer(xbytes, np.uint8, count=seg_len, offset=seg0),
                    np.frombuffer(succ_bytes, np.uint8),
                ).tobytes()
                n = stream.add_hedged(
                    pred, pred_w, tile * cb, data, source="redund"
                )
                folded += n
        if folded:
            self.redund_decodes += folded
            if self.telemetry.enabled:
                self.telemetry.registry.counter(
                    "swarm.hedge.redund_tiles_total",
                    "tail tiles decoded from XOR redundancy sidecars",
                ).inc(folded)
        return folded

    async def _hedge_loop(self, st: _Round, group: Group) -> None:
        """The leader's soft-deadline watcher: sleep to the learned soft
        deadline, then rank stragglers off the aggregator's scoreboard and
        keep at most the learned budget of hedged range re-requests in
        flight until the round fills or the deadline lands. Cancelled with
        the gather; in-flight folds after the freeze are no-ops by the
        aggregator's frozen check."""
        stream = st.stream
        if stream is None:
            return
        asg = self._last_group
        level = asg.level if asg is not None else "flat"
        budget = self._deadline_wait(group)
        t_end = time.monotonic() + budget
        if self.resilience is not None:
            soft_frac, max_inflight = self.resilience.hedge_params(level)
        else:
            soft_frac, max_inflight = 0.6, 2
        await asyncio.sleep(budget * soft_frac)
        addr_by = {pid: addr for pid, addr in group.members}
        attempts: Dict[str, int] = {}
        # Keyed BY PEER: one hedge in flight per straggler — a poll must
        # not re-issue for a peer whose previous attempt is still
        # running, or the per-peer attempt budget burns in three polls
        # (and the duplicate replies would read to the AIMD as hedging a
        # healthy tail). A peer re-enters targeting only after its
        # attempt resolves (reply, error, or per-attempt timeout).
        inflight: Dict[str, asyncio.Task] = {}
        try:
            while not st.full.is_set():
                left = t_end - time.monotonic()
                if left <= 0.1:
                    break
                for p in [p for p, t in inflight.items() if t.done()]:
                    inflight.pop(p)
                if len(inflight) < max_inflight:
                    for peer, rng in self._hedge_targets(
                        stream.scoreboard(), left, addr_by, attempts
                    ):
                        if len(inflight) >= max_inflight:
                            break
                        if peer in inflight:
                            continue
                        attempts[peer] = attempts.get(peer, 0) + 1
                        att_timeout = min(
                            max(left, 0.2),
                            max(0.5, self.HEDGE_ATTEMPT_FRAC * budget),
                        )
                        inflight[peer] = asyncio.create_task(
                            self._hedge_fetch(
                                st, group, peer, addr_by[peer],
                                rng[0], rng[1], att_timeout,
                            )
                        )
                await asyncio.sleep(min(self.HEDGE_POLL_S, max(left, 0.05)))
        finally:
            for t in inflight.values():
                if not t.done():
                    t.cancel()

    def _hedge_targets(
        self,
        board: Dict[str, dict],
        left: float,
        addr_by: Dict[str, Any],
        attempts: Dict[str, int],
    ) -> List[Tuple[str, Tuple[int, int]]]:
        """Rank hedge candidates: unsealed peers with missing tiles whose
        ORIGINAL stream is predicted to miss the deadline — phi-accrual
        suspicion, a stalled stream (no arrival for several RTTs), or a
        transfer estimate (missing bytes / measured bandwidth + latency)
        exceeding the time left. Past the soft deadline a silent peer is
        hedged outright (its p95 completion history already failed it).
        Worst missing-volume first."""
        out: List[Tuple[int, str, Tuple[int, int]]] = []
        for peer, rec in board.items():
            if (
                peer == self.peer_id
                or rec["sealed"]
                or rec["aborted"]
                or not rec["missing"]
                or attempts.get(peer, 0) >= self.HEDGE_MAX_PER_PEER
                or peer not in addr_by
            ):
                continue
            addr = addr_by[peer]
            missing_tiles = sum(t1 - t0 for t0, t1 in rec["missing"])
            lat = self.transport.peer_latency(addr) or 0.05
            bw = self.transport.peer_bw_down(addr)
            suspect = (
                self.failure_detector is not None
                and self.failure_detector.suspect(peer)
            )
            age = rec["last_arrival_age_s"]
            stalled = (
                rec["started"] and age is not None and age > max(0.5, 4.0 * lat)
            )
            eta = (
                lat + missing_tiles * self.transport.chunk_bytes / bw
                if bw else None
            )
            if suspect or stalled or not rec["started"] or (
                eta is not None and eta > left
            ):
                # One contiguous range per request: the original stream is
                # in-order, so the missing set is (almost always) a suffix;
                # residual holes get the next pass.
                rng = rec["missing"][0]
                out.append((missing_tiles, peer, (int(rng[0]), int(rng[1]))))
        out.sort(key=lambda x: -x[0])
        return [(p, r) for _, p, r in out]

    async def _hedge_fetch(
        self,
        st: _Round,
        group: Group,
        peer: str,
        addr,
        t0: int,
        t1: int,
        timeout: float,
    ) -> None:
        """One hedged range re-request: pull tiles [t0, t1) of ``peer``'s
        retained contribution over a second stream and fold them into the
        round's aggregator as they verify. Falls back to the peer's ring
        successor (replica holder of its XOR-shared tail) when the
        straggler itself is unreachable and redundancy is on."""
        stream = st.stream
        if stream is None:
            return
        tele = self.telemetry
        st.hedges_issued += 1
        self.hedges_issued += 1
        if tele.enabled:
            tele.registry.counter(
                "swarm.hedge.issued_total", "hedged tile re-requests issued"
            ).inc()
        tele.event(
            "hedge_issued", epoch=group.epoch, peer=peer,
            t0=int(t0), t1=int(t1),
        )
        span = tele.tracer.start(
            "hedge", trace=group.epoch, role="leader", peer=peer,
            tiles=int(t1 - t0), gen=st.gen,
        )
        token = (st.tokens or {}).get(peer, "")
        args = {
            "epoch": group.epoch, "fence": st.gen, "peer": peer,
            "t0": int(t0), "t1": int(t1), "token": token,
        }
        base = int(t0) * stream.chunk_bytes
        folded = 0
        source = "refetch"
        try:
            try:
                folded = await self._refetch_into(
                    stream, peer, addr, args, base, timeout
                )
            except (RPCError, OSError, asyncio.TimeoutError, TimeoutError) as e:
                # Replica-holder fallback: the straggler itself is gone or
                # saturated; its ring successor retains the XOR-shared
                # tail. Only the tail sub-range is recoverable there.
                succ = self._ring_successor(group, peer)
                r_tiles = stream.tail_keep_tiles
                tail_t0 = stream.n_tiles - r_tiles
                if succ is None or not r_tiles or t1 <= tail_t0:
                    raise
                source = "replica"
                succ_id, succ_addr = succ
                rargs = dict(
                    args,
                    t0=int(max(t0, tail_t0)),
                    token=(st.tokens or {}).get(succ_id, ""),
                )
                log.debug(
                    "hedge: refetch from %s failed (%s); trying replica "
                    "holder %s", peer, errstr(e), succ_id,
                )
                folded = await self._refetch_into(
                    stream, peer, succ_addr, rargs,
                    rargs["t0"] * stream.chunk_bytes,
                    max(timeout / 2, 0.2),
                )
            if span is not None:
                span.end(ok=True, folded=folded, source=source)
        except asyncio.CancelledError:
            # Deadline landed (or the round filled) with this hedge still
            # in flight: end the span so the trace shows the attempt.
            if span is not None:
                span.end(ok=False, cancelled=True, folded=folded)
            raise
        except (RPCError, OSError, asyncio.TimeoutError, TimeoutError) as e:
            self.hedges_failed += 1
            if tele.enabled:
                tele.registry.counter(
                    "swarm.hedge.failed_total", "hedged re-requests that failed"
                ).inc()
            if span is not None:
                span.end(ok=False, error=errstr(e), source=source)

    async def _refetch_into(
        self,
        stream: StreamingAggregator,
        peer: str,
        addr,
        args: dict,
        base: int,
        timeout: float,
    ) -> int:
        """Issue one sync.refetch and fold the reply into ``stream`` under
        ``peer``'s slot. Streams chunk-by-chunk when the peer's weight is
        already known and the transport is unauthenticated (the request-
        sink integrity rule applied client-side: hedged folds are
        irreversible, so under auth the reply buffers whole and folds only
        after the payload MAC verified)."""
        folded = 0
        w_known = stream.weight_of(peer)
        if w_known > 0 and getattr(self.transport, "_secret", None) is None:
            def hsink(off: int, total: int, data: bytes) -> None:
                nonlocal folded
                folded += stream.add_hedged(peer, w_known, base + off, data)

            await self.transport.call(
                addr, "sync.refetch", args, timeout=timeout,
                chunk_sink=hsink, record_latency=False,
            )
            return folded
        ret, payload = await self.transport.call(
            addr, "sync.refetch", args, timeout=timeout, record_latency=False,
        )
        try:
            w = float(ret.get("weight") or 1.0)
        except (TypeError, ValueError):
            w = 1.0

        def fold() -> int:
            n = 0
            cb = stream.chunk_bytes
            for off in range(0, len(payload), cb):
                n += stream.add_hedged(
                    peer, w, base + off, bytes(payload[off : off + cb])
                )
            return n

        return await asyncio.to_thread(fold)

    def _ring_successor(self, group: Group, peer: str) -> Optional[Tuple[str, Any]]:
        """The ring successor of ``peer`` among the round's NON-LEADER
        members (the redundancy ring excludes the leader — it already
        holds its own contribution), or None below 3 members."""
        ring = [m for m in group.members if m[0] != group.leader_id]
        ids = [pid for pid, _ in ring]
        if peer not in ids or len(ring) < 2:
            return None
        return ring[(ids.index(peer) + 1) % len(ring)]

    async def average(self, tree: Any, round_no: int, weight: float = 1.0) -> Optional[Any]:
        self._sweep_rounds(self._rounds)
        # Fenced controller decisions apply HERE — before this round's
        # rendezvous — so a mid-round regime shift can never mix two
        # configurations into one round (the epoch-fence contract).
        self._apply_controller()
        await self._maybe_backoff()
        tele = self.telemetry
        # Round-trace bookkeeping: the JOIN phase (rendezvous + formation)
        # runs before the trace id — the matchmaking epoch — exists, so its
        # wall/duration are captured here and the span recorded
        # retroactively once the group (and therefore the epoch) is known.
        t_round_wall, t_round_pc = tele.clock(), time.perf_counter()
        # Group-scoped rendezvous when a rotating schedule is attached:
        # many groups form this round, each running THIS protocol under
        # its own epoch; we only ever see our own — and the schedule's
        # determinism lets formation skip the DHT entirely (_form_group).
        round_key = await self._rendezvous()
        group = await self._form_group(round_key)
        join_dur = time.perf_counter() - t_round_pc
        if group is None:
            # No group formed (too few peers / no begin): a matchmaking
            # skip, not a round — the policy only learns from rounds that
            # actually ran, so a solo volunteer never ratchets its deadline
            # or backs itself off.
            self.rounds_skipped += 1
            self._last_outcomes = None
            self._note_group_round(None)
            return None
        if group.my_index != 0 and self._recently_deposed(group.leader_id):
            # Leadership strike (tentpole part 3): this peer crashed out of
            # the lead within the TTL — don't hand it our contribution (or
            # gate our round on its fetch) again yet. Our own _pick_leader
            # already prefers someone else; this covers the race where the
            # flaky peer's begin still won.
            log.info(
                "sync round: refusing round led by recently-deposed %s",
                group.leader_id,
            )
            self.rounds_skipped += 1
            self._last_outcomes = None
            self._note_group_round(None)
            return None
        # The trace id IS the round's existing key: the matchmaking epoch,
        # which already hashes the group-scoped rendezvous key (rotation,
        # group index, hierarchy level). Recovery generations ride as span
        # attributes so a recovered round stays ONE trace.
        trace = group.epoch
        asg = self._last_group
        level = asg.level if asg is not None else "flat"
        group_id = group.group_id or (asg.group_id if asg is not None else "")
        role = "leader" if group.my_index == 0 else "member"
        ok = False
        # Reset BEFORE any awaitable can raise: the round span's finally
        # reads this, and a round dying in arm/encode must not inherit the
        # previous round's degraded verdict.
        self._round_degraded = False
        with tele.tracer.trace_scope(trace), log_context(
            peer=self.peer_id, round_key=round_key, trace=trace,
            round_level=level, group=group_id or None,
            zone=self.zone or None,
        ):
            tele.tracer.record(
                "join", trace, t_round_wall, join_dur,
                role=role, key=round_key, size=group.size,
            )
            try:
                if group.my_index == 0 and self._specs is not None:
                    # Arm the streaming round BEFORE packing our own
                    # contribution: members push the instant formation
                    # completes, and the pack at param scale is exactly the
                    # window their first chunks land in.
                    await self._prepare_lead_round(group)
                # One compression per round, leader or member: the leader's
                # own contribution enters the aggregate exactly as a peer
                # would see it.
                with tele.span("encode", role=role):
                    buf, wire_bytes, sent = await self._pack_and_compress(tree)
                t0 = time.monotonic()
                # The leader's own contribution always enters the aggregate;
                # a member's may be dropped in a degraded round (late push),
                # in which case its shipped top-k mass never landed and
                # committing the residual would lose both. _member_round
                # flips this from the leader-reported included set.
                self._contribution_included = True
                try:
                    if group.my_index == 0:
                        result = await self._lead_round(
                            group, await asyncio.to_thread(sent), weight, wire_bytes
                        )
                    else:
                        # Tail-optimal recovery, member side: register the
                        # dense form behind sync.refetch for the round's
                        # lifetime (the leader's hedges re-pull ranges of
                        # it, bit-identical to the push), and — redundancy
                        # on — ship the tail tiles to the ring successor.
                        retained = self.wire in ("f32", "bf16") and buf is not None
                        if retained:
                            self._retain_push(group, buf, weight)
                            if self.tail_redundancy_frac and len(group.members) >= 3:
                                self._spawn_task(
                                    self._send_redund_share(group, buf, weight)
                                )
                        try:
                            result = await self._member_round(
                                group, weight, wire_bytes, sent
                            )
                        finally:
                            if retained:
                                self._drop_retained(group.epoch)
                except (RPCError, OSError, ValueError, asyncio.TimeoutError) as e:
                    log.info(
                        "sync round %d failed (%s); continuing local",
                        round_no, errstr(e),
                    )
                    tele.event("round_failed", key=round_key, error=errstr(e))
                    self.rounds_skipped += 1
                    self._observe_round_failure()
                    self._commit_ef(False)
                    self._flush_round_outcome(time.monotonic() - t0, ok=False)
                    self._note_group_round(False, size=group.size)
                    return None
                self._commit_ef(result is not None and self._contribution_included)
                if result is None:
                    self._observe_round_failure()
                elif self._round_degraded:
                    self.rounds_degraded += 1
                    tele.event("round_degraded", key=round_key)
                else:
                    self._observe_round_time(time.monotonic() - t0)
                self._flush_round_outcome(time.monotonic() - t0, ok=result is not None)
                self._note_group_round(
                    result is not None,
                    degraded=self._round_degraded,
                    led=group.my_index == 0,
                    size=group.size,
                )
                ok = result is not None
                return result
            finally:
                tele.tracer.record(
                    "round", trace, t_round_wall,
                    time.perf_counter() - t_round_pc,
                    role=role, key=round_key, level=level, ok=ok,
                    degraded=self._round_degraded, gen=group.gen,
                    **({"group": group_id} if group_id else {}),
                )

    async def _prepare_lead_round(self, group: Group) -> _Round:
        """The leader-side round prologue, idempotent per epoch: fix the
        token table, pick the estimator, ARM the streaming aggregator, and
        fold any pre-arming parked buffers into it.

        Split from _lead_round so ``average()`` can run it BEFORE packing
        the leader's own contribution: members push the instant formation
        completes, and a param-scale pack is exactly the window their
        headers used to land in — every push that arrived pre-arming had
        to buffer dense (observed on a localhost resnet18 swarm: all
        contributions went dense). Pre-armed, the factory catches them
        from the first chunk. Needs ``self._specs`` (any round after the
        first); round one arms from _lead_round, after the pack."""
        st = self._rounds.get(group.epoch)
        if st is None:
            st = self._rounds[group.epoch] = _Round([])
        if st.armed:
            return st
        arm_span = self.telemetry.tracer.start(
            "arm", trace=group.epoch, role="leader", gen=group.gen
        )
        try:
            await self._phase("pre_arm")
            st.armed = True
            st.gen = group.gen
            member_ids = [pid for pid, _ in group.members]
            st.expected = set(member_ids)
            tokens = group.member_tokens or {}
            st.tokens = tokens
            # Keep only parked entries under the exact (peer, token) pairs
            # we issued at begin — everything else is noise or forgery.
            st.contribs = {
                (p, t): c for (p, t), c in st.contribs.items() if tokens.get(p) == t
            }
            st.payloads = {
                k: pl for k, pl in st.payloads.items() if k in st.contribs
            }
            # The estimator is fixed at ARMING (not commit): streamed tiles
            # aggregate while contributions are still arriving, so the
            # method must be known before the first chunk lands. Safe to
            # fix early because the METHOD choice is count-insensitive —
            # _effective_method picks it from
            # resilience.recommend_method(self.method), which never sees
            # the peer count — so members dropping between arming and
            # commit cannot change it. Only the kwargs depend on row
            # count, and those ARE recomputed per arrived count via kw_fn
            # below. What did move is the escalation-state read: a
            # resilience state change mid-round is seen one round later
            # than the commit-time call saw it.
            method, _ = self._effective_method(len(member_ids))
            kw_cache: Dict[int, dict] = {}

            def kw_fn(n: int, _m=method) -> dict:
                # Memoized per row count: a per-tile recompute would
                # re-log the infeasible-trim clamp warning once per tile.
                if n not in kw_cache:
                    kw_cache[n] = self._robust_kw(n, method=_m)
                return kw_cache[n]

            st.method, st.kw_fn = method, kw_fn
            n_elems = sum(s.size for s in self._specs)
            esz = 4 if self.wire == "f32" else 2
            if self.wire in ("f32", "bf16") and self.transport.chunk_bytes % esz == 0:
                # Arm the streaming pipeline: from here on, chunked pushes
                # fold tile-by-tile as they arrive (transport request
                # sink), inline pushes fold at decode, and the deadline
                # commit reduces to closing whatever is still open.
                _, _, _, n_tiles = self._wire_geometry(n_elems)
                st.stream = StreamingAggregator(
                    n_elems, member_ids, method, self.wire,
                    self.transport.chunk_bytes, kw_fn=kw_fn,
                    codec=self.mesh_codec,
                    telemetry=self.telemetry,
                    # Summand redundancy: retain members' tail-tile wire
                    # bytes as XOR-decode keys for ring sidecars.
                    tail_keep_tiles=self._redund_tiles(n_tiles),
                )
                # Fold every pre-arming parked buffer; fed entries drop
                # their dense copy — the aggregator owns that mass now.
                for k, (w_k, b_k) in [
                    (k, c) for k, c in st.contribs.items()
                    if c[1] is not None and c[1] is not STREAMED
                    and c[1].size == n_elems
                ]:
                    fed = await asyncio.to_thread(st.stream.add_dense, k[0], w_k, b_k)
                    if fed:
                        st.contribs[k] = (w_k, STREAMED)
        except BaseException:
            if arm_span is not None:
                arm_span.end(ok=False)
            raise
        if arm_span is not None:
            arm_span.end(streaming=st.stream is not None)
        return st

    async def _lead_round(
        self,
        group: Group,
        buf: np.ndarray,
        weight: float,
        wire_bytes: bytes = b"",
    ):
        st = await self._prepare_lead_round(group)
        tokens = st.tokens or {}
        method, kw_fn = st.method, st.kw_fn
        st.contribs[(self.peer_id, group.token)] = (weight, buf)
        if self.wire == "powersgd" and wire_bytes:
            st.payloads[(self.peer_id, group.token)] = wire_bytes
        if st.stream is not None:
            # Our own contribution enters through the same pipeline the
            # members' do (mean: one O(D) axpy; window: a borrowed-reference
            # resident).
            fed = await asyncio.to_thread(
                st.stream.add_dense, self.peer_id, weight, buf
            )
            if fed:
                st.contribs[(self.peer_id, group.token)] = (weight, STREAMED)
        if {p for p, _ in st.contribs} >= st.expected:
            st.full.set()
        if self._phase_armed("mid_stream"):
            # Chaos instrumentation: "mid_stream" means member data has
            # started arriving — wait (bounded) for the first remote
            # contribution bytes before firing, so the kill really lands
            # mid-gather and not in the pre-arm window.
            await self._await_remote_contribution(
                st, timeout=min(5.0, self._deadline_wait(group))
            )
            await self._phase("mid_stream")
        # FOLD phase: the gather wait plus the streaming pipeline's commit
        # tail (close open windows, await in-flight tile jobs, re-normalize).
        fold_sp = self.telemetry.tracer.start(
            "fold", trace=group.epoch, role="leader", gen=group.gen
        )
        commit_sp = None
        try:
            # Tail-optimal recovery: the soft-deadline hedger watches the
            # aggregator's tile scoreboard beside the gather wait and
            # re-requests predicted-late ranges. The ROUND deadline is
            # untouched — hedging spends idle wait, not wall time.
            hedger: Optional[asyncio.Task] = None
            if (
                self.hedge
                and st.stream is not None
                and self.wire in ("f32", "bf16")
                and len(group.members) > 1
            ):
                hedger = asyncio.create_task(self._hedge_loop(st, group))
            try:
                # The group DEADLINE bounds the gather: begin fan-out time
                # already spent the budget, so a slow formation shrinks the
                # wait instead of extending the round past its commit time.
                await asyncio.wait_for(
                    st.full.wait(), timeout=self._deadline_wait(group)
                )
            except asyncio.TimeoutError:
                self._round_degraded = True  # deadline commit: not an observation
            finally:
                if hedger is not None:
                    hedger.cancel()
                    try:
                        await hedger
                    except (asyncio.CancelledError, Exception):  # noqa: BLE001
                        pass
            if st.stream is not None and st.redund:
                # Summand redundancy decodes BEFORE the freeze: a tail the
                # original missed folds into the commit iff its XOR
                # sidecar + the successor's own delivered tail are both in.
                await asyncio.to_thread(self._decode_redundancy, st)
            await self._phase("post_partial_commit")
            # Resolve pre-schema-parked powersgd payloads now that our own
            # pack fixed the specs (exact-size-capped decode).
            await self._decode_deferred(st)
            if st.stream is not None:
                # Freeze the pipeline BEFORE deciding membership: a feed
                # that loses this race is late by definition (its dense
                # entry survives but is not adopted), and stream-complete
                # pushes whose handler task hasn't run yet are adopted here.
                st.stream.freeze()
                for k, w_k in list(st.stream_done.items()):
                    if tokens.get(k[0]) == k[1]:
                        st.contribs.setdefault(k, (w_k, STREAMED))
                # The aggregator's own view beats the handler bookkeeping:
                # a contribution that finished folding pre-freeze IS in the
                # aggregate even when its handler (dense-feed STREAMED mark)
                # or sink close() hasn't caught up — report it included, or
                # the resilience policy penalizes an honest peer whose mass
                # the round actually used.
                for p in st.stream.included_peers():
                    t = tokens.get(p)
                    if t is not None:
                        st.contribs[(p, t)] = (st.stream.weight_of(p), STREAMED)
            # Drop contributions whose buffer doesn't match ours (model
            # mismatch that slipped past the early-accept schema check) or
            # whose token isn't the secret WE issued to that member at begin
            # — a member cannot submit under another member's identity. On a
            # streaming round only FOLDED (streamed) entries count: a dense
            # buffer that never made it into the aggregator is late.
            good = {
                p: c
                for (p, t), c in st.contribs.items()
                # c[1] None: a pre-schema deferred entry whose payload a
                # straggler handler parked DURING _decode_deferred's awaits
                # — unresolved, so it sits this round out.
                if tokens.get(p) == t
                and (
                    c[1] is STREAMED
                    if st.stream is not None
                    else c[1] is not None and c[1].size == buf.size
                )
            }
            # Per-peer outcomes for the resilience policy: an expected
            # member missing from ``good`` either never arrived (absent) or
            # arrived malformed under a valid token (rejected).
            rejected = sorted(
                p
                for (p, t), c in st.contribs.items()
                if tokens.get(p) == t
                and p != self.peer_id
                and c[1] is not STREAMED
                and (c[1] is None or c[1].size != buf.size)
            )
            st.excluded = sorted(
                p for p in st.expected if p not in good and p != self.peer_id
            )
            self._last_outcomes = {
                "on_time": [p for p in sorted(good) if p != self.peer_id],
                "absent": [p for p in st.excluded if p not in rejected],
                "rejected": rejected,
            }
            self._last_outcomes_epoch = group.epoch
            if len(good) < self.min_group:
                if fold_sp is not None:
                    fold_sp.end(ok=False, arrived=len(good))
                self.telemetry.event(
                    "round_failed", epoch=group.epoch,
                    reason=f"leader skipped: {len(good)}/{self.min_group} contributions",
                )
                self.rounds_skipped += 1
                # Fail members' pending fetches fast, then free the buffers.
                st.result_ready.set()  # with st.result None -> fetch raises
                # Eager release: the parked contributions are param-sized
                # and nothing after this point reads them — holding them
                # until the 5 s sweep fires kept O(N·D) pinned per skipped
                # round. The _Round shell stays for fetch-error serving.
                self._note_agg_round(st.stream)
                self._release_round(st)
                asyncio.get_running_loop().call_later(
                    5.0, self._rounds.pop, group.epoch, None
                )
                return None
            if st.excluded:
                log.info(
                    "sync round committed at deadline without %s "
                    "(%d/%d contributions)",
                    st.excluded, len(good), len(st.expected),
                )
            peers = sorted(good)
            st.included = peers
            method_kw = kw_fn(len(peers))
            health_on = self.health is not None and self.health.enabled
            dense_q: Dict[str, float] = {}

            def _aggregate() -> np.ndarray:
                if method == "mean":
                    # Streaming weighted accumulation (native axpy when
                    # built): no [n_peers, D] stack copy for the common path.
                    # A deadline-committed subset re-normalizes here by
                    # construction: total_w is the weight that ARRIVED.
                    total_w = float(sum(good[p][0] for p in peers))
                    acc = np.zeros(buf.size, np.float32)
                    for p in peers:
                        w_p, buf_p = good[p]
                        native.weighted_sum_inplace(acc, buf_p, w_p / total_w)
                    return acc
                stack = np.stack([good[p][1] for p in peers])
                out = self.mesh_codec.aggregate(stack, method, **method_kw)
                if health_on and len(peers) >= 3:
                    # Quality attribution for the non-streaming wires
                    # (q8/topk/powersgd/sign take this branch): the byz
                    # flagging contract must not depend on the wire codec.
                    for p, d2 in zip(peers, health_mod.row_d2(stack, out)):
                        dense_q[p] = float(d2)
                return out

            if st.stream is not None:
                # The pipeline already decoded and (for mean/window methods)
                # aggregated most tiles while chunks were arriving: the
                # commit closes the open windows over the arrived subsets,
                # awaits in-flight tile jobs, and re-normalizes — bounded by
                # the tail, not by N full decode+aggregate passes.
                st.result = await st.stream.finalize(peers)
                self._note_agg_round(st.stream)
            else:
                # Seconds of array math at param scale — off the loop
                # (members' fetches park on result_ready; heartbeats must
                # keep flowing).
                st.result = await asyncio.to_thread(_aggregate)
            # Training-health: the balanced mass classification for this
            # commit (streaming rounds classify per slot; dense rounds
            # from the arrived-weight map) plus the per-peer quality
            # distances the tile folds (or the dense branch above)
            # accumulated. Gated on the health probe alone — under
            # --no-health-probe NO health tally runs and the fold span
            # carries no mass column, honoring the "disabled end-to-end"
            # contract even while the rest of telemetry stays on.
            mass = quality = None
            if health_on:
                # Shard-scoped rounds tag every slot with the group's shard
                # domain so health.mass_by_shard can roll the buckets up
                # per shard — a shard-holder death then reads as one
                # shard's committed fraction dipping, not a fleet-wide dip.
                asg_m = self._last_group
                shard_of = (
                    {p: asg_m.shard for p in st.expected}
                    if asg_m is not None and asg_m.shard is not None
                    else None
                )
                mass = (
                    st.stream.mass_report(shard_of)
                    if st.stream is not None
                    else health_mod.mass_from_outcomes(
                        st.expected, {p: float(good[p][0]) for p in good}
                    )
                )
                quality = (
                    st.stream.quality_d2() if st.stream is not None
                    else dense_q or None
                )
            if st.stream is not None:
                # Tail-optimal bookkeeping: cumulative recovered-slot
                # counter, per-peer contribution-latency samples (the
                # policy's tail quantiles), and the AIMD hedge-budget
                # feedback for this round's hierarchy level.
                hs = st.stream.hedge_stats()
                self.slots_recovered += hs["slots_recovered"]
                if hs["slots_recovered"] and self.telemetry.enabled:
                    self.telemetry.registry.counter(
                        "swarm.hedge.slots_recovered_total",
                        "straggler contributions completed by hedged recovery",
                    ).inc(hs["slots_recovered"])
                if self.resilience is not None:
                    for p, dt in st.stream.seal_latencies().items():
                        if p != self.peer_id:
                            self.resilience.record_contribution_latency(p, dt)
                    if self.hedge:
                        if mass is not None:
                            lost_w = float(mass["excluded_weight"]) + float(
                                mass["aborted_weight"]
                            )
                            if lost_w == 0.0 and (
                                mass["excluded_slots"] or mass["aborted_slots"]
                            ):
                                # Silent peers declare no weight; the lost
                                # SLOTS are still the AIMD's open-up signal.
                                lost_w = float(
                                    mass["excluded_slots"] + mass["aborted_slots"]
                                )
                        else:
                            lost_w = float(len(st.excluded))
                        asg_now = self._last_group
                        self.resilience.record_hedge_outcome(
                            asg_now.level if asg_now is not None else "flat",
                            issued=st.hedges_issued,
                            tiles_recovered=hs["tiles_recovered"],
                            duplicate_tiles=hs["hedge_duplicates"],
                            slots_recovered=hs["slots_recovered"],
                            lost_weight=lost_w,
                        )
            if fold_sp is not None:
                fold_sp.end(
                    ok=True, arrived=len(peers),
                    expected=len(st.expected),
                    degraded=self._round_degraded,
                    **(
                        {"mass_frac": mass["mass_committed_frac"]}
                        if mass is not None else {}
                    ),
                )
            commit_sp = self.telemetry.tracer.start(
                "commit", trace=group.epoch, role="leader", gen=group.gen
            )
            # Encode the wire form ONCE before releasing the fetch waiters.
            if self.wire == "powersgd" and method == "mean":
                # Serve the EXACT factored mean (concatenated weighted
                # factor pairs): same value members would get densely, at a
                # fraction of the result-fetch bytes. Falls back to the
                # dense container if any contribution's payload is missing
                # (e.g. a parked entry from before this leader's round).
                good_keys = {(p, t) for (p, t) in st.contribs if p in good}

                def _merge_or_dense() -> bytes:
                    from distributedvolunteercomputing_tpu.swarm import powersgd

                    try:
                        pairs = [
                            (st.contribs[k][0], st.payloads[k]) for k in good_keys
                        ]
                        # Cap each payload's dense-reconstruction work at
                        # the schema size: merge may densify low-rank
                        # entries, and a crafted container must not buy a
                        # bigger allocation than a legitimate dense one.
                        return powersgd.merge(pairs, max_floats=st.result.size)
                    except (KeyError, ValueError):
                        # Missing payload (parked before this round) or a
                        # crafted container whose entry split disagrees with
                        # the others — the round must not die over the
                        # result ENCODING; serve the dense container.
                        return self._to_wire(st.result)

                st.result_wire = await asyncio.to_thread(_merge_or_dense)
            elif self.wire in ("f32", "bf16"):
                # Lazy wire form: each fetch response encodes chunk-by-chunk
                # on a worker thread while earlier chunks are already on the
                # socket (encode/send overlap), so the commit point never
                # pays — or holds — a full-size encoded copy of the result.
                # At most max_group cheap elementwise passes replace the one
                # eager encode, each overlapped with its own send.
                st.result_wire = self._wire_stream(st.result)
            else:
                st.result_wire = await self._encode_wire(st.result)
            await self._phase("pre_fetch")
            st.result_ready.set()
            if commit_sp is not None:
                commit_sp.end(wire=self.wire)
            self.rounds_ok += 1
            # Keep state around long enough for members to fetch.
            asyncio.get_running_loop().call_later(
                self.gather_timeout * 2, self._rounds.pop, group.epoch, None
            )
            if self.health is not None and self.health.enabled:
                # Post-commit health bookkeeping off the loop (members are
                # already fetching — result_ready is set): quality votes,
                # mass gauges + flight event, post-round sketch. Its own
                # span so the leader's critical-path coverage contract
                # (trace_report) still accounts for the round's wall.
                with self.telemetry.span("health", trace=group.epoch, role="leader"):
                    await asyncio.to_thread(
                        self._health_note_commit, st.result, group.epoch,
                        mass, quality,
                    )
            return self._unpack(st.result)
        except Exception:
            # Idempotent ends: whichever phase the failure interrupted is
            # the one still open — record it ok=False instead of dropping
            # exactly the span a post-mortem needs.
            if fold_sp is not None:
                fold_sp.end(ok=False)
            if commit_sp is not None:
                commit_sp.end(ok=False)
            failed = self._rounds.pop(group.epoch, None)
            if failed is not None:
                self._release_round(failed)
            raise

    def _release_round(self, st: _Round) -> None:
        """Free a round's held contribution buffers NOW (skipped/failed
        rounds): parked payloads and dense contributions are param-sized,
        and the streaming aggregator's tiles go back to the pool."""
        st.contribs.clear()
        st.payloads.clear()
        st.stream_done.clear()
        if st.stream is not None:
            st.stream.release()

    async def _member_round(
        self,
        group: Group,
        weight: float,
        wire_bytes,
        dense_fn: Optional[Callable[[], np.ndarray]] = None,
    ):
        """Push to the leader, fetch the result — and if the leader dies
        under us, recover instead of skipping: the wire form is RETAINED
        (``wire_bytes`` stays referenced until a commit is acknowledged, and
        a StreamPayload's factory re-iterates) so the recovery round
        re-pushes exactly the bytes this round compressed, with no second
        error-feedback staging."""
        leader_id, leader_addr = group.members[0]
        tele = self.telemetry
        try:
            # WIRE phase: the push leg (encode overlapped with send on
            # StreamPayload wires); FETCH parks on the leader's commit
            # point by design, so its span brackets the leader's fold.
            with tele.span("wire", role="member", leader=leader_id, gen=group.gen):
                await self._push_contribution(leader_addr, group, weight, wire_bytes)
            with tele.span("fetch", role="member", leader=leader_id, gen=group.gen):
                return await self._fetch_round_result(leader_addr, leader_id, group)
        except _LeaderDown as e:
            log.warning(
                "sync round: leader %s down (%s); attempting failover recovery",
                leader_id, e,
            )
            with tele.span("recover", role="member", deposed=leader_id, gen=group.gen):
                return await self._recover_round(
                    group, weight, wire_bytes, dense_fn, reason=str(e)
                )

    async def _push_contribution(
        self, leader_addr, group: Group, weight: float, wire_bytes
    ) -> None:
        args = {
            "epoch": group.epoch,
            "peer": self.peer_id,
            "weight": weight,
            "schema": self._schema,
            "token": group.token,
            "fence": group.gen,
        }
        # The push must land BEFORE the group deadline or the leader commits
        # without it — spending more than the remaining budget on it would
        # only produce a late arrival the policy then counts against us.
        # record_latency=False on the payload legs: bulk-transfer (and, for
        # the fetch, deliberately-parked) durations must not poison the
        # control-plane latency EWMA the failure detector suspects on.
        try:
            await self.transport.call(
                leader_addr, "sync.contribute", args, wire_bytes,
                timeout=self._deadline_wait(group, floor=1.0),
                record_latency=False,
            )
        except (asyncio.TimeoutError, TimeoutError):
            # A timed-out push is a SLOW gather, not a dead leader — and on
            # Python >= 3.11 asyncio.TimeoutError IS builtins.TimeoutError,
            # an OSError subclass: without this clause the handler below
            # would depose a merely-slow leader (same trap the transport's
            # retry path documents).
            raise
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            # Hard connection-level failure (refused dial, reset socket):
            # the leader process is GONE — distinct from a timeout (which
            # may just be a slow gather) and grounds for immediate
            # deposition rather than outwaiting the round budget.
            raise _LeaderDown(
                f"contribution push failed at connection level: {errstr(e)}"
            ) from e

    async def _fetch_round_result(self, leader_addr, leader_id: str, group: Group):
        # Decode-on-arrival (f32/bf16): verified result chunks land straight
        # in the final f32 buffer while later chunks are still in flight.
        sink, sink_state = self._result_sink()
        call = asyncio.ensure_future(
            self.transport.call(
                leader_addr, "sync.fetch",
                {"epoch": group.epoch, "fence": group.gen},
                # Outwait the leader's own commit point (the deadline) plus
                # its off-loop aggregation headroom plus transfer margin.
                timeout=self._deadline_wait(group, floor=1.0)
                + self.AGGREGATION_HEADROOM + 6.0,
                chunk_sink=sink,
                record_latency=False,
            )
        )
        try:
            if self.failure_detector is not None:
                # Mid-fetch leader suspicion: the fetch deliberately parks
                # on the leader until its commit point, which is exactly
                # the window a silently-dead leader wastes. Poll the
                # phi-accrual verdict while parked and depose instead of
                # outwaiting the full budget.
                while True:
                    done, _ = await asyncio.wait({call}, timeout=0.5)
                    if done:
                        break
                    if self.failure_detector.suspect(leader_id):
                        call.cancel()
                        try:
                            await call
                        except (asyncio.CancelledError, Exception):  # noqa: BLE001
                            pass
                        raise _LeaderDown(
                            "failure detector suspects the leader mid-fetch"
                        )
            ret, payload = await call
        except (asyncio.TimeoutError, TimeoutError):
            # Timeout != death (see _push_contribution): deposing here
            # would punish every slow commit; the deadline machinery
            # already bounds what a slow leader can cost.
            raise
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            raise _LeaderDown(
                f"result fetch failed at connection level: {errstr(e)}"
            ) from e
        except RPCError as e:
            if "unknown or finished round epoch" in str(e) and (
                time.monotonic() - group.formed_mono < self.gather_timeout
            ):
                # EARLY unknown-epoch — well inside the round's lifetime,
                # long before the leader's post-commit retention window
                # (2x gather_timeout) could have swept it — means the
                # leader restarted and lost its round state mid-round:
                # death for this round's purposes. A LATE unknown-epoch is
                # this member stalling past the retention window of a
                # round the leader already served everyone else; deposing
                # a healthy leader for our own slowness would hand out
                # suspicion holds swarm-wide, so that stays a plain
                # failed fetch.
                raise _LeaderDown(f"leader lost round state ({e})") from e
            raise
        finally:
            if not call.done():
                call.cancel()
        # Older leaders don't report the included set; treat absence as
        # included (the pre-existing behavior) rather than stalling EF.
        included = ret.get("included")
        if included is not None:
            self._contribution_included = self.peer_id in included
        if self.peer_id in (ret.get("excluded") or ()):
            # Say WHY our update didn't land from this side too — one line a
            # volunteer operator can read without the leader's logs. (On EF
            # wires the un-landed mass re-stages via _commit_ef above.)
            log.info(
                "sync round committed at its deadline without our "
                "contribution (push arrived late or was dropped)"
            )
        self.rounds_ok += 1
        def _finish(b: Optional[np.ndarray]):
            # Member-side health: sketch the committed aggregate we are
            # about to adopt (the post-round parameters), so the member's
            # heartbeat report carries the same-round sketch the mixing-
            # error rollup compares across peers.
            self._health_note_commit(b, group.epoch)
            return self._unpack(b)

        if (
            sink_state is not None
            and sink_state["out"] is not None
            and sink_state["filled"] == sink_state["expect"]
        ):
            # The streamed sink already decoded the result: unpack only.
            buf = sink_state["out"]
            return await asyncio.to_thread(_finish, buf)
        # Inline (small) response, or a wire the sink doesn't cover.
        return await asyncio.to_thread(
            lambda: _finish(self._buf_from_payload(payload))
        )

    # -- leader failover recovery ------------------------------------------

    def _note_deposed(self, leader_id: str, leader_addr, reason: str) -> None:
        """Record the deposition evidence that is sound from a SINGLE
        observer's vantage: the gauge, the detector's connection-failure
        hold (cleared by the peer's next observed heartbeat), and retiring
        the pooled connection so nothing retries against the corpse. The
        leadership STRIKE — refusing the peer the lead, and its rounds,
        for DEPOSED_LEADER_TTL_S — is recorded separately by
        _recover_round once recovery is actually viable: one member's own
        flaky outbound link (a dropped call in a 2-peer swarm) must not
        blacklist a healthy leader for the whole strike window."""
        log.warning("sync round: deposing leader %s (%s)", leader_id, reason)
        self.telemetry.event("leader_deposed", leader=leader_id, reason=reason)
        self.leaders_deposed += 1
        if self.failure_detector is not None:
            self.failure_detector.report_failure(leader_id)
        self.transport.drop_peer(leader_addr)

    def _strike_deposed(self, leader_id: str) -> None:
        self._deposed_leaders[leader_id] = time.monotonic()
        if self.resilience is not None:
            self.resilience.note_leader_deposed(leader_id)

    def _successor(self, survivors: List[Tuple[str, Any]]) -> Optional[str]:
        """Deterministic successor: the first survivor in epoch (sorted-id)
        order the local policy does not currently suspect — never skipping
        ourselves, and falling back to the plain first survivor when every
        candidate is suspected. Views can diverge across members (suspicion
        is local); the recovery begin is what re-synchronizes them — a
        member follows whichever valid begin arrives, and a second
        self-promoted successor's round simply underfills and skips."""
        for pid, _ in survivors:
            if pid == self.peer_id:
                return pid
            if self.resilience is not None and self.resilience.should_preexclude(pid):
                continue
            if self.failure_detector is not None and self.failure_detector.suspect(pid):
                continue
            return pid
        return survivors[0][0] if survivors else None

    async def _recover_round(
        self,
        group: Group,
        weight: float,
        wire_bytes,
        dense_fn: Optional[Callable[[], np.ndarray]],
        reason: str,
    ):
        """Re-lead (or follow) a recovery round over the SAME epoch at
        generation+1 after deposing the leader. One generation bump per
        round from this node's vantage: if the successor dies too, the
        round fails — cascading multi-death inside a single round is rarer
        than the stall a recovery chain would risk."""
        t_rec = time.monotonic()
        deposed_id, deposed_addr = group.members[0]
        self._note_deposed(deposed_id, deposed_addr, reason)
        if group.gen >= self.MAX_RECOVERY_GEN:
            self.recoveries_failed += 1
            raise RPCError(
                f"recovery generation cap ({self.MAX_RECOVERY_GEN}) reached "
                f"for epoch {group.epoch}"
            )
        survivors = [(p, a) for p, a in group.members if p != deposed_id]
        if len(survivors) < self.min_group:
            self.recoveries_failed += 1
            raise RPCError(
                f"leader down and only {len(survivors)} survivors "
                f"(min_group {self.min_group}): round unrecoverable"
            )
        gen = group.gen + 1
        # Recovery is viable: the group genuinely moves on without this
        # leader — NOW the leadership strike is warranted.
        self._strike_deposed(deposed_id)
        successor = self._successor(survivors)
        try:
            if successor == self.peer_id:
                result = await self._lead_recovery(
                    group, survivors, gen, weight, wire_bytes, dense_fn
                )
            else:
                result = await self._follow_recovery(
                    group, survivors, gen, weight, wire_bytes, successor
                )
        except _LeaderDown as e:
            self.recoveries_failed += 1
            raise RPCError(f"recovery round failed: {e}") from e
        except (RPCError, OSError, ValueError, asyncio.TimeoutError):
            self.recoveries_failed += 1
            raise
        if result is None:
            self.recoveries_failed += 1
            self.telemetry.event(
                "recovery_failed", epoch=group.epoch, gen=gen,
                deposed=deposed_id, reason="recovery round skipped",
            )
            return None
        dt = time.monotonic() - t_rec
        self.rounds_recovered += 1
        self.telemetry.event(
            "round_recovered", epoch=group.epoch, gen=gen,
            deposed=deposed_id, successor=successor, dt_s=round(dt, 3),
        )
        self._recovery_lat_last = dt
        self._recovery_lat_ewma = (
            dt if self._recovery_lat_ewma is None
            else self._recovery_lat_ewma + 0.25 * (dt - self._recovery_lat_ewma)
        )
        log.info(
            "sync round recovered at generation %d in %.2fs (deposed %s, "
            "successor %s)", gen, dt, deposed_id, successor,
        )
        return result

    async def _lead_recovery(
        self,
        group: Group,
        survivors: List[Tuple[str, Any]],
        gen: int,
        weight: float,
        wire_bytes,
        dense_fn: Optional[Callable[[], np.ndarray]],
    ):
        """This node is the successor: mint fresh per-member tokens (the
        deposed leader's table died with it), fan out the recovery begin,
        and re-lead the gather over the retained contributions through the
        ordinary _lead_round machinery — fenced at ``gen``."""
        if dense_fn is None:
            raise RPCError("recovery round: no dense contribution available")
        me = self.peer_id
        my_addr = next(a for p, a in survivors if p == me)
        others = [(p, a) for p, a in survivors if p != me]
        tokens = {pid: uuid.uuid4().hex for pid, _ in survivors}
        budget = self._round_budget()
        deadline = self.clock() + budget
        rgroup = Group(
            epoch=group.epoch,
            members=[(me, my_addr)] + others,
            my_index=0,
            token=tokens[me],
            member_tokens=tokens,
            deadline=deadline,
            budget=budget,
            gen=gen,
            group_id=group.group_id,
        )
        self._record_epoch_gen(group.epoch, gen)
        # Abort/re-arm: whatever round state the deposed generation left
        # under this epoch (parked pushes keyed by dead tokens, half-filled
        # streaming tiles) is fenced off and released — the recovery round
        # re-collects from scratch, so no half-folded mass from the old
        # generation can leak into the recovered result.
        old = self._rounds.pop(group.epoch, None)
        if old is not None:
            if old.stream is not None:
                old.stream.fence()
            self._release_round(old)
        begin = {
            "epoch": group.epoch,
            "gen": gen,
            "members": [[p, list(a)] for p, a in rgroup.members],
            "deadline": deadline,
            "budget": budget,
            "schema": self._schema,
        }
        reached = 0
        for pid, addr in others:
            try:
                await self.transport.call(
                    addr, "sync.recover", {**begin, "token": tokens[pid]},
                    timeout=5.0, connect_timeout=3.0,
                )
                reached += 1
            except Exception as e:  # noqa: BLE001 — per-member fan-out containment
                log.warning("recovery begin to %s failed: %s", pid, errstr(e))
        if reached + 1 < self.min_group:
            raise RPCError(
                f"recovery round: only {reached + 1} reachable survivors "
                f"(min_group {self.min_group})"
            )
        buf = await asyncio.to_thread(dense_fn)
        return await self._lead_round(rgroup, buf, weight, wire_bytes)

    async def _follow_recovery(
        self,
        group: Group,
        survivors: List[Tuple[str, Any]],
        gen: int,
        weight: float,
        wire_bytes,
        successor: Optional[str],
    ):
        """This node expects another survivor to take over: wait (bounded)
        for its recovery begin, validate it against the ORIGINAL membership
        (the begin may only shrink the group, never smuggle outsiders in or
        resurrect the deposed leader), then re-push the retained wire form
        and fetch under the new generation."""
        begin = await self._await_recover_begin(group.epoch)
        if begin is None:
            raise RPCError(
                f"no recovery begin arrived for epoch {group.epoch} "
                f"(expected successor {successor})"
            )
        try:
            rgen = int(begin.get("gen", 0))
            members = [
                (str(pid), (str(a[0]), int(a[1])))
                for pid, a in begin.get("members", [])
            ]
        except (TypeError, ValueError, IndexError):
            raise RPCError("malformed recovery begin") from None
        ids = [p for p, _ in members]
        orig = {p for p, _ in group.members}
        if (
            rgen <= group.gen
            or rgen > self.MAX_RECOVERY_GEN
            or not members
            or not set(ids) <= orig
            or group.leader_id in ids
            or self.peer_id not in ids
            or ids[0] == self.peer_id
        ):
            raise RPCError("invalid recovery begin (membership/generation)")
        self._record_epoch_gen(group.epoch, rgen)
        deadline = begin.get("deadline")
        budget = begin.get("budget")
        rgroup = Group(
            epoch=group.epoch,
            members=members,
            my_index=ids.index(self.peer_id),
            token=str(begin.get("token", "")),
            deadline=float(deadline) if isinstance(deadline, (int, float)) else None,
            budget=float(budget) if isinstance(budget, (int, float)) else None,
            gen=rgen,
            group_id=group.group_id,
        )
        new_leader_id, new_leader_addr = members[0]
        await self._push_contribution(new_leader_addr, rgroup, weight, wire_bytes)
        return await self._fetch_round_result(new_leader_addr, new_leader_id, rgroup)

    async def _await_recover_begin(self, epoch: str) -> Optional[dict]:
        parked = self._recover_parked.pop(epoch, None)
        if (
            parked is not None
            and time.monotonic() - parked[0] <= self.RECOVER_PARKED_TTL_S
        ):
            return parked[1]
        fut = self._recover_futs.get(epoch)
        if fut is None or fut.done():
            fut = self._recover_futs[epoch] = (
                asyncio.get_running_loop().create_future()
            )
        try:
            return await asyncio.wait_for(
                asyncio.shield(fut), timeout=self.RECOVERY_BEGIN_WAIT_S
            )
        except asyncio.TimeoutError:
            return None
        finally:
            if self._recover_futs.get(epoch) is fut:
                self._recover_futs.pop(epoch, None)

    def _sweep_epoch_gens(self) -> None:
        cutoff = time.monotonic() - (self.gather_timeout * 3 + 60.0)
        for k in [k for k, (ts, _) in self._epoch_gen.items() if ts < cutoff]:
            del self._epoch_gen[k]

    def _record_epoch_gen(self, epoch: str, gen: int) -> None:
        """Record an ACCEPTED recovery generation for an epoch (validated
        follow, or our own lead) — the state the sync.recover handler's
        only-advance fence checks against."""
        self._sweep_epoch_gens()
        if epoch in self._epoch_gen or len(self._epoch_gen) < self.MAX_EPOCH_GENS:
            self._epoch_gen[epoch] = (time.monotonic(), gen)

    async def _rpc_recover(self, args: dict, payload: bytes):
        """A successor's recovery begin. Membership proof is knowledge of
        the epoch — a 16-hex digest delivered only inside the original
        round's private begin messages (plus the transport HMAC when the
        swarm runs authenticated); the follower re-validates the proposed
        member list against its own original group before acting on it.
        Generations only ever advance per epoch, so a replayed or
        second-guessing begin for an already-recovered round is refused."""
        epoch = args.get("epoch")
        gen = args.get("gen")
        if (
            not isinstance(epoch, str)
            or not epoch
            or not isinstance(gen, int)
            or isinstance(gen, bool)
            or gen < 1
            or gen > self.MAX_RECOVERY_GEN
        ):
            raise RPCError("malformed recovery begin")
        self._sweep_epoch_gens()
        known = self._epoch_gen.get(epoch, (0.0, 0))[1]
        if gen <= known:
            raise RPCError(
                f"stale recovery begin (generation {gen} <= accepted {known})"
            )
        # NOT recorded here: _epoch_gen advances only when a begin is
        # ACCEPTED — validated against the original membership in
        # _follow_recovery (or minted by our own _lead_recovery). Recording
        # an unvalidated begin would let one shape-valid forgery at the
        # generation cap permanently consume the epoch's budget and block
        # the genuine successor.
        fut = self._recover_futs.get(epoch)
        if fut is not None and not fut.done():
            fut.set_result(args)
        else:
            now = time.monotonic()
            for k in [
                k for k, (ts, _) in self._recover_parked.items()
                if now - ts > self.RECOVER_PARKED_TTL_S
            ]:
                del self._recover_parked[k]
            if (
                epoch not in self._recover_parked
                and len(self._recover_parked) >= 64
            ):
                raise RPCError("parked recovery begin cap reached")
            self._recover_parked[epoch] = (now, args)
        return {"ok": True}, b""

    async def _await_remote_contribution(self, st: _Round, timeout: float) -> None:
        """Block (bounded) until at least one REMOTE contribution has
        started arriving — chunks folding into the stream, a parked dense
        buffer, or a completed sink. Chaos instrumentation only (the
        'mid_stream' phase point must fire mid-gather, not pre-arm)."""
        deadline = time.monotonic() + max(timeout, 0.0)
        while time.monotonic() < deadline:
            if st.stream_done or any(p != self.peer_id for p, _ in st.contribs):
                return
            if st.stream is not None and any(
                n for p, n in st.stream.progress().items() if p != self.peer_id
            ):
                return
            await asyncio.sleep(0.05)


class GossipAverager(AveragerBase):
    """Asynchronous pairwise gossip (config 3): no rounds, no barriers.

    Caller mixes with one random live peer per averaging point; the
    counterparty banks the caller's contribution in an inbox and folds it in
    at ITS next averaging point. Every volunteer's params drift toward the
    swarm mean without any global synchronization (Moshpit/PushSum genre).
    """

    mode = "gossip"

    # Inbox entries are un-keyed (unlike sync's (peer, token) contributions),
    # so without a dedup id a REPLAYED exchange frame — even an HMAC-valid
    # one captured within the transport auth window — would inject the same
    # stale vector repeatedly. Every exchange carries a fresh xid; seen xids
    # are remembered (bounded by count and age) and duplicates rejected.
    _XID_TTL_S = 600.0
    _XID_CAP = 4096

    def __init__(self, *a, seed: int = 0, **kw):
        super().__init__(*a, **kw)
        self._inbox: List[Tuple[float, np.ndarray]] = []
        self._current: Optional[Tuple[float, np.ndarray]] = None
        self._rng = random.Random(seed ^ hash(self.peer_id))
        self._seen_xids: Dict[str, float] = {}
        self.transport.register("gossip.exchange", self._rpc_exchange)

    def publish(self, tree: Any, weight: float = 1.0) -> None:
        """Make this peer's params available to exchanges BEFORE its own
        first averaging point. Without this a peer busy compiling serves
        every incoming exchange 'no params published yet' — under startup
        skew two peers can each burn ALL their rounds against the other's
        unpublished window and finish having never mixed (observed as an
        e2e flake before this existed). The volunteer publishes its post-
        state-sync snapshot right after joining (params mode only)."""
        buf = self._pack(tree)
        self._current = (weight, self._wire_roundtrip(buf))

    def _xid_seen(self, xid: str) -> bool:
        return xid in self._seen_xids

    def _xid_record(self, xid: str) -> None:
        now = time.monotonic()
        if len(self._seen_xids) >= self._XID_CAP:
            cutoff = now - self._XID_TTL_S
            self._seen_xids = {k: t for k, t in self._seen_xids.items() if t >= cutoff}
            while len(self._seen_xids) >= self._XID_CAP:  # still full: drop oldest
                self._seen_xids.pop(min(self._seen_xids, key=self._seen_xids.get))
        self._seen_xids[xid] = now

    async def _rpc_exchange(self, args: dict, payload: bytes):
        if not self._check_schema(args):
            raise RPCError("schema mismatch")
        xid = args.get("xid")
        if not isinstance(xid, str) or not xid:
            raise RPCError("missing exchange id")
        if self._current is None:
            raise RPCError("peer has no params published yet")
        my_w, my_buf = self._current
        if self._xid_seen(xid):
            # A seen xid is either the transport's transparent retry of an
            # exchange whose response was lost (the caller's vector IS
            # banked — failing here would skew the mix it already entered),
            # or a replayed frame. Both get the idempotent answer: serve
            # our half WITHOUT banking, so the same vector can never enter
            # the inbox twice no matter how often the frame is repeated.
            return {"weight": my_w}, await self._encode_wire_stream(my_buf)
        inbuf = await self._decode_payload(payload)
        if inbuf.size != my_buf.size:
            # Invalid exchanges never record their xid: a corrected retry
            # under the same xid gets a fresh verdict, not a silent serve.
            raise RPCError(f"buffer size {inbuf.size} != local {my_buf.size}")
        if not self._xid_seen(xid):  # re-check: a twin ran during the decode
            self._xid_record(xid)
            if len(self._inbox) < self.MAX_PARKED_CONTRIBS:
                self._inbox.append((float(args["weight"]), inbuf))
            else:
                # Inbox full (peer long between averaging points — e.g.
                # still compiling after publish()): serve OUR half of the
                # exchange but drop theirs, bounding banked param-sized
                # buffers. Push-pull degrades to pull-only instead of
                # growing without bound.
                log.debug("gossip inbox full (%d); dropping incoming contribution",
                          len(self._inbox))
        # Lazy stream on the dense wires: the reply's chunks are encoded
        # while the transport writes earlier ones, instead of a full encode
        # before the first response byte moves.
        return {"weight": my_w}, await self._encode_wire_stream(my_buf)

    def _mix(self, w1, b1, w2, b2) -> Tuple[float, np.ndarray]:
        total = w1 + w2
        return total, (b1 * (w1 / total) + b2 * (w2 / total))

    async def average(self, tree: Any, round_no: int, weight: float = 1.0) -> Optional[Any]:
        inbox, self._inbox = self._inbox, []

        def _fold():
            buf = self._pack(tree)
            w = weight
            # 1. fold in whatever neighbours pushed since last time
            for iw, ibuf in inbox:
                if ibuf.size != buf.size:  # banked before our schema changed
                    continue
                w, buf = self._mix(w, buf, iw, ibuf)
            return w, buf

        # Payload-scale flatten + up to inbox-cap mixes: off the loop.
        w, buf = await asyncio.to_thread(_fold)
        self._current = (w, buf)
        # 2. push-pull with one random live peer — same-namespace peers only.
        # Gossip has no rendezvous key, so the namespace filter happens here:
        # a namespaced averager requires the record's avg_ns (membership
        # extra_info, volunteer.py) to match EXACTLY — "model/average_what",
        # so a params-mode peer never mixes with a grads-mode one. A record's
        # model field alone is NOT enough (it can't distinguish params from
        # grads trees, which flatten to identical schemas).
        # Gossip has no leader to pre-exclude stragglers for us, so partner
        # SELECTION is where the suspicion signal lands: suspected peers
        # (phi over threshold / policy miss streak) are filtered out of the
        # candidate set — they keep receiving our published params via their
        # own pulls, we just never block a round on them.
        peers = await self.membership.alive_peers(
            include_self=False,
            exclude_suspected=self.failure_detector is not None,
        )
        targets = [
            (pid, tuple(rec["addr"]))
            for pid, rec in peers.items()
            if "addr" in rec
            and (not self.namespace or rec.get("avg_ns") == self.namespace)
            and not (
                self.resilience is not None
                and self.resilience.should_preexclude(pid)
            )
        ]
        mixed = bool(inbox)
        await self._maybe_backoff()
        if targets:
            pid, addr = self._rng.choice(targets)
            try:
                t0 = time.monotonic()
                ret, payload = await self.transport.call(
                    addr,
                    "gossip.exchange",
                    {"peer": self.peer_id, "weight": w, "schema": self._schema,
                     "xid": uuid.uuid4().hex},
                    await self._encode_wire_stream(buf),
                    # The round budget (policy-learned when attached) bounds
                    # the exchange: a stalled partner costs seconds, and the
                    # inbox fold above already banked everyone else's pushes.
                    timeout=min(self._round_budget(), self.effective_gather_timeout),
                    record_latency=False,  # bulk payload both ways
                )
                self._observe_round_time(time.monotonic() - t0)
                rbuf = await self._decode_payload(payload)
                if rbuf.size != buf.size:
                    raise RPCError(f"peer buffer size {rbuf.size} != local {buf.size}")
                w, buf = await asyncio.to_thread(
                    self._mix, w, buf, float(ret["weight"]), rbuf
                )
                self._current = (w, buf)
                mixed = True
                self._last_outcomes = {"on_time": [pid]}
                self._flush_round_outcome(time.monotonic() - t0, ok=True)
            except (RPCError, OSError, ValueError, asyncio.TimeoutError) as e:
                log.info("gossip with %s failed (%s)", pid, errstr(e))
                self._observe_round_failure()
                self._last_outcomes = {"absent": [pid]}
                self._flush_round_outcome(time.monotonic() - t0, ok=False)
        if not mixed:
            self.rounds_skipped += 1
            return None
        self.rounds_ok += 1
        return await asyncio.to_thread(self._unpack, buf)


class ButterflyAverager(AveragerBase):
    """Butterfly (hypercube) allreduce (config 4).

    log2(n) pairwise stages; at stage s, peer i exchanges its running
    weighted average with peer i XOR 2^s. Bandwidth is balanced (every peer
    moves ~log n buffers — no leader hotspot), and heterogeneous/absent
    partners cost ONE skipped stage, not the round: with a partial butterfly
    each peer still holds the average of a 2^k subset, which contracts
    variance every round (Moshpit SGD's argument, PAPERS.md:9).
    """

    mode = "butterfly"

    def __init__(self, *a, stage_timeout: float = 8.0, **kw):
        super().__init__(*a, **kw)
        self.stage_timeout = stage_timeout
        # (epoch, stage) -> {"ready": Event, "buf":, "w":, "done": Event, "in": (w, buf)}
        self._stages: Dict[Tuple[str, int], dict] = {}
        self.transport.register("bfly.exchange", self._rpc_exchange)

    def _stage_state(self, epoch: str, stage: int, *, remote: bool = False) -> dict:
        key = (epoch, stage)
        if key not in self._stages:
            if remote:
                # Same asymmetry the byz path had in round 1: every (epoch,
                # stage) a remote names allocates state AND pins the handler
                # task for stage_timeout — and the local sweep only runs
                # inside average(), which a peer that stops averaging never
                # calls. Sweep on the RPC path and cap remotely-created
                # entries (buf is None until the LOCAL peer reaches the
                # stage, so "parked" is exactly that predicate), mirroring
                # MAX_PARKED_ROUNDS on the gather paths.
                self._sweep_stages()
                parked = sum(1 for s in self._stages.values() if s["buf"] is None)
                if parked >= self.MAX_PARKED_ROUNDS:
                    raise RPCError("parked stage cap reached")
            self._stages[key] = {
                "ready": asyncio.Event(),
                "done": asyncio.Event(),
                "buf": None,
                "w": None,
                "in": None,
                "t0": time.monotonic(),
            }
        return self._stages[key]

    def _sweep_stages(self) -> None:
        # A partner's exchange for a round we never joined leaves a stage
        # entry behind after its handler times out — evict by age.
        cutoff = time.monotonic() - (self.stage_timeout * 4 + 30.0)
        for key in [k for k, st in self._stages.items() if st["t0"] < cutoff]:
            del self._stages[key]

    async def _rpc_exchange(self, args: dict, payload: bytes):
        if not self._check_schema(args):
            raise RPCError("schema mismatch")
        st = self._stage_state(args["epoch"], int(args["stage"]), remote=True)
        # Wait until the local peer reaches this stage (it may be behind).
        await asyncio.wait_for(st["ready"].wait(), timeout=self.stage_timeout)
        inbuf = await self._decode_payload(payload)
        if inbuf.size != st["buf"].size:
            raise RPCError(f"buffer size {inbuf.size} != local {st['buf'].size}")
        st["in"] = (float(args["weight"]), inbuf)
        st["done"].set()
        return {"weight": st["w"]}, await self._encode_wire_stream(st["buf"])

    @staticmethod
    def _mix(w1: float, b1: np.ndarray, w2: float, b2: np.ndarray) -> Tuple[float, np.ndarray]:
        total = w1 + w2
        # Same expression on both sides of the pair -> bitwise-identical
        # results (float + and * are commutative), so the pair stays in sync.
        # With wire=bf16 this holds because average() round-trips the LOCAL
        # buffer through the codec before mixing — each side mixes the same
        # (quantized-mine, quantized-theirs) pair.
        return total, (b1 * (w1 / total) + b2 * (w2 / total))

    def _stage_wait(self, group: Group, stage: int, n_stages: int) -> float:
        """Per-stage wait under the round deadline: the remaining budget is
        split evenly over the stages still to run (a straggler at stage 0
        must not eat the whole round's budget and starve stages 1..k), and
        ``stage_timeout`` stays the per-stage ceiling."""
        remaining = self._deadline_remaining(group)  # skew-guarded
        if remaining is None:
            return self.stage_timeout
        stages_left = max(n_stages - stage, 1)
        return float(min(self.stage_timeout, max(remaining / stages_left, 0.5)))

    async def average(self, tree: Any, round_no: int, weight: float = 1.0) -> Optional[Any]:
        self._sweep_stages()
        await self._maybe_backoff()
        round_key = await self._rendezvous()
        group = await self._form_group(round_key)
        if group is None:
            self.rounds_skipped += 1
            self._last_outcomes = None
            self._note_group_round(None)
            return None
        # Round proper starts AFTER formation (same vantage as sync/byz):
        # the policy's deadline estimate must learn exchange time, not
        # matchmaking settle/join time.
        t0 = time.monotonic()
        buf = self._pack(tree)
        w = float(weight)
        n = group.size
        n_stages = max((n - 1).bit_length(), 1)
        mixed_any = False
        missed_partners: List[str] = []
        on_time_partners: List[str] = []
        for s in range(n_stages):
            partner_idx = group.my_index ^ (1 << s)
            if partner_idx >= n:
                continue
            partner_id, partner_addr = group.members[partner_idx]
            buf = await asyncio.to_thread(self._wire_roundtrip, buf)
            st = self._stage_state(group.epoch, s)
            st["buf"], st["w"] = buf, w
            st["ready"].set()
            stage_wait = self._stage_wait(group, s, n_stages)
            try:
                if group.my_index < partner_idx:
                    ret, payload = await self.transport.call(
                        partner_addr,
                        "bfly.exchange",
                        {
                            "epoch": group.epoch,
                            "stage": s,
                            "peer": self.peer_id,
                            "weight": w,
                            "schema": self._schema,
                        },
                        await self._encode_wire_stream(buf),
                        timeout=stage_wait,
                        # Bulk payload, and the partner may legitimately
                        # park until it reaches this stage.
                        record_latency=False,
                    )
                    pw, pbuf = float(ret["weight"]), await self._decode_payload(payload)
                else:
                    await asyncio.wait_for(st["done"].wait(), timeout=stage_wait)
                    pw, pbuf = st["in"]
                if pbuf.size != buf.size:
                    raise RPCError(f"partner buffer size {pbuf.size} != local {buf.size}")
                w, buf = await asyncio.to_thread(self._mix, w, buf, pw, pbuf)
                mixed_any = True
                on_time_partners.append(partner_id)
            except (RPCError, OSError, ValueError, asyncio.TimeoutError) as e:
                log.info(
                    "butterfly round %d stage %d with %s failed (%s); skipping stage",
                    round_no, s, partner_id, errstr(e),
                )
                missed_partners.append(partner_id)
            finally:
                self._stages.pop((group.epoch, s), None)
        self._round_degraded = bool(missed_partners) and mixed_any
        self._last_outcomes = {
            "on_time": on_time_partners,
            "absent": missed_partners,
        }
        if not mixed_any:
            self.rounds_skipped += 1
            self._flush_round_outcome(time.monotonic() - t0, ok=False)
            self._note_group_round(False, size=group.size)
            return None
        self.rounds_ok += 1
        if self._round_degraded:
            self.rounds_degraded += 1
        self._flush_round_outcome(time.monotonic() - t0, ok=True)
        self._note_group_round(
            True, degraded=self._round_degraded, size=group.size
        )
        return await asyncio.to_thread(self._unpack, buf)


class ByzantineAverager(AveragerBase):
    """Full-mesh robust aggregation (config 5): no trusted leader.

    Every member pushes its contribution to every other member; each member
    independently applies the robust estimator (trimmed mean by default;
    median/krum/geometric_median via ``method=``) to whatever arrived by the
    deadline. A Byzantine peer can send garbage — the estimator bounds its
    influence — and, unlike leader-gather, no single peer computes the
    aggregate for others. Identity limits without a PKI: a contribution can
    never claim the receiver's own id and can never overwrite an
    already-received entry (first write wins), so impersonating an honest
    peer requires beating its first push in a race, per round, per receiver.
    """

    mode = "byzantine"

    def __init__(self, *a, **kw):
        kw.setdefault("method", "trimmed_mean")
        super().__init__(*a, **kw)
        self._rounds: Dict[str, _Round] = {}
        self.transport.register("byz.contribute", self._rpc_contribute)

    async def _rpc_contribute(self, args: dict, payload: bytes):
        if not self._check_schema(args):
            raise RPCError("schema mismatch")
        peer = args["peer"]
        # A remote push may never claim OUR identity, and may never REPLACE a
        # contribution that already arrived (first write wins): with no PKI on
        # the WAN an attacker can still race an honest peer's first push, but
        # it cannot overwrite the honest value afterwards — and the robust
        # estimator bounds whatever single rows it does land.
        if peer == self.peer_id:
            raise RPCError("contribution claims receiver's own identity")
        # Contribution can arrive before we enter the round: park it
        # (swept + capped against fabricated-epoch flooding).
        st = self._get_or_park_round(self._rounds, args["epoch"])
        if st.expected and peer not in st.expected:
            # Round membership is known: reject outsiders outright instead of
            # parking them (they'd be dropped at aggregation anyway).
            raise RPCError("peer is not a member of this round")
        if peer in st.contribs:
            raise RPCError("duplicate contribution for peer (first write wins)")
        if not st.expected and len(st.contribs) >= self.MAX_PARKED_CONTRIBS:
            raise RPCError("round contribution cap reached")
        buf = await self._decode_payload(payload)
        # Re-check after the await: first write wins, so a contribution that
        # landed while we decoded keeps its slot and THIS one is the forgery
        # (or a pointless retry) — refuse rather than overwrite.
        if peer in st.contribs:
            raise RPCError("duplicate contribution for peer (first write wins)")
        if not st.expected and len(st.contribs) >= self.MAX_PARKED_CONTRIBS:
            raise RPCError("round contribution cap reached")
        st.contribs[peer] = (float(args["weight"]), buf)
        if buf is None:
            # Pre-schema powersgd push: park the raw payload for
            # _decode_deferred (decode amplification is the attack here;
            # raw bytes cost the sender its own bandwidth).
            st.payloads[peer] = payload
        if st.expected and set(st.contribs) >= st.expected:
            st.full.set()
        return {"ok": True}, b""

    async def average(self, tree: Any, round_no: int, weight: float = 1.0) -> Optional[Any]:
        self._sweep_rounds(self._rounds)
        # Same fencing contract as the sync path: staged controller
        # decisions (regime -> hedge floor, wire, cadence when a schedule
        # is attached) promote HERE, before this round's rendezvous.
        self._apply_controller()
        await self._maybe_backoff()
        round_key = await self._rendezvous()
        group = await self._form_group(round_key)
        if group is None:
            self.rounds_skipped += 1
            self._last_outcomes = None
            self._note_group_round(None)
            return None
        buf, wire_bytes, sent = await self._pack_and_compress(tree)
        st = self._rounds.get(group.epoch)
        if st is None:
            st = self._rounds[group.epoch] = _Round([])
        st.expected = set(pid for pid, _ in group.members)
        st.contribs[self.peer_id] = (weight, await asyncio.to_thread(sent))
        if set(st.contribs) >= st.expected:
            st.full.set()

        args = {
            "epoch": group.epoch,
            "peer": self.peer_id,
            "weight": weight,
            "schema": self._schema,
        }

        async def push(addr):
            try:
                await self.transport.call(
                    addr, "byz.contribute", args, wire_bytes,
                    timeout=self._deadline_wait(group, floor=1.0),
                    record_latency=False,  # bulk payload leg
                )
            except (RPCError, OSError, ValueError, asyncio.TimeoutError) as e:
                log.info("byz push to %s failed: %s", addr, errstr(e))

        t0 = time.monotonic()
        degraded = False
        await asyncio.gather(
            *(push(addr) for pid, addr in group.members if pid != self.peer_id)
        )
        try:
            # Every member closes its gather at the SAME consensus-clock
            # deadline (the full-mesh twin of the sync leader's commit).
            await asyncio.wait_for(
                st.full.wait(), timeout=self._deadline_wait(group)
            )
        except asyncio.TimeoutError:
            degraded = True  # deadline commit: aggregate the arrived subset
        # Resolve pre-schema-parked powersgd payloads (exact-size-capped now
        # that our own pack fixed the specs).
        await self._decode_deferred(st)
        received = {
            p: c
            for p, c in st.contribs.items()
            # c[1] None: unresolved deferred entry (see _leader_round note).
            if p in st.expected and c[1] is not None and c[1].size == buf.size
        }
        self._rounds.pop(group.epoch, None)
        excluded = sorted(
            p for p in st.expected if p not in received and p != self.peer_id
        )
        self._round_degraded = degraded
        self._last_outcomes = {
            "on_time": [p for p in sorted(received) if p != self.peer_id],
            "absent": excluded,
        }
        if len(received) < self.min_group:
            self.rounds_skipped += 1
            self._observe_round_failure()
            self._commit_ef(False)
            self._flush_round_outcome(time.monotonic() - t0, ok=False)
            self._note_group_round(False, size=group.size)
            return None
        self._commit_ef(True)
        if excluded:
            log.info(
                "byzantine round committed at deadline without %s (%d/%d)",
                excluded, len(received), len(st.expected),
            )
        peers = sorted(received)
        method, kw = self._effective_method(len(peers))
        if method == "mean":
            kw["weights"] = np.array([received[p][0] for p in peers])
        self.rounds_ok += 1
        if degraded:
            self.rounds_degraded += 1
        else:
            self._observe_round_time(time.monotonic() - t0)
        stack = np.stack([received[p][1] for p in peers])

        def _aggregate_and_flag():
            out = self.mesh_codec.aggregate(stack, method, **kw)
            qmap: Dict[str, float] = {}
            if method != "mean" and len(peers) >= 3:
                # Estimator-rejection feedback for the policy: rows far from
                # the robust aggregate (>3x the median row DISTANCE) were
                # effectively voted out — Chameleon's observed-failure
                # signal for escalating/keeping the estimator. The median
                # is taken in distance space (even group sizes average two
                # middle values, so median(d²) would be a strictly looser
                # bar than median(d)²); the squared distances double as
                # the contribution-quality votes.
                d2 = health_mod.row_d2(stack, out)
                qmap = {peers[i]: float(d2[i]) for i in range(len(peers))}
                med2 = float(np.median(np.sqrt(d2))) ** 2
                if med2 > 0:
                    return out, [
                        peers[i] for i in np.nonzero(d2 > 9.0 * med2)[0]
                        if peers[i] != self.peer_id
                    ], qmap
            return out, [], qmap

        agg, outliers, qmap = await asyncio.to_thread(_aggregate_and_flag)
        if outliers and self.resilience is not None:
            for p in outliers:
                self.resilience.record_rejection(p)
        if self.health is not None and self.health.enabled:
            # Full-mesh vantage: every member attributes quality and mass
            # independently (no trusted leader — that is the point).
            await asyncio.to_thread(
                self._health_note_commit, agg, group.epoch,
                health_mod.mass_from_outcomes(
                    st.expected, {p: float(received[p][0]) for p in received}
                ),
                qmap or None,
            )
        self._flush_round_outcome(time.monotonic() - t0, ok=True)
        self._note_group_round(True, degraded=degraded, size=group.size)
        return await asyncio.to_thread(lambda: self._unpack(agg))


AVERAGERS = {
    "sync": SyncAverager,
    "gossip": GossipAverager,
    "butterfly": ButterflyAverager,
    "byzantine": ByzantineAverager,
}


def make_averager(mode: str, transport, dht, membership, **kw) -> AveragerBase:
    if mode not in AVERAGERS:
        raise KeyError(f"unknown averaging mode {mode!r}; known: {sorted(AVERAGERS)}")
    return AVERAGERS[mode](transport, dht, membership, **kw)
