"""Fault injection for swarm tests (SURVEY.md §5): a Transport that lies.

ChaosTransport wraps the real TCP transport with seeded, tunable faults on
the OUTBOUND path:

- ``drop_rate``   — a call fails with OSError before touching the network
                    (peer unreachable / mid-round death);
- ``delay_s``     — uniform random delay before each call (WAN jitter,
                    stragglers; drives timeout paths without sleeping tests
                    for real-world durations);
- ``corrupt_rate``— one payload byte is flipped AFTER the frame checksum is
                    computed, so the corruption is wire-level and must be
                    caught by the receiver's CRC — this validates the
                    integrity machinery itself, not just error handling.

Rates are attributes, so a test can flip a node from lossy to healthy
mid-scenario deterministically. Production code never imports this module.
"""

from __future__ import annotations

import asyncio
import json
import random
from typing import Optional

from distributedvolunteercomputing_tpu.swarm.transport import (
    _HEADER,
    MAGIC,
    VERSION,
    Addr,
    Transport,
)
from distributedvolunteercomputing_tpu.utils.logging import get_logger

log = get_logger(__name__)


class ChaosTransport(Transport):
    def __init__(
        self,
        *args,
        drop_rate: float = 0.0,
        delay_s: float = 0.0,
        corrupt_rate: float = 0.0,
        seed: int = 0,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.drop_rate = drop_rate
        self.delay_s = delay_s
        self.corrupt_rate = corrupt_rate
        self._chaos = random.Random(seed)

    # Overrides the base class method — called as self._write_frame at
    # every send site, so instance dispatch picks this up for both the
    # client and server halves of this node.
    async def _write_frame(self, writer, ftype: int, meta: dict, payload: bytes) -> None:  # type: ignore[override]
        if payload and self.corrupt_rate and self._chaos.random() < self.corrupt_rate:
            import zlib

            meta_b = json.dumps(meta).encode()
            crc = zlib.crc32(payload) & 0xFFFFFFFF  # checksum of the TRUE payload
            bad = bytearray(payload)
            pos = self._chaos.randrange(len(bad))
            bad[pos] ^= 0xFF
            log.debug("chaos: corrupting payload byte %d", pos)
            writer.write(_HEADER.pack(MAGIC, VERSION, ftype, len(meta_b), len(bad), crc))
            writer.write(meta_b)
            writer.write(bytes(bad))
            await writer.drain()
            return
        await Transport._write_frame(self, writer, ftype, meta, payload)

    async def call(
        self,
        addr: Addr,
        method: str,
        args: Optional[dict] = None,
        payload: bytes = b"",
        timeout: float = 30.0,
    ):
        if self.drop_rate and self._chaos.random() < self.drop_rate:
            raise OSError(f"chaos: dropped call {method} to {addr}")
        if self.delay_s:
            await asyncio.sleep(self._chaos.random() * self.delay_s)
        return await super().call(addr, method, args=args, payload=payload, timeout=timeout)
