"""Fault injection for swarm tests (SURVEY.md §5): a Transport that lies.

ChaosTransport wraps the real TCP transport with seeded, tunable faults on
the OUTBOUND path:

- ``drop_rate``   — a call fails with OSError before touching the network
                    (peer unreachable / mid-round death);
- ``delay_s``     — uniform random delay before each call (WAN jitter,
                    stragglers; drives timeout paths without sleeping tests
                    for real-world durations);
- ``corrupt_rate``— one payload byte is flipped AFTER the frame checksum is
                    computed, so the corruption is wire-level and must be
                    caught by the receiver's CRC — this validates the
                    integrity machinery itself, not just error handling.

Rates are attributes, so a test can flip a node from lossy to healthy
mid-scenario deterministically. Production code never imports this module.

Chaos CAMPAIGNS (the resilience layer's proving ground) want more than
constant rates: a scripted, reproducible SEQUENCE of faults — a latency
spike from t=10..20, a partition from t=30..40, a peer that is 10x slow for
the whole run. ``FaultSchedule`` is that script: a list of ``FaultEvent``
windows (relative to ``start()``), optionally scoped to destination
addresses, combined deterministically (same seed + same schedule = same
fault decisions) and attached to a ChaosTransport via ``schedule=``.
Window-scoped effects COMBINE with the constant attribute rates: delays
add, drop/corrupt probabilities take the max.
"""

from __future__ import annotations

import asyncio
import contextvars
import dataclasses
import random
import time
from typing import Dict, Iterable, Optional, Sequence, Set, Tuple

from distributedvolunteercomputing_tpu.swarm.transport import (
    Addr,
    Transport,
    _payload_len,
)
from distributedvolunteercomputing_tpu.utils.logging import get_logger

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scripted fault window, relative to the schedule's start.

    ``kind``:
      - "delay"     — add ``magnitude`` seconds before every matching call
                      (latency spike / slow peer);
      - "drop"      — fail matching calls with probability ``magnitude``
                      (flaky link; 1.0 = hard partition);
      - "partition" — alias for drop at rate 1.0 (magnitude ignored);
      - "corrupt"   — flip one payload byte with probability ``magnitude``.

    ``targets``: destination addresses the event applies to (None = every
    destination) — a partition event scoped to two addrs cuts exactly that
    edge of the mesh.
    """

    t0: float
    t1: float
    kind: str
    magnitude: float = 0.0
    targets: Optional[frozenset] = None

    _KINDS = ("delay", "drop", "partition", "corrupt")

    def __post_init__(self):
        if self.kind not in self._KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; known: {self._KINDS}")
        if self.t1 < self.t0:
            raise ValueError(f"fault window ends before it starts: {self.t0}..{self.t1}")

    def applies(self, rel_t: float, addr: Addr) -> bool:
        if not (self.t0 <= rel_t < self.t1):
            return False
        return self.targets is None or tuple(addr) in self.targets


def fault_event(
    t0: float,
    t1: float,
    kind: str,
    magnitude: float = 0.0,
    targets: Optional[Iterable[Addr]] = None,
) -> FaultEvent:
    """Convenience constructor normalizing ``targets`` into a frozenset of
    addr tuples (the dataclass itself wants hashable, comparable state)."""
    return FaultEvent(
        t0=float(t0),
        t1=float(t1) if t1 is not None else float("inf"),
        kind=kind,
        magnitude=float(magnitude),
        targets=frozenset(tuple(a) for a in targets) if targets is not None else None,
    )


class FaultSchedule:
    """A deterministic, seedable script of fault windows.

    The schedule is inert until ``start()`` anchors its clock; every
    ChaosTransport sharing one schedule then sees the same timeline, and
    the drop/corrupt coin flips come from the schedule's OWN seeded rng —
    replaying the same schedule with the same traffic order reproduces the
    same faults (the property the chaos-campaign artifact rests on)."""

    def __init__(self, events: Sequence[FaultEvent], seed: int = 0):
        self.events = list(events)
        self.seed = seed
        self._rng = random.Random(seed)
        self._t_start: Optional[float] = None

    def start(self, now: Optional[float] = None) -> None:
        self._t_start = time.monotonic() if now is None else float(now)
        self._rng = random.Random(self.seed)  # restart = same coin flips

    @property
    def started(self) -> bool:
        return self._t_start is not None

    def rel_time(self, now: Optional[float] = None) -> float:
        if self._t_start is None:
            return float("-inf")  # not started: no event matches
        return (time.monotonic() if now is None else float(now)) - self._t_start

    def effects(self, addr: Addr, now: Optional[float] = None) -> Tuple[float, float, float]:
        """(delay_s, drop_rate, corrupt_rate) active for a call to ``addr``
        right now: delays ADD across overlapping windows, probabilities
        take the max (two half-broken links don't make a mended one)."""
        rel = self.rel_time(now)
        delay, drop, corrupt = 0.0, 0.0, 0.0
        for ev in self.events:
            if not ev.applies(rel, addr):
                continue
            if ev.kind == "delay":
                delay += ev.magnitude
            elif ev.kind == "drop":
                drop = max(drop, ev.magnitude)
            elif ev.kind == "partition":
                drop = 1.0
            elif ev.kind == "corrupt":
                corrupt = max(corrupt, ev.magnitude)
        return delay, drop, corrupt

    def coin(self, p: float) -> bool:
        """One seeded fault decision (shared rng -> reproducible runs)."""
        return p > 0 and self._rng.random() < p


# Scheduled corruption travels from the per-CALL decision to the per-FRAME
# write through the task context (each call's message write runs inside the
# call's own wait_for task, which snapshots this at creation) — concurrent
# calls multiplexed onto ONE pooled connection cannot steal each other's
# corruption.
_corrupt_this_call: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "chaos_corrupt_this_call", default=False
)


class ChaosTransport(Transport):
    # Process-wide blackholed peer pairs, shared by every ChaosTransport in
    # the process: entering (a, b) here makes calls between those two
    # addresses fail like a severed link, in BOTH directions provided both
    # endpoints run ChaosTransports (each side refuses its own outbound
    # half). Class-level on purpose — a partition is a property of the
    # network between two nodes, not of one endpoint — so a scenario script
    # can cut an edge with one call on any instance. Tests/campaigns must
    # ``heal()`` in teardown. Composes with the constant rates, the
    # corrupt-offset hook, and any attached FaultSchedule: the partition
    # check runs first (a cut link delivers nothing to delay or corrupt).
    _partitions: Set[frozenset] = set()
    # Process-wide per-peer-pair LINK MODEL (set_link): propagation latency
    # plus serialization bandwidth — and, optionally, a heavy-tailed
    # per-call jitter distribution — for the edge between two addresses.
    # Class-level for the same reason as _partitions — a link is a property
    # of the path between two nodes. Applied on the OUTBOUND half at each
    # endpoint (delay = latency + request_payload/bw before the call), so a
    # WAN scenario models what the hierarchical schedule cares about: a
    # member's bulk contribution push crossing a thin/far edge pays for it
    # in wall time. Composes with everything above — partition first (a cut
    # link delivers nothing), then the link delay, then rates/schedules.
    # Tests/campaigns must ``clear_links()`` in teardown.
    _links: Dict[frozenset, Tuple[float, Optional[float], Optional[dict]]] = {}
    # Process-wide STAR-isolated peer addresses (isolate/restore): every
    # link touching one of these is cut, in both directions, wherever a
    # ChaosTransport runs either endpoint. The shard-kill primitive — a
    # holder "dies" to the whole zone with one call while its process
    # stays inspectable. Class-level like _partitions; tests/campaigns
    # must ``restore_all()`` in teardown. Checked inside _partitioned, so
    # it composes everywhere partitions do.
    _isolated: Set[Addr] = set()

    # Known heavy-tailed jitter shapes for set_link(jitter=...).
    _JITTER_DISTS = ("pareto", "lognormal")

    def __init__(
        self,
        *args,
        drop_rate: float = 0.0,
        delay_s: float = 0.0,
        corrupt_rate: float = 0.0,
        corrupt_at_frac: Optional[float] = None,
        seed: int = 0,
        schedule: Optional[FaultSchedule] = None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.drop_rate = drop_rate
        self.delay_s = delay_s
        self.corrupt_rate = corrupt_rate
        # Deterministic corruption PLACEMENT: flip the byte at this fraction
        # of the payload (0.0 = first byte, ~1.0 = last) instead of a seeded
        # random offset. On the chunked wire that pins which CHUNK dies —
        # the streaming-aggregation tests use it to control exactly how many
        # tiles a contribution seals before its stream aborts.
        self.corrupt_at_frac = corrupt_at_frac
        self.schedule = schedule
        self._chaos = random.Random(seed)

    # Overrides the base transport's fault-injection hook — consulted once
    # per outbound MESSAGE (client request or server response) with the
    # total payload size. Returning an offset makes the transport flip that
    # payload byte AFTER computing the frame/chunk checksums, so the
    # corruption is wire-level and must be caught by the receiver's CRC;
    # on the chunked path the flip lands inside exactly one chunk, whose
    # per-chunk CRC is what fails.
    def _chaos_corrupt_offset(self, ftype: int, total: int):  # type: ignore[override]
        if total <= 0:
            return None
        if _corrupt_this_call.get() or (
            self.corrupt_rate and self._chaos.random() < self.corrupt_rate
        ):
            if self.corrupt_at_frac is not None:
                pos = min(int(self.corrupt_at_frac * total), total - 1)
            else:
                pos = self._chaos.randrange(total)
            log.debug("chaos: corrupting payload byte %d", pos)
            return pos
        return None

    # -- scriptable partitions --------------------------------------------

    @staticmethod
    def _pair(peer_a, peer_b) -> frozenset:
        return frozenset(
            ((str(peer_a[0]), int(peer_a[1])), (str(peer_b[0]), int(peer_b[1])))
        )

    def partition(self, peer_a, peer_b) -> None:
        """Blackhole traffic between two peer addresses: every call either
        of them makes to the other fails with OSError before touching the
        network (both endpoints must run ChaosTransports for both
        directions to be cut). Unlike a scheduled ``partition`` FaultEvent
        this is imperative — a scenario script cuts and heals edges at
        exact protocol points instead of wall-clock windows."""
        ChaosTransport._partitions.add(self._pair(peer_a, peer_b))
        log.debug("chaos: partitioned %s <-> %s", tuple(peer_a), tuple(peer_b))

    def heal(self, peer_a=None, peer_b=None) -> None:
        """Remove one blackholed pair; with a single peer, every partition
        touching that peer; with no arguments, every partition (scenario
        teardown)."""
        if peer_a is None:
            ChaosTransport._partitions.clear()
        elif peer_b is None:
            pa = (str(peer_a[0]), int(peer_a[1]))
            ChaosTransport._partitions = {
                p for p in ChaosTransport._partitions if pa not in p
            }
        else:
            ChaosTransport._partitions.discard(self._pair(peer_a, peer_b))

    def _partitioned(self, addr: Addr) -> bool:
        me = (str(self.addr[0]), int(self.addr[1]))
        a = (str(addr[0]), int(addr[1]))
        if me in ChaosTransport._isolated or a in ChaosTransport._isolated:
            return True
        if not ChaosTransport._partitions:
            return False
        return self._pair(self.addr, addr) in ChaosTransport._partitions

    # -- star isolation (shard-holder kill at the network level) ------------

    def isolate(self, peer=None) -> None:
        """Cut EVERY link touching one peer address (self when None) — the
        network half of a shard-holder SIGKILL: the process lives (its
        state is inspectable by the test) but the zone sees a silent
        death, must re-shard around it, and its own late serves fail
        exactly like a dead socket's would. A star partition, not N
        ``partition`` calls: joins/leaves during the isolation window are
        covered too."""
        addr = self.addr if peer is None else peer
        ChaosTransport._isolated.add((str(addr[0]), int(addr[1])))
        log.debug("chaos: isolated %s", tuple(addr))

    def restore(self, peer=None) -> None:
        """Lift one peer's star isolation (self when None); with
        ``peer=...`` absent AND no self addr, scenario teardown clears
        via ``restore_all``."""
        addr = self.addr if peer is None else peer
        ChaosTransport._isolated.discard((str(addr[0]), int(addr[1])))

    @staticmethod
    def restore_all() -> None:
        ChaosTransport._isolated.clear()

    # -- per-pair link model ------------------------------------------------

    def set_link(
        self,
        peer_a,
        peer_b,
        latency_s: float = 0.0,
        bw_bps: Optional[float] = None,
        jitter: Optional[dict] = None,
    ) -> None:
        """Model the link between two peer addresses: every call either
        endpoint makes to the other first pays ``latency_s`` plus the
        request payload's serialization time at ``bw_bps`` bytes/s (None =
        unconstrained). The WAN building block for hierarchical-scheduling
        scenarios — a two-zone swarm is a few fat intra-zone links plus
        thin, far cross-zone ones. Both endpoints must run ChaosTransports
        for both directions to be modeled; response payloads ride the
        receiver's own outbound model when it calls back. Re-setting a
        pair replaces its link; composes with ``partition``/``heal``,
        constant rates, ``corrupt_at_frac``, and fault schedules.

        ``jitter`` adds a HEAVY-TAILED per-call delay on top of the base
        latency — the tail-latency model tail-optimal benches need (most
        calls near the base, a fat tail of 10-100x outliers), replacing
        hand-rolled x10 stragglers:

        - ``{"dist": "pareto", "scale": s, "alpha": a}`` — extra delay
          ``s * (X - 1)`` with X ~ Pareto(alpha); alpha in (1, 2] is the
          classic heavy WAN tail (smaller alpha = fatter). Median extra
          ~``s * (2^(1/a) - 1)``, unbounded tail.
        - ``{"dist": "lognormal", "scale": s, "sigma": g}`` — extra delay
          ``s * LogNormal(0, g)``; median exactly ``s``.
        - optional ``"cap"``: ceiling (seconds) on the extra delay — real
          stacks retransmit/abort rather than stall a flow for minutes,
          and an uncapped alpha~1 draw otherwise turns one unlucky
          control RPC into a process-scale stall.
        - optional ``"min_bytes"``: draw the jitter only for calls whose
          request payload is at least this size — the bulk-flow tail
          model (a straggler's *data* transfers stall; its meta-sized
          control RPCs ride the base latency), which is the tail the
          hedged-recovery pipeline targets.

        Draws come from this transport's own SEEDED rng, so a campaign
        replay with the same traffic order reproduces the same tail.

        Fidelity limit (same as the PR-8 note on the base model): the
        delay — jitter included — is applied BEFORE the call's bytes are
        written, so it shapes WALL TIME but not the receiver's measured
        arrival rate — the production bandwidth-measurement path (the
        read-timed bw_down EWMA and the rx_bps uplink echo) still
        observes localhost speed over a modeled thin link, and a jittered
        call stalls WHOLE (one draw per call, not per packet — a fresh
        hedged request re-draws, which is exactly the tail-dodging effect
        hedging exploits, but intra-payload pacing is not modeled).
        Scenarios that need bandwidth ADVERTISEMENTS under a modeled WAN
        inject them directly via membership ``extra_info``
        (hierarchy_bench does); pacing the actual socket writes is a
        transport change, not a wrapper's."""
        if latency_s < 0:
            raise ValueError(f"latency_s must be >= 0, got {latency_s}")
        if bw_bps is not None and bw_bps <= 0:
            raise ValueError(f"bw_bps must be > 0 (or None), got {bw_bps}")
        if jitter is not None:
            dist = jitter.get("dist")
            if dist not in self._JITTER_DISTS:
                raise ValueError(
                    f"unknown jitter dist {dist!r}; known: {self._JITTER_DISTS}"
                )
            if float(jitter.get("scale", 0.0)) <= 0:
                raise ValueError("jitter needs scale > 0")
            if dist == "pareto" and float(jitter.get("alpha", 0.0)) <= 0:
                raise ValueError("pareto jitter needs alpha > 0")
            if dist == "lognormal" and float(jitter.get("sigma", 0.0)) <= 0:
                raise ValueError("lognormal jitter needs sigma > 0")
            if jitter.get("cap") is not None and float(jitter["cap"]) < 0:
                raise ValueError("jitter cap must be >= 0")
            jitter = dict(jitter)
        ChaosTransport._links[self._pair(peer_a, peer_b)] = (
            float(latency_s),
            float(bw_bps) if bw_bps is not None else None,
            jitter,
        )

    def clear_links(self, peer_a=None, peer_b=None) -> None:
        """Remove one modeled link; with a single peer, every link touching
        that peer; with no arguments, every link (scenario teardown)."""
        if peer_a is None:
            ChaosTransport._links.clear()
        elif peer_b is None:
            pa = (str(peer_a[0]), int(peer_a[1]))
            ChaosTransport._links = {
                p: v for p, v in ChaosTransport._links.items() if pa not in p
            }
        else:
            ChaosTransport._links.pop(self._pair(peer_a, peer_b), None)

    def _link_delay(self, addr: Addr, n_bytes: int) -> float:
        link = ChaosTransport._links.get(self._pair(self.addr, addr))
        if link is None:
            return 0.0
        latency, bw, jitter = link
        delay = latency + (n_bytes / bw if bw else 0.0)
        if jitter is not None and n_bytes < int(jitter.get("min_bytes") or 0):
            jitter = None
        if jitter is not None:
            # One seeded draw per CALL: most calls ride near the base
            # latency, a heavy tail stalls whole — and a hedged re-request
            # is a fresh call with a fresh draw.
            scale = float(jitter["scale"])
            if jitter["dist"] == "pareto":
                extra = scale * (
                    self._chaos.paretovariate(float(jitter["alpha"])) - 1.0
                )
            else:  # lognormal
                extra = scale * self._chaos.lognormvariate(
                    0.0, float(jitter["sigma"])
                )
            cap = jitter.get("cap")
            if cap is not None:
                extra = min(extra, float(cap))
            delay += extra
        return delay

    async def call(
        self,
        addr: Addr,
        method: str,
        args: Optional[dict] = None,
        payload=b"",
        timeout: float = 30.0,
        **kw,
    ):
        if self._partitioned((str(addr[0]), int(addr[1]))):
            raise OSError(
                f"chaos: partitioned link {self.addr} <-> {tuple(addr)} "
                f"(call {method} dropped)"
            )
        if ChaosTransport._links:
            link_delay = self._link_delay(
                (str(addr[0]), int(addr[1])), _payload_len(payload)
            )
            if link_delay > 0:
                # Deterministic (no jitter), like scheduled delays: a link
                # model should reproduce exactly across campaign replays.
                await asyncio.sleep(link_delay)
        if self.drop_rate and self._chaos.random() < self.drop_rate:
            raise OSError(f"chaos: dropped call {method} to {addr}")
        if self.delay_s:
            await asyncio.sleep(self._chaos.random() * self.delay_s)
        if self.schedule is not None and self.schedule.started:
            delay, drop, corrupt = self.schedule.effects(addr)
            if self.schedule.coin(drop):
                raise OSError(
                    f"chaos schedule: dropped call {method} to {addr} "
                    f"(t={self.schedule.rel_time():.1f}s)"
                )
            if delay > 0:
                # Deterministic magnitude (no jitter): a scheduled latency
                # spike should reproduce exactly across campaign replays.
                await asyncio.sleep(delay)
            if self.schedule.coin(corrupt):
                # Task-local, not a transport-level flag: Transport.call runs
                # the request write inside the call's own wait_for task,
                # which COPIES this context at creation — so under
                # concurrent pushes (asyncio.gather) sharing one pooled
                # connection the corruption lands on exactly the scheduled
                # call's request frame, never on whichever unrelated frame
                # (or server-half response) writes next.
                tok = _corrupt_this_call.set(True)
                try:
                    return await super().call(
                        addr, method, args=args, payload=payload, timeout=timeout, **kw
                    )
                finally:
                    _corrupt_this_call.reset(tok)
        return await super().call(
            addr, method, args=args, payload=payload, timeout=timeout, **kw
        )
