"""Adaptive resilience policy for averaging rounds (Chameleon-style).

One object per volunteer that watches how rounds actually go and adjusts
the knobs the averaging tier runs on, instead of static configuration:

- **round deadline** (``round_budget(level)``): the wall-clock budget a
  round is allowed before it commits with partial participation. Learned
  from COMPLETE rounds' durations (EWMA + 4 deviations, the classic
  adaptive-RTO shape) and AIMD-backed-off on failures — a healthy swarm
  converges to tight deadlines where a stalled peer costs little; a
  genuinely slow network ratchets the budget back toward the configured
  ceiling instead of failing forever. The AIMD state is split PER
  HIERARCHY LEVEL (flat / intra / cross): intra-zone rounds run on fat
  local links and cross-zone rounds on thin WAN links BY DESIGN, so one
  shared estimate would either starve cross rounds or slacken intra ones.
  A level's estimator seeds from the flat record's current operating
  point the first time that level runs, then diverges on its own
  evidence; ``round_budget()`` with no level keeps the pre-split
  behavior (the flat record) for every existing caller.
- **retry backoff** (``backoff_s()``): consecutive failed rounds back off
  exponentially (capped), so a partitioned volunteer stops hammering
  matchmaking at full cadence and re-probes on a widening schedule.
- **robust-estimator escalation** (``recommend_method()``): per-peer
  rejected-contribution counts (size/schema/token mismatches at
  aggregation, plus estimator-flagged outlier rows) escalate the
  aggregation method at runtime — a swarm configured with the cheap
  ``mean`` switches itself to ``trimmed_mean``/``median`` while rejection
  evidence persists, Chameleon's select-the-policy-from-observed-failures
  idea applied to the estimator choice.
- **pre-exclusion** (``should_preexclude()``): per-peer outcome history
  (absent/late streaks) combined with the phi-accrual detector's suspicion
  marks peers the matchmaker should leave out of group formation.
- **hedge budget** (``hedge_params()``): the tail-optimal recovery loop's
  two knobs — what fraction of the round budget to wait before the first
  hedged re-request (the *soft deadline*) and how many hedges may be in
  flight at once — learned per hierarchy level with AIMD, the same shape
  the round deadline uses: mass still lost at the deadline despite
  hedging opens the budget (additive increase in-flight, earlier soft
  deadline); rounds where hedges only duplicated tiles the original
  delivered anyway close it (multiplicative decrease, later soft
  deadline). Cross-zone rounds hedge on slow links by design, so each
  level learns its own operating point.
- **per-peer tail quantiles** (``stats()["peers"][p]["lat_p50_s"/"lat_p95_s"]``):
  observed contribution-completion latencies (arming -> seal, recorded by
  the leader per committed round) kept as a bounded per-peer sample
  window — the hedge-target ranking evidence, visible in coord.status and
  citable by the doctor.

The policy is advisory and local: every averager consults its own
instance; nothing is negotiated over the wire (the leader's deadline
travels in the round's begin message, which is the one place a single
node's policy binds a group — bounded by every member's own ceiling).

Thread-safety: all mutation happens on the asyncio loop (averager round
paths); reads from other threads see atomically-replaced floats.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, Iterable, Optional, Tuple

from distributedvolunteercomputing_tpu.utils.logging import get_logger

log = get_logger(__name__)

# Escalation ladder for the estimator recommendation. Only estimators with
# parameter-free (derived) robustness knobs — krum/bulyan need an explicit
# n_byzantine and stay operator-chosen.
_METHOD_LADDER = ("mean", "trimmed_mean", "coordinate_median")


@dataclasses.dataclass
class PeerOutcomes:
    """Per-peer round-outcome counters (sliding decay, see _decay)."""

    on_time: float = 0.0
    late: float = 0.0
    absent: float = 0.0
    rejected: float = 0.0
    # Consecutive not-on-time rounds; resets on any on-time arrival.
    miss_streak: int = 0
    # Observed contribution-completion latencies (seconds, arming -> seal;
    # recorded by the round leader). Bounded window: the tail quantiles
    # exported in stats() are what rank hedge targets.
    lat: "deque[float]" = dataclasses.field(
        default_factory=lambda: deque(maxlen=64)
    )

    def total(self) -> float:
        return self.on_time + self.late + self.absent + self.rejected


class ResiliencePolicy:
    def __init__(
        self,
        *,
        max_deadline_s: float = 20.0,
        min_deadline_s: float = 2.0,
        initial_deadline_s: Optional[float] = None,
        decay: float = 0.9,
        preexclude_misses: int = 3,
        escalate_rejections: float = 3.0,
        failure_detector=None,
        clock=time.monotonic,
        recorder=None,
    ):
        if min_deadline_s <= 0 or max_deadline_s < min_deadline_s:
            raise ValueError(
                f"need 0 < min_deadline_s <= max_deadline_s, got "
                f"{min_deadline_s} / {max_deadline_s}"
            )
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        self.max_deadline_s = float(max_deadline_s)
        self.min_deadline_s = float(min_deadline_s)
        init_deadline = float(
            max_deadline_s if initial_deadline_s is None else initial_deadline_s
        )
        init_deadline = min(max(init_deadline, min_deadline_s), max_deadline_s)
        self.decay = float(decay)
        self.preexclude_misses = int(preexclude_misses)
        self.escalate_rejections = float(escalate_rejections)
        self.failure_detector = failure_detector
        self.clock = clock
        # Flight recorder (swarm/telemetry.py FlightRecorder, or anything
        # with .record(kind, **fields)): escalation/backoff transitions are
        # exactly the events a chaos post-mortem wants timestamped. The
        # averager attaches its telemetry bundle's recorder when one isn't
        # supplied; None = transitions are logged only.
        self.recorder = recorder
        self.peers: Dict[str, PeerOutcomes] = {}
        # Adaptive-deadline estimate over COMPLETE (non-degraded) rounds,
        # PER HIERARCHY LEVEL. "flat" is the default record every
        # level-less caller reads and writes — byte-identical to the
        # pre-split single estimator; "intra"/"cross" records are created
        # on first use, seeded from flat's current deadline so a level
        # starts at the shared operating point and then diverges on its
        # own evidence (the ISSUE-15 acceptance: cross > intra on a
        # two-zone swarm with a slow WAN).
        self._deadline_levels: Dict[str, dict] = {
            "flat": {
                "deadline": init_deadline,
                "rt_ewma": None,
                "rt_ewdev": 0.0,
            }
        }
        self._consecutive_failures = 0
        self.rounds_seen = 0
        self.rounds_degraded = 0
        # Leader depositions this node participated in (failover recovery):
        # each one also counts as an absent outcome against the deposed
        # peer, so a crash-prone leader accrues the same miss-streak
        # evidence a straggler does and pre-exclusion (and the matchmaker's
        # leadership exclusion) fires on it.
        self.leaders_deposed = 0
        self._method_level = 0
        # Per-group round records (multi-group schedule): group ids rotate
        # every window, so this is a bounded most-recent map plus it keeps
        # the LEARNING unit honest — deadlines and outcome history stay
        # per-PEER (peers persist across rotating groups; a group id does
        # not), while these gauges expose per-group commit health so an
        # operator can see which group is slow instead of one flat number.
        self.group_rounds: Dict[str, dict] = {}
        # Per-hierarchy-level round records (flat | intra | cross), the
        # level-scoped twin of group_rounds: the hierarchical schedule
        # runs intra-zone rounds on fast links every rotation and
        # cross-zone rounds on slow links every k-th, so their durations,
        # degradation rates, and learned-deadline pressure differ BY
        # DESIGN — folding both into one gauge would hide exactly the
        # asymmetry the hierarchy exists to exploit. (Learning stays
        # per-peer and global: a deadline per level is a follow-on.)
        self.level_rounds: Dict[str, dict] = {}
        # Tail-optimal hedge budget, learned PER HIERARCHY LEVEL (flat /
        # intra / cross — cross-zone rounds hedge on slow links by design,
        # so one shared operating point would be wrong for both).
        self._hedge_levels: Dict[str, dict] = {}
        # Per-level regime stamped by the closed-loop controller
        # (swarm/controller.py): "calm" | "churn" | "degraded". Folds the
        # hedge budget into the controller's shared regime model — under
        # churn the hedger's own AIMD would need several lossy rounds to
        # re-open a budget the regime change already predicts, so
        # hedge_params() floors the operating point instead of waiting
        # for the loss evidence. Empty (every level "calm") without a
        # controller: the PR-13 behavior, unchanged.
        self._hedge_regime: Dict[str, str] = {}
        # One slow round must count ONCE: a peer whose push lands after the
        # commit is seen twice (absent in the commit batch, late on the RPC
        # path), in either order. These two sets reconcile the duplicate —
        # _last_absent remembers who the latest flush counted absent (so a
        # late arrival after it reclassifies instead of re-counting), and
        # _late_noted who record_late_arrival already counted (so a flush
        # arriving after it skips them).
        self._last_absent: set = set()
        self._late_noted: set = set()

    # -- deadline (per hierarchy level) ------------------------------------

    @property
    def _deadline(self) -> float:
        """The flat record's deadline — the pre-split scalar every legacy
        reader (group gauges, stats headline) still sees."""
        return self._deadline_levels["flat"]["deadline"]

    def _dl_rec(self, level: Optional[str]) -> dict:
        lv = level or "flat"
        rec = self._deadline_levels.get(lv)
        if rec is None:
            # Seed a new level at the FLAT record's current operating
            # point: a cross-zone round's first deadline should start
            # where the swarm already learned to run, not back at the
            # ceiling — then diverge on its own durations/failures.
            rec = self._deadline_levels[lv] = {
                "deadline": self._deadline_levels["flat"]["deadline"],
                "rt_ewma": None,
                "rt_ewdev": 0.0,
            }
        return rec

    def round_budget(self, level: Optional[str] = None) -> float:
        """Wall-clock budget for the NEXT round at ``level`` (flat when
        None — the pre-split behavior), in seconds."""
        return self._dl_rec(level)["deadline"]

    def deadlines(self) -> Dict[str, float]:
        """Current learned deadline per hierarchy level (stats/status)."""
        return {
            lv: round(rec["deadline"], 3)
            for lv, rec in self._deadline_levels.items()
        }

    def backoff_s(self) -> float:
        """Extra wait before retrying after failed rounds (0 when healthy)."""
        k = self._consecutive_failures
        if k <= 0:
            return 0.0
        return float(min(0.5 * (2.0 ** (k - 1)), 30.0))

    def _observe_duration(self, dt: float, level: Optional[str] = None) -> None:
        rec = self._dl_rec(level)
        if rec["rt_ewma"] is None:
            rec["rt_ewma"], rec["rt_ewdev"] = dt, dt / 2.0
        else:
            rec["rt_ewdev"] += 0.25 * (abs(dt - rec["rt_ewma"]) - rec["rt_ewdev"])
            rec["rt_ewma"] += 0.25 * (dt - rec["rt_ewma"])
        est = rec["rt_ewma"] + 4.0 * rec["rt_ewdev"] + 0.5
        # Multiplicative decrease TOWARD the estimate (never jumping below
        # it): one fast outlier round must not slam the deadline down onto
        # the next round's normal tail.
        target = min(max(est, self.min_deadline_s), self.max_deadline_s)
        if target < rec["deadline"]:
            rec["deadline"] = max(0.7 * rec["deadline"] + 0.3 * target, target)
        else:
            rec["deadline"] = target

    def _observe_failure(self, level: Optional[str] = None) -> None:
        # AIMD: a failed round doubles the budget toward the ceiling — a
        # genuinely slow network recovers instead of timing out forever.
        # Only the failing LEVEL pays: a partitioned WAN must not slacken
        # the intra-zone deadline that is still committing fine.
        rec = self._dl_rec(level)
        rec["deadline"] = min(rec["deadline"] * 2.0, self.max_deadline_s)
        rec["rt_ewma"] = None  # re-learn at the new regime

    # -- outcomes ----------------------------------------------------------

    def _peer(self, peer: str) -> PeerOutcomes:
        st = self.peers.get(peer)
        if st is None:
            st = self.peers[peer] = PeerOutcomes()
        return st

    def _decay_all(self) -> None:
        for st in self.peers.values():
            st.on_time *= self.decay
            st.late *= self.decay
            st.absent *= self.decay
            st.rejected *= self.decay

    MAX_GROUP_RECORDS = 16

    def _note_group(
        self, group_id: Optional[str], *, ok: bool, degraded: bool,
        duration_s: float, absent_n: int,
    ) -> None:
        if group_id is None:
            return
        rec = self.group_rounds.get(group_id)
        if rec is None:
            while len(self.group_rounds) >= self.MAX_GROUP_RECORDS:
                self.group_rounds.pop(next(iter(self.group_rounds)))
            rec = self.group_rounds[group_id] = {
                "rounds": 0, "ok": 0, "degraded": 0,
                "excluded": 0, "last_dt_s": None, "deadline_s": None,
            }
        rec["rounds"] += 1
        rec["ok"] += int(ok)
        rec["degraded"] += int(degraded)
        rec["excluded"] += absent_n
        rec["last_dt_s"] = round(duration_s, 3)
        rec["deadline_s"] = round(self._deadline, 3)

    def _note_level(
        self, level: Optional[str], *, ok: bool, degraded: bool,
        duration_s: float,
    ) -> None:
        if not level:
            return
        rec = self.level_rounds.setdefault(
            level, {"rounds": 0, "ok": 0, "degraded": 0, "last_dt_s": None},
        )
        rec["rounds"] += 1
        rec["ok"] += int(ok)
        rec["degraded"] += int(degraded)
        rec["last_dt_s"] = round(duration_s, 3)
        # The level's LEARNED deadline rides its round record so stats()
        # (and coord.status) show the per-level split next to the
        # outcomes that drove it.
        rec["deadline_s"] = round(self._dl_rec(level)["deadline"], 3)

    def record_round(
        self,
        *,
        duration_s: float,
        ok: bool,
        degraded: bool = False,
        on_time: Iterable[str] = (),
        late: Iterable[str] = (),
        absent: Iterable[str] = (),
        rejected: Iterable[str] = (),
        group_id: Optional[str] = None,
        level: Optional[str] = None,
    ) -> None:
        """One finished round, from whichever vantage this node had (a
        leader knows per-peer arrivals; a member may only know ok/duration).

        A DEGRADED round (committed at the deadline with a subset) counts
        as success for the deadline estimate's failure logic but its
        duration is NOT observed — it took ~the deadline by construction,
        and observing it would ratchet the estimate to the ceiling in
        exactly the persistent-straggler case this policy targets."""
        self.rounds_seen += 1
        absent = list(absent)
        self._note_group(
            group_id, ok=ok, degraded=degraded,
            duration_s=duration_s, absent_n=len(absent),
        )
        self._note_level(level, ok=ok, degraded=degraded, duration_s=duration_s)
        self._decay_all()
        for p in on_time:
            st = self._peer(p)
            st.on_time += 1.0
            st.miss_streak = 0
        for p in late:
            st = self._peer(p)
            st.late += 1.0
            st.miss_streak += 1
        counted_absent = set()
        for p in absent:
            if p in self._late_noted:
                # Its late arrival already counted this round's miss (the
                # push landed between the commit and this flush).
                continue
            st = self._peer(p)
            st.absent += 1.0
            st.miss_streak += 1
            counted_absent.add(p)
        self._last_absent = counted_absent
        self._late_noted.clear()
        for p in rejected:
            st = self._peer(p)
            st.rejected += 1.0
            st.miss_streak += 1
        if ok:
            self._consecutive_failures = 0
            if degraded:
                self.rounds_degraded += 1
            else:
                self._observe_duration(duration_s, level)
        else:
            self._consecutive_failures += 1
            self._observe_failure(level)
        self._maybe_escalate()

    def record_late_arrival(self, peer: str) -> None:
        """A contribution that landed AFTER its round committed (detected
        on the RPC path, outside record_round's batch). The commit usually
        counted the same peer absent already — that one event reclassifies
        absent -> late rather than advancing the miss streak twice."""
        st = self._peer(peer)
        if peer in self._last_absent:
            self._last_absent.discard(peer)
            st.absent = max(0.0, st.absent - 1.0)
            st.late += 1.0
            return  # the absent count already advanced the streak
        st.late += 1.0
        st.miss_streak += 1
        self._late_noted.add(peer)

    def note_leader_deposed(self, peer: str) -> None:
        """A round this node was a member of deposed ``peer`` as its leader
        (connection-level death / suspicion mid-round, recovered by a
        successor). Counts one absent outcome and advances the miss streak
        — the same evidence trail any other failure leaves — so
        ``should_preexclude`` (and the matchmaker's leadership exclusion,
        which consults it) keeps a crash-looping leader out of the lead."""
        self.leaders_deposed += 1
        st = self._peer(peer)
        st.absent += 1.0
        st.miss_streak += 1

    def record_rejection(self, peer: str) -> None:
        """A contribution dropped at aggregation (bad size/schema/token, or
        flagged as an outlier row by the robust estimator)."""
        self._peer(peer).rejected += 1.0
        self._maybe_escalate()

    def record_contribution_latency(self, peer: str, dt: float) -> None:
        """One observed contribution-completion latency (seconds from round
        arming to the peer's seal, recorded by the leader). Feeds the
        per-peer tail quantiles in ``stats()`` — the evidence the hedge
        loop ranks re-request targets by."""
        if dt < 0 or not dt < float("inf"):
            return
        self._peer(peer).lat.append(float(dt))

    def peer_latency_quantiles(self, peer: str) -> Optional[Tuple[float, float]]:
        """(p50, p95) of the peer's observed contribution latencies, or
        None before any sample."""
        st = self.peers.get(peer)
        if st is None or not st.lat:
            return None
        xs = sorted(st.lat)
        return (
            xs[int(0.5 * (len(xs) - 1))],
            xs[int(round(0.95 * (len(xs) - 1)))],
        )

    # -- hedge budget (tail-optimal recovery) -------------------------------

    HEDGE_SOFT_FRAC_INIT = 0.6
    HEDGE_SOFT_FRAC_MIN = 0.3
    HEDGE_SOFT_FRAC_MAX = 0.9
    HEDGE_SOFT_FRAC_STEP = 0.05
    HEDGE_INFLIGHT_INIT = 2
    HEDGE_INFLIGHT_MIN = 1
    HEDGE_INFLIGHT_MAX = 8

    def _hedge_rec(self, level: Optional[str]) -> dict:
        lv = level or "flat"
        rec = self._hedge_levels.get(lv)
        if rec is None:
            rec = self._hedge_levels[lv] = {
                "soft_frac": self.HEDGE_SOFT_FRAC_INIT,
                "max_inflight": float(self.HEDGE_INFLIGHT_INIT),
                "rounds": 0,
                "issued": 0,
                "tiles_recovered": 0,
                "duplicate_tiles": 0,
                "slots_recovered": 0,
                "lost_weight_after": 0.0,
            }
        return rec

    def set_regime(self, level: Optional[str], regime: str) -> None:
        """Adopt the controller's regime verdict for ``level`` (one shared
        model for topology/wire/hedge instead of three AIMD loops fighting
        each other). Unknown regimes are treated as "calm"."""
        self._hedge_regime[level or "flat"] = str(regime)

    def hedge_params(self, level: Optional[str] = None) -> Tuple[float, int]:
        """(soft_deadline_frac, max_inflight_hedges) for the NEXT round at
        ``level``: wait soft_frac x the round budget before the first
        hedged re-request, and keep at most max_inflight in flight.

        The controller's regime (``set_regime``) floors the learned
        operating point: under "churn" the soft deadline is pulled to at
        most 0.5x the budget with >= 2 hedges allowed, under "degraded"
        to 0.4x with >= 3 — the AIMD state itself is untouched, so when
        the regime clears the learned point resumes exactly where the
        loss evidence left it."""
        rec = self._hedge_rec(level)
        soft = float(rec["soft_frac"])
        inflight = max(1, int(round(rec["max_inflight"])))
        regime = self._hedge_regime.get(level or "flat", "calm")
        if regime == "churn":
            soft = min(soft, 0.5)
            inflight = max(inflight, 2)
        elif regime == "degraded":
            soft = min(soft, 0.4)
            inflight = max(inflight, 3)
        return soft, inflight

    def record_hedge_outcome(
        self,
        level: Optional[str] = None,
        *,
        issued: int,
        tiles_recovered: int = 0,
        duplicate_tiles: int = 0,
        slots_recovered: int = 0,
        lost_weight: float = 0.0,
    ) -> None:
        """One committed round's hedge scorecard, AIMD'd into the level's
        budget the way round deadlines learn: mass STILL lost at the
        deadline means the hedger was too little / too late — additive
        increase of in-flight budget, earlier soft deadline; a round whose
        hedges only duplicated tiles the original delivered anyway means
        the hedger fired on a healthy tail — multiplicative decrease,
        later soft deadline. Rounds with no hedges and no loss leave the
        operating point alone (no evidence either way)."""
        rec = self._hedge_rec(level)
        rec["rounds"] += 1
        rec["issued"] += int(issued)
        rec["tiles_recovered"] += int(tiles_recovered)
        rec["duplicate_tiles"] += int(duplicate_tiles)
        rec["slots_recovered"] += int(slots_recovered)
        rec["lost_weight_after"] += float(lost_weight)
        if lost_weight > 0:
            rec["max_inflight"] = min(
                rec["max_inflight"] + 1.0, float(self.HEDGE_INFLIGHT_MAX)
            )
            rec["soft_frac"] = max(
                rec["soft_frac"] - self.HEDGE_SOFT_FRAC_STEP,
                self.HEDGE_SOFT_FRAC_MIN,
            )
        elif issued and tiles_recovered == 0 and duplicate_tiles > 0:
            rec["max_inflight"] = max(
                rec["max_inflight"] * 0.7, float(self.HEDGE_INFLIGHT_MIN)
            )
            rec["soft_frac"] = min(
                rec["soft_frac"] + self.HEDGE_SOFT_FRAC_STEP,
                self.HEDGE_SOFT_FRAC_MAX,
            )

    # -- decisions ---------------------------------------------------------

    def should_preexclude(self, peer: str) -> bool:
        """Should group formation leave this peer out? True when the
        phi-accrual detector suspects it, or its recent outcome history is
        a miss streak (absent/late/rejected ``preexclude_misses`` rounds
        running)."""
        if self.failure_detector is not None and self.failure_detector.suspect(peer):
            return True
        st = self.peers.get(peer)
        return st is not None and st.miss_streak >= self.preexclude_misses

    def _maybe_escalate(self) -> None:
        worst = max(
            (st.rejected for st in self.peers.values()), default=0.0
        )
        if worst >= 2.0 * self.escalate_rejections:
            level = 2
        elif worst >= self.escalate_rejections:
            level = 1
        else:
            level = 0
        if level > self._method_level:
            log.warning(
                "resilience: escalating aggregation to %s "
                "(peer rejection score %.1f)", _METHOD_LADDER[level], worst,
            )
            self._record_event(
                "method_escalated",
                method=_METHOD_LADDER[level],
                rejection_score=round(worst, 2),
            )
            self._method_level = level
        elif level < self._method_level and worst < 0.5:
            # De-escalate only once the evidence has decayed away entirely —
            # flapping between estimators round-to-round helps nobody.
            log.info("resilience: rejection evidence decayed; back to %s",
                     _METHOD_LADDER[level])
            self._record_event(
                "method_deescalated", method=_METHOD_LADDER[level]
            )
            self._method_level = level

    def _record_event(self, kind: str, **fields) -> None:
        if self.recorder is not None:
            try:
                self.recorder.record(kind, **fields)
            except Exception:  # noqa: BLE001 — recording must not affect policy
                pass

    def recommend_method(self, configured: str) -> str:
        """Estimator to aggregate with THIS round. Only ever escalates an
        explicitly-cheap configuration (``mean``) up the derived-knob ladder;
        an operator-chosen robust method is respected as the floor."""
        if configured != _METHOD_LADDER[0]:
            return configured
        return _METHOD_LADDER[self._method_level]

    def stats(self) -> dict:
        out = {
            "deadline_s": round(self._deadline, 3),
            # The per-level deadline split (ISSUE 15): flat always
            # present; intra/cross appear once those levels have run.
            "deadlines": self.deadlines(),
            "rounds_seen": self.rounds_seen,
            "rounds_degraded": self.rounds_degraded,
            "leaders_deposed": self.leaders_deposed,
            "consecutive_failures": self._consecutive_failures,
            "method_level": _METHOD_LADDER[self._method_level],
            "peers": {
                p: self._peer_stats_dict(p, st) for p, st in self.peers.items()
            },
        }
        if self.group_rounds:
            out["groups"] = {g: dict(r) for g, r in self.group_rounds.items()}
        if self.level_rounds:
            out["levels"] = {lv: dict(r) for lv, r in self.level_rounds.items()}
        if self._hedge_levels:
            out["hedge"] = {
                lv: {
                    "soft_frac": round(rec["soft_frac"], 3),
                    "max_inflight": max(1, int(round(rec["max_inflight"]))),
                    "regime": self._hedge_regime.get(lv, "calm"),
                    "rounds": rec["rounds"],
                    "issued": rec["issued"],
                    "tiles_recovered": rec["tiles_recovered"],
                    "duplicate_tiles": rec["duplicate_tiles"],
                    "slots_recovered": rec["slots_recovered"],
                    "lost_weight_after": round(rec["lost_weight_after"], 6),
                }
                for lv, rec in self._hedge_levels.items()
            }
        return out

    def _peer_stats_dict(self, peer: str, st: PeerOutcomes) -> dict:
        out = {
            "on_time": round(st.on_time, 2),
            "late": round(st.late, 2),
            "absent": round(st.absent, 2),
            "rejected": round(st.rejected, 2),
            "miss_streak": st.miss_streak,
        }
        q = self.peer_latency_quantiles(peer)
        if q is not None:
            # Observed contribution-latency tail — the hedge-target
            # ranking, visible in coord.status and citable by the doctor.
            out["lat_p50_s"] = round(q[0], 4)
            out["lat_p95_s"] = round(q[1], 4)
            out["lat_samples"] = len(st.lat)
        return out
