"""Swarm telemetry plane: round tracing, metrics registry, flight recorder.

PRs 1-9 each bolted their own gauges onto ``Averager.stats()`` and the
coord.status rollup — ~10 disjoint ad-hoc dicts and no way to answer the
question every chaos campaign and bench actually asks: **where did a
round's wall time go, across volunteers?** This module is the shared
substrate those surfaces re-register into:

- **Distributed round tracing** (:class:`Tracer`): lightweight spans over
  the round protocol's phases (``join -> arm -> encode -> wire -> fold ->
  commit`` / ``recover``) whose trace id IS the existing round key — the
  matchmaking epoch hash, which already folds in the group-scoped
  rendezvous key (``r<rot>.g<idx>`` levels included) — with the failover
  generation riding as a span attribute. The trace id propagates in the
  transport frame meta (``Transport.call`` stamps the ambient trace into
  every outbound frame; the server half restores it around the handler
  task), so the leader's handler-side spans and each member's client-side
  spans stitch into one tree WITHOUT any new RPC. Span timestamps are
  taken on the telemetry clock — ``ClockSync.now`` when the volunteer has
  one — so cross-volunteer spans align to swarm-consensus time, not raw
  host clocks.

- **Unified metrics registry** (:class:`MetricsRegistry`): counters,
  gauges, and log2-bucketed histograms with bounded label sets, plus
  *callback sources* — the existing ``stats()`` dict surfaces (transport,
  failover, aggregation, control_plane, ...) register themselves once and
  every scrape flattens their numeric leaves into gauges under a stable
  dotted namespace. Scraped via the ``telemetry.scrape`` RPC, batched
  through the PR-9 ``cp.exchange`` beat (the volunteer report carries
  :meth:`Telemetry.summary`), and rolled up by control-plane replicas into
  ``coord.status["telemetry"]`` under the versioned schema below.

- **Flight recorder** (:class:`FlightRecorder`): a bounded ring buffer of
  structured events (depositions, fences rejected, degrades, backoff and
  escalation transitions) every volunteer keeps locally. Dumped on demand
  via the ``telemetry.flight`` debug RPC, and attached automatically to
  chaos campaign artifacts on verdict (experiments/chaos_soak.py) — a
  failed verdict ships its own post-mortem.

Everything is advisory and bounded: a telemetry bug must never fail a
round, so record paths swallow their own exceptions, ring buffers cap
memory, and ``Telemetry(enabled=False)`` turns every hot-path call into a
cheap no-op (the overhead smoke in tests/test_telemetry.py holds the
enabled path within 5% of disabled commit latency).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

# Version stamp carried by every scrape, report summary, and the
# coord.status rollup. Bump when the SHAPE of the telemetry surfaces
# changes; tests/test_telemetry.py pins the documented schema per version
# so rollup drift breaks CI instead of dashboards.
# v2: the training-health layer (swarm/health.py) — health summaries ride
# the report beat, scrapes carry the health view, and the status rollup
# counts health reporters (the full health rollup is coord.status["health"],
# pinned by its own STATUS_HEALTH_SCHEMA).
# v3: the watchdog layer (swarm/watchdog.py) — flight events carry a
# severity (``sev``) and the flight RPC an incremental ``since_seq``
# cursor; scrapes carry the watchdog view; the status rollups gain an
# ``age_s`` staleness stamp (the slo/alerts sections are pinned by
# watchdog.STATUS_WATCHDOG_SCHEMA).
TELEMETRY_SCHEMA_VERSION = 3

# RPC method names (registered by Telemetry.register_rpcs).
SCRAPE_METHOD = "telemetry.scrape"
TRACE_METHOD = "telemetry.trace"
FLIGHT_METHOD = "telemetry.flight"
PROM_METHOD = "telemetry.prom"

# Default severity per flight-recorder event kind (the alerting tier's
# triage order: ``page`` wakes someone, ``warn`` waits for business
# hours, ``info`` is context). Callers can override per event via
# ``sev=``; unknown kinds default to "info".
KIND_SEVERITY: Dict[str, str] = {
    "leader_deposed": "warn",
    "fence_rejected": "warn",
    "round_degraded": "warn",
    "round_failed": "warn",
    "round_recovered": "info",
    "recovery_failed": "page",
    "backoff": "warn",
    "method_escalated": "warn",
    "method_deescalated": "info",
    "codec_degraded": "warn",
    "peer_quality_flagged": "page",
    "mass_lost_at_deadline": "warn",
    # Tail-optimal hedged recovery: a hedge being issued is routine
    # tail-chasing; recovered mass is the good-news twin of
    # mass_lost_at_deadline.
    "hedge_issued": "info",
    "mass_recovered_by_hedge": "info",
    # Closed-loop controller: an applied policy transition is an
    # INTENTIONAL retune (context for the anomaly it pre-empts or
    # explains, not itself an anomaly).
    "policy_changed": "info",
    "alert_raised": "page",
    "alert_cleared": "info",
    # Zone-sharded training (swarm/sharding.py): a holder departing with
    # its shard starts a recovery clock (warn until the ladder closes it);
    # a fence rejection is the protocol WORKING (a stale serve/pull was
    # refused) but worth a look in bulk; an exhausted ladder means a
    # shard's state is gone from the zone — page.
    "shard_lost": "warn",
    "shard_recovered": "info",
    "shard_fence_rejected": "warn",
    "shard_recovery_failed": "page",
}

# The ambient trace id: set by Tracer.trace_scope around a round on the
# client side, and restored by the transport server around each handler
# task from the frame meta's ``tr`` field — which is how a leader's
# handler-side spans inherit the member's round trace with no new RPCs.
_CURRENT_TRACE: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "dvc_trace", default=None
)


def current_trace() -> Optional[str]:
    """The ambient round trace id, or None outside any traced round."""
    return _CURRENT_TRACE.get()


def set_current_trace(trace: Optional[str]) -> contextvars.Token:
    """Bind the ambient trace (transport server half; see module doc)."""
    return _CURRENT_TRACE.set(trace)


def reset_current_trace(token: contextvars.Token) -> None:
    try:
        _CURRENT_TRACE.reset(token)
    except ValueError:
        # Token from another context (a handler that migrated tasks) —
        # the var is request-scoped anyway; losing the reset is harmless.
        pass


# -- metrics registry --------------------------------------------------------


class Counter:
    """Monotone counter, optionally labeled. Thread-safe."""

    __slots__ = ("name", "help", "_lock", "_values")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, value: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def value(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def _scrape(self) -> dict:
        with self._lock:
            return {
                "type": "counter",
                "values": [
                    {"labels": dict(k), "value": v}
                    for k, v in self._values.items()
                ],
            }


class Gauge:
    """Last-write-wins gauge, optionally labeled or callback-sourced."""

    __slots__ = ("name", "help", "_lock", "_values", "_fn")

    def __init__(self, name: str, help: str = "", fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._fn = fn

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def value(self, **labels: str) -> Optional[float]:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a gauge callback must not raise out
                return None
        return self._values.get(_label_key(labels))

    def _scrape(self) -> dict:
        if self._fn is not None:
            v = self.value()
            vals = [] if v is None else [{"labels": {}, "value": v}]
        else:
            with self._lock:
                vals = [
                    {"labels": dict(k), "value": v}
                    for k, v in self._values.items()
                ]
        return {"type": "gauge", "values": vals}


# Log2 histogram bucket upper bounds, in seconds, covering 1ms .. ~2min.
# Chosen once for every duration histogram in the swarm: cross-volunteer
# rollups can merge buckets without resampling.
HIST_BUCKETS: Tuple[float, ...] = tuple(0.001 * (2.0 ** i) for i in range(18))


class Histogram:
    """Log2-bucketed histogram (fixed shared buckets), optionally labeled."""

    __slots__ = ("name", "help", "_lock", "_series")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        # label key -> [counts per bucket (+inf last), total count, total sum]
        self._series: Dict[Tuple[Tuple[str, str], ...], list] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [[0] * (len(HIST_BUCKETS) + 1), 0, 0.0]
            counts, _, _ = s
            for i, ub in enumerate(HIST_BUCKETS):
                if value <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            s[1] += 1
            s[2] += float(value)

    def snapshot(self, **labels: str) -> Optional[dict]:
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None:
                return None
            return {"buckets": list(s[0]), "count": s[1], "sum": s[2]}

    def _scrape(self) -> dict:
        with self._lock:
            return {
                "type": "histogram",
                "bucket_bounds": list(HIST_BUCKETS),
                "values": [
                    {
                        "labels": dict(k),
                        "buckets": list(s[0]),
                        "count": s[1],
                        "sum": round(s[2], 6),
                    }
                    for k, s in self._series.items()
                ],
            }


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """One namespace of counters/gauges/histograms plus callback sources.

    ``source(prefix, fn)`` registers an existing ``stats()``-style dict
    callable; every scrape flattens its numeric leaves into gauges under
    ``<prefix>.<dotted.path>`` — the re-registration path that unifies the
    pre-telemetry ad-hoc dicts without rewriting the code that fills them.
    """

    # Bound on flattened series emitted per callback source per scrape:
    # the per-peer transport map can grow to MAX_PEER_STATS entries x 7
    # fields, and a scrape rides RPC replies/reports.
    MAX_SOURCE_SERIES = 512
    MAX_FLATTEN_DEPTH = 4

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}
        self._sources: Dict[str, Callable[[], dict]] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_make(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_make(name, Gauge, help)

    def gauge_fn(self, name: str, fn: Callable[[], float], help: str = "") -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Gauge(name, help, fn=fn)
            elif not isinstance(m, Gauge):
                # Same contract as every other accessor: a name collision
                # across metric types is a bug, not a silent no-op.
                raise ValueError(
                    f"metric {name!r} already registered as {type(m).__name__}"
                )
            elif m._fn is None:
                # A set()-style gauge pre-registered under this name: adopt
                # the callback rather than silently never reporting it.
                m._fn = fn
            return m

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get_or_make(name, Histogram, help)

    def _get_or_make(self, name: str, cls, help: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {type(m).__name__}"
                )
            return m

    def source(self, prefix: str, fn: Callable[[], dict]) -> None:
        """Register a stats()-style dict callable; scrapes flatten its
        numeric leaves into gauges under ``<prefix>.<path>``."""
        with self._lock:
            self._sources[prefix] = fn

    def _flatten(self, prefix: str, obj: Any, out: Dict[str, float], depth: int) -> None:
        if len(out) >= self.MAX_SOURCE_SERIES:
            return
        if isinstance(obj, bool):
            out[prefix] = float(obj)
        elif isinstance(obj, (int, float)):
            out[prefix] = float(obj)
        elif isinstance(obj, dict) and depth < self.MAX_FLATTEN_DEPTH:
            for k, v in obj.items():
                self._flatten(f"{prefix}.{k}", v, out, depth + 1)

    def scrape(self) -> dict:
        """Versioned point-in-time view of every metric and source."""
        with self._lock:
            metrics = dict(self._metrics)
            sources = dict(self._sources)
        out: Dict[str, Any] = {}
        for name, m in sorted(metrics.items()):
            out[name] = m._scrape()
        for prefix, fn in sorted(sources.items()):
            flat: Dict[str, float] = {}
            try:
                self._flatten(prefix, fn() or {}, flat, 0)
            except Exception as e:  # noqa: BLE001 — a source bug must not fail the scrape
                log.debug("telemetry source %s failed: %s", prefix, errstr(e))
                continue
            for name, v in flat.items():
                out[name] = {"type": "gauge", "values": [{"labels": {}, "value": v}]}
        return {"schema_version": TELEMETRY_SCHEMA_VERSION, "metrics": out}


# -- tracing -----------------------------------------------------------------


class Span:
    """One timed phase of a round. End exactly once (idempotent)."""

    __slots__ = ("tracer", "name", "trace", "attrs", "t0", "_pc0", "dur_s", "_done")

    def __init__(self, tracer: "Tracer", name: str, trace: str, attrs: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.trace = trace
        self.attrs = attrs
        # Wall timestamp on the telemetry clock (ClockSync-aligned when the
        # volunteer has one) for cross-volunteer stitching; duration from
        # the monotonic clock so a mid-span offset correction cannot
        # produce a negative phase.
        self.t0 = tracer._clock()
        self._pc0 = time.perf_counter()
        self.dur_s = None
        self._done = False

    def end(self, **attrs: Any) -> None:
        if self._done:
            return
        self._done = True
        self.dur_s = time.perf_counter() - self._pc0
        if attrs:
            self.attrs.update(attrs)
        self.tracer._finish(self)

    def as_dict(self) -> dict:
        return {
            "trace": self.trace,
            "name": self.name,
            "peer": self.tracer.peer_id,
            "t0": round(self.t0, 6),
            "dur_s": round(self.dur_s, 6) if self.dur_s is not None else None,
            **({"attrs": self.attrs} if self.attrs else {}),
        }


class Tracer:
    """Bounded ring of finished spans, keyed by round trace id.

    Ended spans also land in the registry as the
    ``swarm.span_seconds{span=<name>}`` histogram — the metrics half of
    the span taxonomy, scrapeable without pulling whole traces.
    """

    MAX_SPANS = 4096

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        peer_id: str = "",
        clock: Callable[[], float] = time.time,
        enabled: bool = True,
    ):
        self.registry = registry
        self.peer_id = peer_id
        self.enabled = enabled
        self._clock = clock
        self._lock = threading.Lock()
        self._done: "deque[dict]" = deque(maxlen=self.MAX_SPANS)
        self._hist = registry.histogram(
            "swarm.span_seconds", "round phase durations by span name"
        ) if registry is not None else None
        # Finished-span hook (the watchdog's per-level round-wall feed):
        # called with each ended span's dict, exceptions swallowed.
        self.on_record: Optional[Callable[[dict], None]] = None

    def start(self, name: str, trace: Optional[str] = None, **attrs: Any) -> Optional[Span]:
        if not self.enabled:
            return None
        trace = trace or current_trace()
        if not trace:
            return None
        return Span(self, name, trace, attrs)

    def _finish(self, span: Span) -> None:
        try:
            sp = span.as_dict()
            with self._lock:
                self._done.append(sp)
            if self._hist is not None and span.dur_s is not None:
                self._hist.observe(span.dur_s, span=span.name)
            if self.on_record is not None:
                self.on_record(sp)
        except Exception as e:  # noqa: BLE001 — tracing must never fail the round
            log.debug("span finish failed: %s", errstr(e))

    def record(
        self, name: str, trace: str, t0: float, dur_s: float, **attrs: Any
    ) -> None:
        """Append an already-measured span retroactively — for phases
        (like ``join``) that finish before their round's trace id exists."""
        if not self.enabled or not trace:
            return
        sp: Dict[str, Any] = {
            "trace": trace,
            "name": name,
            "peer": self.peer_id,
            "t0": round(t0, 6),
            "dur_s": round(dur_s, 6),
        }
        if attrs:
            sp["attrs"] = attrs
        with self._lock:
            self._done.append(sp)
        if self._hist is not None:
            self._hist.observe(dur_s, span=name)
        if self.on_record is not None:
            try:
                self.on_record(sp)
            except Exception as e:  # noqa: BLE001 — the hook must not fail the caller
                log.debug("span hook failed: %s", errstr(e))

    @contextlib.contextmanager
    def span(self, name: str, trace: Optional[str] = None, **attrs: Any) -> Iterator[Optional[Span]]:
        sp = self.start(name, trace, **attrs)
        try:
            yield sp
        finally:
            if sp is not None:
                sp.end()

    @contextlib.contextmanager
    def trace_scope(self, trace: str) -> Iterator[None]:
        """Bind the ambient trace id for the duration of a round: spans
        started without an explicit trace, and every outbound
        ``Transport.call`` issued inside, inherit it."""
        token = set_current_trace(trace)
        try:
            yield
        finally:
            reset_current_trace(token)

    def spans(self, trace: Optional[str] = None, since: float = 0.0) -> List[dict]:
        with self._lock:
            out = list(self._done)
        if trace:
            out = [s for s in out if s["trace"] == trace]
        if since:
            out = [s for s in out if s["t0"] >= since]
        return out

    def clear(self) -> None:
        with self._lock:
            self._done.clear()


# -- flight recorder ---------------------------------------------------------


class FlightRecorder:
    """Bounded ring buffer of structured swarm events for post-mortems.

    Event kinds recorded by the swarm tier (the documented taxonomy —
    docs/OBSERVABILITY.md keeps the authoritative list):

    - ``leader_deposed`` — this node decided a deposition (failover).
    - ``fence_rejected`` — a push/fetch/recover carried a stale generation.
    - ``round_degraded`` — a round committed at its deadline with a subset.
    - ``round_failed`` — a round raised / skipped below min_group.
    - ``round_recovered`` / ``recovery_failed`` — failover outcomes.
    - ``backoff`` — the resilience backoff engaged/changed after failures.
    - ``method_escalated`` / ``method_deescalated`` — estimator ladder moves.
    - ``codec_degraded`` — the on-mesh data path fell back to host.
    - ``peer_quality_flagged`` — the contribution-quality score crossed
      the flag threshold for a peer (swarm/health.py).
    - ``mass_lost_at_deadline`` — a committed round excluded/aborted
      nonzero gradient mass (swarm/health.py).
    """

    MAX_EVENTS = 2048

    def __init__(
        self,
        peer_id: str = "",
        clock: Callable[[], float] = time.time,
        enabled: bool = True,
    ):
        self.peer_id = peer_id
        self.enabled = enabled
        self._clock = clock
        self._lock = threading.Lock()
        self._events: "deque[dict]" = deque(maxlen=self.MAX_EVENTS)
        self._seq = 0

    def record(self, kind: str, **fields: Any) -> None:
        if not self.enabled:
            return
        try:
            trace = fields.pop("trace", None) or current_trace()
            # Severity rides every event (triage tier for the alerting
            # plane): explicit sev= wins, else the documented per-kind
            # default, else "info".
            sev = fields.pop("sev", None) or KIND_SEVERITY.get(str(kind), "info")
            ev = {
                "seq": self._seq,
                "t": round(self._clock(), 6),
                "kind": str(kind),
                "sev": str(sev),
                "peer": self.peer_id,
            }
            if trace:
                ev["trace"] = trace
            ev.update(fields)
            with self._lock:
                ev["seq"] = self._seq
                self._seq += 1
                self._events.append(ev)
        except Exception as e:  # noqa: BLE001 — recording must never fail the caller
            log.debug("flight record failed: %s", errstr(e))

    def dump(
        self,
        since: float = 0.0,
        kinds: Optional[List[str]] = None,
        since_seq: Optional[int] = None,
    ) -> List[dict]:
        """Ring contents, filterable by time (``since``), kind, and the
        monotonic ``since_seq`` CURSOR (events with seq >= since_seq) —
        the incremental-poll half of the flight RPC: a watchdog poller or
        chaos collector passes the previous reply's ``next_seq`` back and
        re-ships only what's new instead of the whole ring."""
        with self._lock:
            out = list(self._events)
        if since:
            out = [e for e in out if e["t"] >= since]
        if since_seq is not None:
            out = [e for e in out if e["seq"] >= since_seq]
        if kinds:
            want = set(kinds)
            out = [e for e in out if e["kind"] in want]
        return out

    @property
    def next_seq(self) -> int:
        """The cursor a caller passes as ``since_seq`` next poll to see
        only events recorded after everything currently in the ring."""
        return self._seq

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


# -- the bundle --------------------------------------------------------------


class Telemetry:
    """Per-volunteer telemetry bundle: registry + tracer + flight recorder.

    One instance per process half (a volunteer, a coordinator replica),
    shared by the averager, membership, resilience policy, and transport
    via constructor injection. ``enabled=False`` short-circuits every
    record path (the overhead-smoke baseline and the ``--no-telemetry``
    escape hatch); the registry still answers scrapes (empty-ish) so the
    RPC surface never disappears mid-fleet.
    """

    def __init__(
        self,
        peer_id: str = "",
        clock: Callable[[], float] = time.time,
        enabled: bool = True,
        health_enabled: Optional[bool] = None,
        watchdog_enabled: Optional[bool] = None,
    ):
        self.peer_id = peer_id
        self.enabled = enabled
        self.clock = clock
        self.registry = MetricsRegistry()
        self.tracer = Tracer(self.registry, peer_id, clock, enabled=enabled)
        self.recorder = FlightRecorder(peer_id, clock, enabled=enabled)
        # Training-health layer (swarm/health.py): sketches, mass
        # accounting, contribution quality, codec distortion. Gated
        # independently (--no-health-probe disables the sketch/tally work
        # while the rest of the plane stays on); --no-telemetry disables
        # both. The object always exists so call sites stay branch-free.
        from distributedvolunteercomputing_tpu.swarm import health as health_mod

        if health_enabled is None:
            health_enabled = enabled
        self.health = health_mod.HealthMonitor(
            self.registry, self.recorder, peer_id,
            enabled=bool(enabled and health_enabled), clock=clock,
        )
        # Watchdog layer (swarm/watchdog.py): streaming anomaly detectors
        # over the plane's own series. Gated independently the same way
        # (--no-watchdog keeps tracing/health on but ships no alert
        # bytes); --no-telemetry disables everything. Always constructed
        # so call sites stay branch-free.
        from distributedvolunteercomputing_tpu.swarm import watchdog as watchdog_mod

        if watchdog_enabled is None:
            watchdog_enabled = enabled
        self.watchdog = watchdog_mod.Watchdog(
            self.registry, self.recorder, peer_id,
            enabled=bool(enabled and watchdog_enabled), clock=clock,
        )
        if self.watchdog.enabled:
            # Ended round spans feed the per-level wall detectors.
            self.tracer.on_record = self.watchdog.observe_span

    def set_clock(self, clock: Callable[[], float]) -> None:
        """Adopt the ClockSync-corrected clock once the volunteer builds
        one (the averager/membership may construct telemetry earlier)."""
        self.clock = clock
        self.tracer._clock = clock
        self.recorder._clock = clock
        self.health.clock = clock
        self.watchdog.clock = clock

    # -- hot-path shorthands (None/no-op when disabled) ---------------------

    def span(self, name: str, trace: Optional[str] = None, **attrs: Any):
        return self.tracer.span(name, trace, **attrs)

    def event(self, kind: str, **fields: Any) -> None:
        self.recorder.record(kind, **fields)

    # -- RPC surface ---------------------------------------------------------

    def register_rpcs(self, transport) -> None:
        """Expose scrape/trace/flight over the swarm transport (debug +
        collection surface; trace_report and operators dial these)."""

        async def _scrape(args: dict, payload: bytes):
            return self.scrape(), b""

        async def _trace(args: dict, payload: bytes):
            return {
                "schema_version": TELEMETRY_SCHEMA_VERSION,
                "peer": self.peer_id,
                "spans": self.tracer.spans(
                    trace=args.get("trace") or None,
                    since=float(args.get("since") or 0.0),
                ),
            }, b""

        async def _flight(args: dict, payload: bytes):
            since_seq = args.get("since_seq")
            # Cursor read BEFORE the dump: an event recorded (from a
            # trainer/averager thread) between the two reads must show up
            # in the NEXT poll, not vanish — at-least-once duplication is
            # fine for a poller, a silently dropped event is not.
            next_seq = self.recorder.next_seq
            return {
                "schema_version": TELEMETRY_SCHEMA_VERSION,
                "peer": self.peer_id,
                "events": self.recorder.dump(
                    since=float(args.get("since") or 0.0),
                    kinds=args.get("kinds") or None,
                    since_seq=int(since_seq) if since_seq is not None else None,
                ),
                # Incremental cursor: pass back as since_seq next poll and
                # repeated dumps ship only new events, not the whole ring.
                "next_seq": next_seq,
            }, b""

        async def _prom(args: dict, payload: bytes):
            # Prometheus text exposition of the whole registry: any stock
            # scraper (or the --metrics-port HTTP shim) can watch this
            # volunteer without the coordinator.
            text = render_prom(self.registry.scrape())
            return {
                "peer": self.peer_id,
                "content_type": PROM_CONTENT_TYPE,
            }, text.encode()

        transport.register(SCRAPE_METHOD, _scrape)
        transport.register(TRACE_METHOD, _trace)
        transport.register(FLIGHT_METHOD, _flight)
        transport.register(PROM_METHOD, _prom)

    def scrape(self) -> dict:
        out = self.registry.scrape()
        out["peer"] = self.peer_id
        out["enabled"] = self.enabled
        # Training-health view (None when the probe is disabled): summary
        # plus the bounded sketch history — what trace_report matches
        # across peers by trace id for the per-round mixing-error column.
        out["health"] = self.health.scrape()
        # Watchdog view (None when disabled): the firing alert set plus
        # lifetime raise/clear totals and per-level wall histograms.
        out["watchdog"] = self.watchdog.summary()
        return out

    # -- report summary (rides the cp.exchange beat) -------------------------

    # Span-histogram names summarized into every report: the per-phase
    # latency evidence coord.status rolls up without shipping whole scrapes
    # every beat.
    SUMMARY_SPANS = (
        "round", "join", "encode", "wire", "fold", "commit", "health",
        "fetch", "recover",
    )

    def summary(self) -> dict:
        """Compact per-beat telemetry summary for the volunteer report:
        schema version, flight-recorder high-water, and per-span
        count/sum pairs (enough for rate + mean-latency rollups without
        shipping buckets every heartbeat)."""
        spans: Dict[str, dict] = {}
        hist = self.registry.histogram("swarm.span_seconds")
        for name in self.SUMMARY_SPANS:
            snap = hist.snapshot(span=name)
            if snap is not None:
                spans[name] = {
                    "count": snap["count"],
                    "sum_s": round(snap["sum"], 6),
                }
        return {
            "schema_version": TELEMETRY_SCHEMA_VERSION,
            "enabled": self.enabled,
            "events_recorded": self.recorder._seq,
            "spans": spans,
        }


# -- coord.status rollup -----------------------------------------------------

# The documented coord.status["telemetry"] schema, keyed by dotted path.
# Every entry must be present (None allowed only where marked) and typed
# as stated — tests/test_telemetry.py::test_status_telemetry_schema walks
# this table against a live rollup, so drift breaks CI instead of
# dashboards. per-peer / per-span maps are typed by their VALUE schema.
STATUS_TELEMETRY_SCHEMA: Dict[str, type] = {
    "schema_version": int,
    "reporting": int,          # volunteers whose fresh report carried telemetry
    "events_recorded_total": int,
    "spans": dict,             # span name -> {count, sum_s, mean_s}
    "per_peer": dict,          # peer id -> its report summary (verbatim)
    # v2: how many fresh reports also carried a training-health summary
    # (the full health rollup lives at coord.status["health"], pinned by
    # health.STATUS_HEALTH_SCHEMA).
    "health_reporting": int,
    # v3: staleness stamp — seconds since the FRESHEST contributing report
    # landed, stamped by the serving replica on the telemetry clock. A
    # frozen replica serves a growing age_s; a healthy quiet swarm serves
    # a small one. (Stamped at serve time, so rollup_status() output only
    # carries it after the replica's status path adds it.)
    "age_s": float,
}
STATUS_SPAN_SCHEMA: Dict[str, type] = {
    "count": int,
    "sum_s": float,
    "mean_s": float,
}


def rollup_status(fresh_reports: List[dict]) -> Optional[dict]:
    """Merge per-volunteer telemetry summaries (from fresh reports) into
    the versioned coord.status rollup. None until some volunteer reports
    telemetry — same contract as the multigroup rollup."""
    per_peer: Dict[str, dict] = {}
    for m in fresh_reports:
        t = m.get("telemetry")
        if isinstance(t, dict) and t.get("schema_version") == TELEMETRY_SCHEMA_VERSION:
            per_peer[str(m.get("peer", "?"))] = t
    if not per_peer:
        return None
    spans: Dict[str, dict] = {}
    for t in per_peer.values():
        for name, rec in (t.get("spans") or {}).items():
            agg = spans.setdefault(str(name), {"count": 0, "sum_s": 0.0})
            agg["count"] += int(rec.get("count") or 0)
            agg["sum_s"] += float(rec.get("sum_s") or 0.0)
    for agg in spans.values():
        agg["sum_s"] = round(agg["sum_s"], 6)
        agg["mean_s"] = round(agg["sum_s"] / agg["count"], 6) if agg["count"] else 0.0
    return {
        "schema_version": TELEMETRY_SCHEMA_VERSION,
        "reporting": len(per_peer),
        "events_recorded_total": sum(
            int(t.get("events_recorded") or 0) for t in per_peer.values()
        ),
        "spans": spans,
        "per_peer": per_peer,
        "health_reporting": sum(
            1 for m in fresh_reports if isinstance(m.get("health"), dict)
        ),
    }


# -- Prometheus text exposition ----------------------------------------------

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_PROM_NAME_RE = None


def _prom_name(name: str) -> str:
    global _PROM_NAME_RE
    if _PROM_NAME_RE is None:
        import re

        _PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
    out = _PROM_NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
        parts.append(f'{_prom_name(str(k))}="{v}"')
    return "{" + ",".join(parts) + "}"


def render_prom(scrape: dict) -> str:
    """Render a registry scrape (:meth:`MetricsRegistry.scrape`) in the
    Prometheus text exposition format, so any stock scraper can watch a
    volunteer directly — no coordinator, no custom client. Dotted names
    sanitize to underscores; histograms emit the standard cumulative
    ``_bucket``/``_sum``/``_count`` triple over the shared log2 bounds."""
    lines: List[str] = []
    for name, m in sorted((scrape.get("metrics") or {}).items()):
        pname = _prom_name(name)
        mtype = m.get("type")
        if mtype == "counter":
            lines.append(f"# TYPE {pname} counter")
            for v in m.get("values") or []:
                lines.append(
                    f"{pname}{_prom_label_str(v.get('labels') or {})} "
                    f"{float(v['value']):g}"
                )
        elif mtype == "gauge":
            lines.append(f"# TYPE {pname} gauge")
            for v in m.get("values") or []:
                lines.append(
                    f"{pname}{_prom_label_str(v.get('labels') or {})} "
                    f"{float(v['value']):g}"
                )
        elif mtype == "histogram":
            lines.append(f"# TYPE {pname} histogram")
            bounds = m.get("bucket_bounds") or list(HIST_BUCKETS)
            for v in m.get("values") or []:
                labels = dict(v.get("labels") or {})
                acc = 0
                for ub, c in zip(bounds, v.get("buckets") or []):
                    acc += int(c)
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_label_str({**labels, 'le': f'{ub:g}'})} {acc}"
                    )
                acc += int((v.get("buckets") or [0])[-1])
                lines.append(
                    f"{pname}_bucket"
                    f"{_prom_label_str({**labels, 'le': '+Inf'})} "
                    f"{int(v.get('count') or acc)}"
                )
                lines.append(
                    f"{pname}_sum{_prom_label_str(labels)} "
                    f"{float(v.get('sum') or 0.0):g}"
                )
                lines.append(
                    f"{pname}_count{_prom_label_str(labels)} "
                    f"{int(v.get('count') or 0)}"
                )
    return "\n".join(lines) + "\n"


class MetricsHTTPServer:
    """Minimal local HTTP shim serving ``GET /metrics`` in Prometheus text
    format (the ``--metrics-port`` toggle): hand-rolled over asyncio
    streams — no HTTP dependency — because the only consumers are stock
    scrapers doing one GET per interval. Binds the volunteer's host; port
    0 picks an ephemeral port (returned from :meth:`start`)."""

    def __init__(self, telemetry: "Telemetry", host: str = "127.0.0.1", port: int = 0):
        self.telemetry = telemetry
        self.host = host
        self.port = int(port)
        self._server = None

    async def start(self) -> Tuple[str, int]:
        import asyncio

        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("metrics endpoint on http://%s:%d/metrics", self.host, self.port)
        return self.host, self.port

    async def _handle(self, reader, writer) -> None:
        try:
            request = await reader.readline()
            # Drain headers (bounded) so keep-alive clients see a clean close.
            for _ in range(64):
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request.decode("latin-1", "replace").split()
            path = parts[1] if len(parts) >= 2 else ""
            if parts[:1] == ["GET"] and path.split("?")[0] in ("/metrics", "/"):
                body = render_prom(self.telemetry.registry.scrape()).encode()
                head = (
                    "HTTP/1.0 200 OK\r\n"
                    f"Content-Type: {PROM_CONTENT_TYPE}\r\n"
                    f"Content-Length: {len(body)}\r\n\r\n"
                ).encode()
            else:
                body = b"watchdog: only /metrics lives here\n"
                head = (
                    "HTTP/1.0 404 Not Found\r\n"
                    f"Content-Length: {len(body)}\r\n\r\n"
                ).encode()
            writer.write(head + body)
            await writer.drain()
        except Exception as e:  # noqa: BLE001 — a broken scraper must not log-spam
            log.debug("metrics request failed: %s", errstr(e))
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
