"""Training-health telemetry: live mixing error, gradient-mass accounting,
per-peer contribution quality, and codec distortion.

PR 10's telemetry plane answers *where the wall-clock goes*; this module
answers *whether the learning is healthy*. Four signals, all riding the
existing surfaces (metrics registry, flight recorder, the ``cp.exchange``
report beat — zero new RPC types):

- **Live mixing error** (:func:`params_sketch` / :func:`sketch_dispersion`):
  every peer folds a seeded random-projection sketch of its POST-ROUND
  parameters into its report. The projection is a blocked
  Johnson-Lindenstrauss map over a seeded coordinate subsample — the seed
  is swarm-constant (derived from the averaging namespace), so every
  peer's sketch lives in the SAME k-dim space and cross-peer sketch
  distances estimate cross-peer parameter distances (relative error
  ~1/sqrt(2k) per pair). Control-plane replicas compute cross-peer sketch
  dispersion per zone into ``coord.status["health"]["mixing"]`` — the
  hierarchy bench's offline "equal mixing error" criterion, watched live.

- **Gradient-mass accounting**: every committed round classifies each
  armed peer's declared weight into exactly one of included / excluded /
  aborted (``StreamingAggregator.mass_report`` on streaming rounds,
  :func:`mass_from_outcomes` on dense ones), so included + excluded +
  aborted == total armed weight BY CONSTRUCTION and the cost of
  deadline-dropping stragglers is a first-class metric
  (``swarm.health.mass_committed_frac``). A silent peer's undelivered
  weight is unknowable to the leader and counts 0 toward the balance —
  it still counts as one excluded SLOT.

- **Per-peer contribution quality** (:class:`HealthMonitor`): the window
  folds and dense stacks already hold per-peer rows next to the robust
  aggregate; a row whose squared distance to the aggregate exceeds
  ``OUTLIER_FACTOR²`` x the median row's is an outlier vote. Votes decay
  into a per-peer flag rate; a peer whose rate crosses FLAG_RATE after
  FLAG_MIN_ROUNDS observations is FLAGGED — ``peer_quality_flagged`` in
  the flight recorder, the quality map in the report, and (via the
  averager's hook) a ``health_flagged`` field in the membership record.
  Quality needs per-peer rows, so it covers the robust estimators
  (window/d2_dense/dense tile modes and the byzantine full mesh); a
  ``mean`` swarm first escalates via the resilience ladder.

- **Codec distortion**: per-round relative compression error per wire
  format — the EF-residual norm over the gradient norm on the lossy
  wires (topk/powersgd/sign, exactly the mass error feedback re-stages),
  a sampled round-trip estimate on bf16/q8, and 0 on f32. The raw
  material for ranking wire formats by convergence-per-byte (ROADMAP
  item 1).

Everything here follows the telemetry plane's contract: advisory and
bounded — record paths swallow their own exceptions, per-peer maps are
capped, and a disabled monitor (``--no-telemetry`` / ``--no-health-probe``)
turns every call into a no-op and ships NO sketch bytes on the heartbeat.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

# Version stamp carried by every health summary and the coord.status
# rollup (independent of TELEMETRY_SCHEMA_VERSION: the two surfaces can
# evolve separately; both are CI-pinned).
HEALTH_SCHEMA_VERSION = 1

# Sketch geometry. dim = projected dimensionality (the sketch is dim f32
# values, 256 B at 64 — "few KB" with history); sample = max coordinates
# fed to the projection (a seeded with-replacement subsample when the
# model is bigger, an unbiased dispersion estimator); block = projection
# matrix tile (cached per seed, so steady-state sketches are one small
# matmul, not fresh Gaussian generation).
DEFAULT_SKETCH_DIM = 64
DEFAULT_SKETCH_SAMPLE = 32_768
_SKETCH_BLOCK = 8_192

# Cached projection blocks keyed by (seed, dim, block_index) and cached
# subsample indices keyed by (seed, n_elems, sample): the seed is
# swarm-constant, so these are computed once per process, not per round.
_PROJ_CACHE: Dict[Tuple[int, int, int], np.ndarray] = {}
_IDX_CACHE: Dict[Tuple[int, int, int], np.ndarray] = {}
_CACHE_LOCK = threading.Lock()
_PROJ_CACHE_MAX = 16
_IDX_CACHE_MAX = 8


def sketch_seed(namespace: str = "") -> int:
    """Swarm-constant sketch seed: every peer averaging the same namespace
    derives the same projection, which is what makes sketches comparable
    across the swarm without negotiating anything on the wire."""
    return zlib.crc32(f"dvc-health/{namespace}".encode()) & 0x7FFFFFFF


def _proj_block(seed: int, dim: int, block_idx: int, rows: int) -> np.ndarray:
    key = (seed, dim, block_idx)
    with _CACHE_LOCK:
        r = _PROJ_CACHE.get(key)
    if r is None or r.shape[0] < rows:
        rng = np.random.default_rng((seed, dim, block_idx))
        r = rng.standard_normal((_SKETCH_BLOCK, dim)).astype(np.float32)
        with _CACHE_LOCK:
            if len(_PROJ_CACHE) >= _PROJ_CACHE_MAX:
                _PROJ_CACHE.clear()
            _PROJ_CACHE[key] = r
    return r[:rows]


def _sample_idx(seed: int, n: int, sample: int) -> np.ndarray:
    key = (seed, n, sample)
    with _CACHE_LOCK:
        idx = _IDX_CACHE.get(key)
    if idx is None:
        # With-replacement: O(sample) regardless of n, deterministic per
        # (seed, n) — every peer picks the SAME coordinates.
        idx = np.random.default_rng((seed, n)).integers(0, n, size=sample)
        with _CACHE_LOCK:
            if len(_IDX_CACHE) >= _IDX_CACHE_MAX:
                _IDX_CACHE.clear()
            _IDX_CACHE[key] = idx
    return idx


def params_sketch(
    buf: np.ndarray,
    seed: int,
    dim: int = DEFAULT_SKETCH_DIM,
    sample: int = DEFAULT_SKETCH_SAMPLE,
) -> np.ndarray:
    """Seeded random-projection sketch of a flat f32 parameter buffer.

    ``sketch = x_sel @ R / sqrt(dim)`` where ``x_sel`` is a seeded
    coordinate subsample (all coordinates when the buffer is small) and
    ``R`` is a blocked seeded Gaussian matrix — the classic JL map, so
    for two peers sharing a seed ``||s_a - s_b|| ~= ||x_a_sel - x_b_sel||``
    with relative error ~1/sqrt(2·dim). Deterministic: same (buf, seed,
    dim, sample) always yields the same sketch on every peer."""
    x = np.ascontiguousarray(buf, np.float32).ravel()
    if x.size > sample:
        x = x[_sample_idx(seed, x.size, sample)]
    out = np.zeros(dim, np.float64)
    for bi, e0 in enumerate(range(0, x.size, _SKETCH_BLOCK)):
        chunk = x[e0 : e0 + _SKETCH_BLOCK]
        out += chunk.astype(np.float64) @ _proj_block(seed, dim, bi, chunk.size)
    return (out / np.sqrt(float(dim))).astype(np.float32)


def sketch_dispersion(sketches: List[np.ndarray]) -> Optional[dict]:
    """Cross-peer dispersion of a set of same-space sketches: the live
    mixing-error estimate.

    ``rms`` = root-mean-square deviation from the sketch mean (same units
    as the sketched values); ``rel`` = rms normalized by the RMS sketch
    norm — scale-free, directly comparable to a relative parameter
    dispersion computed offline (hierarchy_bench-style), and ~0 when all
    peers hold (numerically) equal parameters."""
    vs = [np.asarray(s, np.float64).ravel() for s in sketches if s is not None]
    if len(vs) < 2 or len({v.size for v in vs}) != 1:
        return None
    stack = np.stack(vs)
    mean = stack.mean(axis=0)
    dev = stack - mean[None, :]
    rms = float(np.sqrt((dev * dev).sum(axis=1).mean()))
    norm = float(np.sqrt((stack * stack).sum(axis=1).mean()))
    return {
        "n": len(vs),
        "rms": round(rms, 9),
        "rel": round(rms / norm, 9) if norm > 0 else 0.0,
    }


def row_d2(stack: np.ndarray, agg: np.ndarray) -> np.ndarray:
    """Per-row squared L2 distance to the aggregate, in float64 — THE
    contribution-quality attribution metric, shared by every vantage that
    holds rows next to a robust aggregate (window tile folds, the dense
    finalize paths, the sync leader's dense branch, the byzantine full
    mesh) so the metric can never silently diverge between them.

    Row-at-a-time: the dense call sites hold param-scale [n, D] stacks,
    and a whole-stack float64 upcast would transiently double-plus the
    round's resident memory; one O(D) f64 deviation per row accumulates
    to the same values."""
    agg64 = np.asarray(agg, np.float64).ravel()
    out = np.empty(stack.shape[0], np.float64)
    for i in range(stack.shape[0]):
        dev = np.asarray(stack[i], np.float64).ravel() - agg64
        out[i] = float(dev @ dev)
    return out


def mass_from_outcomes(
    expected: Iterable[str],
    included_w: Dict[str, float],
    aborted: Iterable[str] = (),
) -> dict:
    """Mass report for a DENSE (non-streaming) round, from what the
    aggregating vantage knows: arrived contributions carry their declared
    weight; an expected peer that never delivered counts one excluded
    slot at weight 0 (its undelivered mass is unknowable here)."""
    aborted = set(aborted)
    per_peer: Dict[str, dict] = {}
    for p in expected:
        if p in included_w:
            per_peer[p] = {"outcome": "included", "weight": float(included_w[p])}
        elif p in aborted:
            per_peer[p] = {"outcome": "aborted", "weight": 0.0}
        else:
            per_peer[p] = {"outcome": "excluded", "weight": 0.0}
    return mass_report_from_per_peer(per_peer)


def mass_report_from_per_peer(per_peer: Dict[str, dict]) -> dict:
    """Fold a per-peer outcome/weight classification into the balanced
    mass report (each peer in exactly one bucket, so the weights sum by
    construction — the property test's invariant). ``recovered`` is the
    tail-optimal pipeline's bucket: mass that COMMITTED, but only because
    hedged re-requests / summand redundancy finished a straggling
    contribution — split from ``included`` so the hedger's win is
    auditable per round while both count toward the committed fraction."""
    sums = {"included": 0.0, "recovered": 0.0, "excluded": 0.0, "aborted": 0.0}
    counts = {"included": 0, "recovered": 0, "excluded": 0, "aborted": 0}
    for rec in per_peer.values():
        oc = rec["outcome"]
        sums[oc] += float(rec["weight"])
        counts[oc] += 1
    armed_w = sum(sums.values())
    committed_w = sums["included"] + sums["recovered"]
    committed_n = counts["included"] + counts["recovered"]
    n = len(per_peer)
    if armed_w > 0:
        frac = committed_w / armed_w
    elif n:
        frac = committed_n / n
    else:
        frac = 1.0
    # Round the buckets first and report their EXACT sum as armed_weight:
    # independently-rounded buckets against an independently-rounded
    # total could miss the balance invariant by ~2e-6, which is exactly
    # what the property tests and the chaos verdict assert against.
    rounded = {oc: round(sums[oc], 6) for oc in sums}
    return {
        "armed_slots": n,
        "armed_weight": round(sum(rounded.values()), 6),
        "included_slots": counts["included"],
        "included_weight": rounded["included"],
        "recovered_slots": counts["recovered"],
        "recovered_weight": rounded["recovered"],
        "excluded_slots": counts["excluded"],
        "excluded_weight": rounded["excluded"],
        "aborted_slots": counts["aborted"],
        "aborted_weight": rounded["aborted"],
        "mass_committed_frac": round(frac, 6),
        # The slot view alongside the weight view: a SILENT peer's
        # undelivered weight is unknowable (counts 0 above), so the slot
        # fraction is what shows a deadline-dropped straggler's cost when
        # its push never declared a weight at all.
        "slot_committed_frac": round(committed_n / n, 6) if n else 1.0,
        "per_peer": per_peer,
    }


def mass_by_shard(report: dict) -> Dict[str, dict]:
    """Roll a balanced mass report up per shard domain (zone-sharded
    training): per_peer entries carrying a ``shard`` tag bucket under
    ``"s<k>"``, untagged entries under ``"~"``. Each sub-report is itself
    balanced (same rounding rule as the parent), so a shard-holder death
    reads as ONE shard's committed fraction dipping while the others hold
    at 1.0 — the signal the ``shard_zone_degraded`` doctor rule and the
    campaign verdict consume. An unsharded round returns a single ``"~"``
    bucket equal to the parent report."""
    groups: Dict[str, Dict[str, dict]] = {}
    for pid, rec in (report.get("per_peer") or {}).items():
        s = rec.get("shard")
        tag = f"s{int(s)}" if isinstance(s, int) and not isinstance(s, bool) else "~"
        groups.setdefault(tag, {})[pid] = rec
    return {tag: mass_report_from_per_peer(pp) for tag, pp in sorted(groups.items())}


class HealthMonitor:
    """Per-volunteer training-health state: quality, mass, sketch, codec.

    One per telemetry bundle (``Telemetry.health``), shared by the
    averager and the streaming aggregator. All record paths are advisory:
    they must never fail a round, so they swallow their own exceptions;
    a disabled monitor no-ops everything and ``summary()`` returns None —
    the report beat then carries no health bytes at all."""

    MAX_PEERS = 256
    MAX_SKETCH_HISTORY = 32
    # Quality flagging: a row whose squared distance to the robust
    # aggregate exceeds OUTLIER_FACTOR² x the (floored) median row's is
    # one outlier vote; votes EWMA into a flag rate, and a peer crosses
    # into FLAGGED at rate >= FLAG_RATE after >= FLAG_MIN_ROUNDS
    # observations (unflagged again once the rate decays under
    # UNFLAG_RATE — persistent, not one unlucky round).
    OUTLIER_FACTOR = 3.0
    FLAG_MIN_ROUNDS = 3
    FLAG_RATE = 0.5
    UNFLAG_RATE = 0.2
    QUALITY_ALPHA = 0.25
    # Absolute floor on the outlier threshold (squared distance): a round
    # where every row sits within numeric noise of the aggregate (the
    # all-equal degenerate case) must flag nobody — relative rules alone
    # would amplify 1e-12-scale jitter into votes.
    D2_FLOOR = 1e-9

    def __init__(
        self,
        registry,
        recorder=None,
        peer_id: str = "",
        enabled: bool = True,
        clock: Callable[[], float] = time.time,
        sketch_dim: int = DEFAULT_SKETCH_DIM,
        sketch_sample: int = DEFAULT_SKETCH_SAMPLE,
    ):
        self.registry = registry
        self.recorder = recorder
        self.peer_id = peer_id
        self.enabled = enabled
        self.clock = clock
        self.sketch_dim = int(sketch_dim)
        self.sketch_sample = int(sketch_sample)
        self.seed = sketch_seed("")
        # Zone advertised in the health summary (the rollup's per-zone
        # dispersion join key); the averager wires its zone property in.
        self.zone_fn: Optional[Callable[[], str]] = None
        # Called with the sorted flagged-peer list on every flag-set
        # change (the averager surfaces it into the membership record).
        self.on_flag: Optional[Callable[[List[str]], None]] = None
        self._lock = threading.Lock()
        # peer -> {rounds, outlier_rounds, rate (EWMA), flagged}
        self._quality: Dict[str, dict] = {}
        self._flagged: set = set()
        self._lost_mass: Dict[str, float] = {}
        self._sketches: "deque[dict]" = deque(maxlen=self.MAX_SKETCH_HISTORY)
        self._last_mass: Optional[dict] = None
        self._codec: Dict[str, dict] = {}
        self.rounds_observed = 0
        # Committed-round mass reports folded (the watchdog's "is there a
        # NEW mass observation this tick" cursor).
        self.mass_rounds = 0
        self.sketches_computed = 0
        if enabled and registry is not None:
            self._mass_gauge = registry.gauge(
                "swarm.health.mass_committed_frac",
                "fraction of armed gradient mass committed last round",
            )
            self._mass_ctr = registry.counter(
                "swarm.health.mass_weight_total",
                "cumulative armed weight by round outcome",
            )
            self._sketch_ctr = registry.counter(
                "swarm.health.sketches_total", "post-round parameter sketches"
            )
            self._flag_ctr = registry.counter(
                "swarm.health.quality_flags_total",
                "peers newly flagged by the contribution-quality score",
            )
            self._codec_gauge = registry.gauge(
                "swarm.health.codec_rel_err",
                "relative compression error by wire format",
            )
        else:
            self._mass_gauge = self._mass_ctr = None
            self._sketch_ctr = self._flag_ctr = self._codec_gauge = None

    def configure(self, namespace: str = "") -> None:
        """Adopt the swarm-constant sketch seed for this averaging
        namespace (every peer in a namespace projects identically)."""
        self.seed = sketch_seed(namespace)

    def _event(self, kind: str, **fields: Any) -> None:
        if self.recorder is not None:
            try:
                self.recorder.record(kind, **fields)
            except Exception:  # noqa: BLE001 — recording must not affect the caller
                pass

    # -- contribution quality ------------------------------------------------

    def observe_round_quality(
        self, d2_by_peer: Dict[str, float], *, trace: Optional[str] = None
    ) -> None:
        """One aggregated round's per-peer squared distances to the robust
        aggregate. Outlier votes are RELATIVE (vs the floored median row),
        so honest heterogeneity — every row somewhat off-center — votes
        nobody, while a scaled/garbage contributor votes itself every
        round."""
        if not self.enabled or len(d2_by_peer) < 3:
            return
        try:
            vals = np.array(list(d2_by_peer.values()), np.float64)
            med = float(np.median(vals))
            # Floor against degenerate all-(near-)equal rounds: med 0 must
            # not flag every row with any numeric noise.
            base = max(med, 0.01 * float(vals.mean()), 0.0)
            thr = max((self.OUTLIER_FACTOR ** 2) * base, self.D2_FLOOR)
            changed = False
            with self._lock:
                self.rounds_observed += 1
                for peer, d2 in d2_by_peer.items():
                    st = self._quality.get(peer)
                    if st is None:
                        if len(self._quality) >= self.MAX_PEERS:
                            continue
                        st = self._quality[peer] = {
                            "rounds": 0, "outlier_rounds": 0, "rate": 0.0,
                            "flagged": False,
                        }
                    outlier = bool(thr > 0 and float(d2) > thr)
                    st["rounds"] += 1
                    st["outlier_rounds"] += int(outlier)
                    a = self.QUALITY_ALPHA
                    st["rate"] = (1 - a) * st["rate"] + a * float(outlier)
                    if (
                        not st["flagged"]
                        and st["rounds"] >= self.FLAG_MIN_ROUNDS
                        and st["rate"] >= self.FLAG_RATE
                    ):
                        st["flagged"] = True
                        self._flagged.add(peer)
                        changed = True
                        if self._flag_ctr is not None:
                            self._flag_ctr.inc()
                        self._event(
                            "peer_quality_flagged",
                            peer=peer,
                            score=round(1.0 - st["rate"], 4),
                            flag_rate=round(st["rate"], 4),
                            rounds=st["rounds"],
                            trace=trace,
                        )
                    elif st["flagged"] and st["rate"] <= self.UNFLAG_RATE:
                        st["flagged"] = False
                        self._flagged.discard(peer)
                        changed = True
                flagged = sorted(self._flagged)
            if changed and self.on_flag is not None:
                try:
                    self.on_flag(flagged)
                except Exception as e:  # noqa: BLE001 — surfacing is advisory
                    log.debug("health flag hook failed: %s", errstr(e))
        except Exception as e:  # noqa: BLE001 — health must never fail a round
            log.debug("quality observation failed: %s", errstr(e))

    def quality_score(self, peer: str) -> float:
        """1.0 = never voted an outlier; 0.0 = outlier every recent round."""
        with self._lock:
            st = self._quality.get(peer)
            return 1.0 if st is None else round(1.0 - st["rate"], 4)

    def flagged_peers(self) -> List[str]:
        with self._lock:
            return sorted(self._flagged)

    # -- gradient-mass accounting -------------------------------------------

    def note_round_mass(self, report: dict, *, trace: Optional[str] = None) -> None:
        """One committed round's balanced mass report (see module doc)."""
        if not self.enabled or not report:
            return
        try:
            lost_w = float(report.get("excluded_weight", 0.0)) + float(
                report.get("aborted_weight", 0.0)
            )
            lost_slots = int(report.get("excluded_slots", 0)) + int(
                report.get("aborted_slots", 0)
            )
            with self._lock:
                self.mass_rounds += 1
                self._last_mass = {
                    k: report[k] for k in report if k != "per_peer"
                }
                # Per-shard rollup (zone-sharded training): only when some
                # slot carries a shard tag — unsharded rounds add nothing.
                if any(
                    "shard" in (rec or {})
                    for rec in (report.get("per_peer") or {}).values()
                ):
                    self._last_mass["by_shard"] = {
                        tag: {
                            "armed_weight": sub["armed_weight"],
                            "mass_committed_frac": sub["mass_committed_frac"],
                        }
                        for tag, sub in mass_by_shard(report).items()
                    }
                for pid, rec in (report.get("per_peer") or {}).items():
                    if rec.get("outcome") in ("excluded", "aborted"):
                        if pid not in self._lost_mass and len(
                            self._lost_mass
                        ) >= self.MAX_PEERS:
                            continue
                        self._lost_mass[pid] = self._lost_mass.get(pid, 0.0) + float(
                            rec.get("weight") or 0.0
                        )
            if self._mass_gauge is not None:
                self._mass_gauge.set(float(report.get("mass_committed_frac", 1.0)))
                for oc in ("included", "recovered", "excluded", "aborted"):
                    w = float(report.get(f"{oc}_weight", 0.0))
                    if w:
                        self._mass_ctr.inc(w, outcome=oc)
            rec_slots = int(report.get("recovered_slots", 0))
            if rec_slots:
                # The hedger's auditable win: mass that would have been
                # lost at the deadline, committed anyway. The doctor's
                # straggler rule demotes itself on this evidence.
                self._event(
                    "mass_recovered_by_hedge",
                    trace=trace,
                    recovered_weight=report.get("recovered_weight"),
                    recovered_slots=rec_slots,
                    recovered=sorted(
                        p for p, r in (report.get("per_peer") or {}).items()
                        if r.get("outcome") == "recovered"
                    ),
                    mass_committed_frac=report.get("mass_committed_frac"),
                )
            if lost_slots:
                self._event(
                    "mass_lost_at_deadline",
                    trace=trace,
                    lost_weight=round(lost_w, 6),
                    lost_slots=lost_slots,
                    mass_committed_frac=report.get("mass_committed_frac"),
                    slot_committed_frac=report.get("slot_committed_frac"),
                    recovered_weight=report.get("recovered_weight", 0.0),
                    recovered_slots=report.get("recovered_slots", 0),
                    excluded=sorted(
                        p for p, r in (report.get("per_peer") or {}).items()
                        if r.get("outcome") == "excluded"
                    ),
                    aborted=sorted(
                        p for p, r in (report.get("per_peer") or {}).items()
                        if r.get("outcome") == "aborted"
                    ),
                )
        except Exception as e:  # noqa: BLE001
            log.debug("mass accounting failed: %s", errstr(e))

    # -- mixing-error sketch -------------------------------------------------

    def note_sketch(self, buf: np.ndarray, *, trace: Optional[str] = None) -> None:
        """Sketch the post-round parameters (the committed aggregate this
        peer adopted). Called off the event loop — the projection is a
        few small matmuls against cached blocks (~ms)."""
        if not self.enabled:
            return
        try:
            sk = params_sketch(buf, self.seed, self.sketch_dim, self.sketch_sample)
            rec = {
                "trace": trace,
                "t": round(self.clock(), 6),
                "dim": self.sketch_dim,
                "seed": self.seed,
                "v": [round(float(x), 6) for x in sk],
            }
            with self._lock:
                self._sketches.append(rec)
                self.sketches_computed += 1
            if self._sketch_ctr is not None:
                self._sketch_ctr.inc()
        except Exception as e:  # noqa: BLE001
            log.debug("sketch failed: %s", errstr(e))

    def last_sketch(self) -> Optional[dict]:
        with self._lock:
            return dict(self._sketches[-1]) if self._sketches else None

    def sketch_history(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._sketches]

    # -- codec distortion ----------------------------------------------------

    def note_codec_error(self, wire: str, rel_err: float) -> None:
        """Per-round relative compression error for ``wire`` (EF residual
        norm / gradient norm on the lossy wires)."""
        if not self.enabled:
            return
        try:
            rel = float(rel_err)
            with self._lock:
                rec = self._codec.get(wire)
                if rec is None:
                    rec = self._codec[wire] = {"last": rel, "ewma": rel, "rounds": 0}
                a = 0.2
                rec["last"] = rel
                rec["ewma"] = (1 - a) * rec["ewma"] + a * rel
                rec["rounds"] += 1
            if self._codec_gauge is not None:
                self._codec_gauge.set(rel, wire=wire)
        except Exception as e:  # noqa: BLE001
            log.debug("codec error gauge failed: %s", errstr(e))

    # -- report summary ------------------------------------------------------

    MAX_REPORTED_PEERS = 16

    def summary(self) -> Optional[dict]:
        """Compact health summary for the volunteer report (rides the
        batched ``cp.exchange`` beat). None when disabled — the heartbeat
        then carries no sketch bytes at all (the --no-health-probe test's
        contract)."""
        if not self.enabled:
            return None
        with self._lock:
            # Worst-quality peers first; bounded so the beat stays small.
            worst = sorted(
                self._quality.items(), key=lambda kv: -kv[1]["rate"]
            )[: self.MAX_REPORTED_PEERS]
            lost_top = dict(
                sorted(self._lost_mass.items(), key=lambda kv: -kv[1])[
                    : self.MAX_REPORTED_PEERS
                ]
            )
            return {
                "schema_version": HEALTH_SCHEMA_VERSION,
                "zone": str(self.zone_fn() if self.zone_fn is not None else ""),
                "rounds_observed": self.rounds_observed,
                "mass": {
                    "last": dict(self._last_mass) if self._last_mass else None,
                    "lost_by_peer": {
                        p: round(w, 6) for p, w in lost_top.items()
                    },
                },
                "quality": {
                    p: {
                        "score": round(1.0 - st["rate"], 4),
                        "rounds": st["rounds"],
                        "flagged": st["flagged"],
                    }
                    for p, st in worst
                },
                "flagged": sorted(self._flagged),
                "codec": {
                    w: {
                        "rel_err_last": round(rec["last"], 8),
                        "rel_err_ewma": round(rec["ewma"], 8),
                    }
                    for w, rec in self._codec.items()
                },
                "sketch": dict(self._sketches[-1]) if self._sketches else None,
            }

    def scrape(self) -> Optional[dict]:
        """The debug/collection view (rides ``telemetry.scrape``): the
        summary plus the bounded sketch HISTORY, which is what lets
        trace_report compute a per-round mixing-error column by matching
        sketches across peers by trace id."""
        out = self.summary()
        if out is None:
            return None
        out["sketch_history"] = self.sketch_history()
        return out


# -- coord.status["health"] rollup -------------------------------------------

# The documented coord.status["health"] schema — walked by the test lane
# like STATUS_TELEMETRY_SCHEMA, so drift breaks CI instead of dashboards.
STATUS_HEALTH_SCHEMA: Dict[str, type] = {
    "schema_version": int,
    "age_s": float,          # staleness stamp (serve-time, freshest report)
    "reporting": int,        # volunteers whose fresh report carried health
    "mixing": dict,          # global + per-zone sketch dispersion (below)
    "mass": dict,            # committed-frac stats + cumulative lost weight
    "quality": dict,         # peer -> merged {score, rounds, flagged, reporters}
    "flagged_peers": list,   # union of reporters' flag sets
    "codec": dict,           # wire -> mean relative error across reporters
}


def rollup_status(fresh_reports: List[dict]) -> Optional[dict]:
    """Merge per-volunteer health summaries (from fresh reports) into the
    versioned ``coord.status["health"]`` rollup. None until some
    volunteer reports health — the telemetry rollup's contract.

    Mixing: sketches are grouped by (dim, seed) — only same-space
    sketches compare — then dispersed globally, per zone, and ACROSS
    zone means (the cross-zone mixing signal the hierarchy's
    ``cross_zone_every_k`` exists to converge)."""
    per_peer: Dict[str, dict] = {}
    for m in fresh_reports:
        h = m.get("health")
        if isinstance(h, dict) and h.get("schema_version") == HEALTH_SCHEMA_VERSION:
            per_peer[str(m.get("peer", "?"))] = h
    if not per_peer:
        return None
    # -- mixing ------------------------------------------------------------
    sketches: List[Tuple[str, str, dict]] = []  # (peer, zone, sketch rec)
    for pid, h in per_peer.items():
        sk = h.get("sketch")
        if isinstance(sk, dict) and sk.get("v"):
            sketches.append((pid, str(h.get("zone") or ""), sk))
    by_space: Dict[Tuple[int, int], list] = {}
    for pid, zone, sk in sketches:
        by_space.setdefault(
            (int(sk.get("dim") or 0), int(sk.get("seed") or 0)), []
        ).append((pid, zone, np.asarray(sk["v"], np.float64)))
    mixing: Dict[str, Any] = {
        "n_sketches": 0, "dispersion": None, "per_zone": {}, "across_zones": None,
    }
    if by_space:
        _, group = max(by_space.items(), key=lambda kv: len(kv[1]))
        mixing["n_sketches"] = len(group)
        mixing["dispersion"] = sketch_dispersion([v for _, _, v in group])
        zones: Dict[str, list] = {}
        for _, zone, v in group:
            zones.setdefault(zone, []).append(v)
        mixing["per_zone"] = {
            z: sketch_dispersion(vs) for z, vs in zones.items()
        }
        if len(zones) >= 2:
            mixing["across_zones"] = sketch_dispersion(
                [np.stack(vs).mean(axis=0) for vs in zones.values()]
            )
    # -- mass --------------------------------------------------------------
    fracs = []
    lost_total = 0.0
    recovered_total = 0.0
    for h in per_peer.values():
        last = (h.get("mass") or {}).get("last")
        if isinstance(last, dict):
            f = last.get("mass_committed_frac")
            if isinstance(f, (int, float)):
                fracs.append(float(f))
            recovered_total += float(last.get("recovered_weight") or 0.0)
        for w in ((h.get("mass") or {}).get("lost_by_peer") or {}).values():
            lost_total += float(w or 0.0)
    mass = {
        "reporting": len(fracs),
        "committed_frac_mean": round(sum(fracs) / len(fracs), 6) if fracs else None,
        "committed_frac_min": round(min(fracs), 6) if fracs else None,
        "lost_weight_total": round(lost_total, 6),
        # Mass the hedged-recovery pipeline saved in the reporters' latest
        # rounds: lost vs recovered side by side is the tail-optimal
        # pipeline's live scorecard.
        "recovered_weight_last": round(recovered_total, 6),
    }
    # -- quality -----------------------------------------------------------
    quality: Dict[str, dict] = {}
    flagged: set = set()
    for h in per_peer.values():
        flagged.update(h.get("flagged") or [])
        for pid, q in (h.get("quality") or {}).items():
            cur = quality.setdefault(
                str(pid),
                {"score": 1.0, "rounds": 0, "flagged": False, "reporters": 0},
            )
            cur["score"] = round(min(cur["score"], float(q.get("score", 1.0))), 4)
            cur["rounds"] += int(q.get("rounds") or 0)
            cur["flagged"] = cur["flagged"] or bool(q.get("flagged"))
            cur["reporters"] += 1
    # -- codec -------------------------------------------------------------
    codec_acc: Dict[str, list] = {}
    for h in per_peer.values():
        for wire, rec in (h.get("codec") or {}).items():
            v = rec.get("rel_err_ewma")
            if isinstance(v, (int, float)):
                codec_acc.setdefault(str(wire), []).append(float(v))
    return {
        "schema_version": HEALTH_SCHEMA_VERSION,
        "reporting": len(per_peer),
        "mixing": mixing,
        "mass": mass,
        "quality": quality,
        "flagged_peers": sorted(flagged),
        "codec": {
            w: round(sum(vs) / len(vs), 8) for w, vs in codec_acc.items()
        },
    }
