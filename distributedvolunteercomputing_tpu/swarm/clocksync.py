"""NTP-style peer clock-offset estimation over the swarm transport.

The wall-clock averaging cadence (``--average-interval-s``;
``Trainer._avg_due``) rendezvouses volunteers at absolute multiples of T —
which until r5 ASSUMED NTP-synced clocks (the r4 MIGRATION known-limitation
and VERDICT directive #9). This module removes the assumption with the
classic two-timestamp exchange: probe a peer, read its clock ``ts``, and
estimate ``offset = ts - (t_send + t_recv) / 2`` (error bounded by RTT/2;
the minimum-RTT sample per peer carries the least queueing noise).

Combining rule: the volunteer adopts the MEDIAN of ``{0} ∪ {per-peer
offsets}`` as a correction to its own clock, accumulated across estimation
rounds. Including the self-sample 0 is what makes a two-node swarm meet in
the middle instead of swapping clocks (each would otherwise correct by the
full pairwise offset simultaneously); with n ≥ 3 honest peers the median
pins the skewed minority to the honest majority's clock while honest nodes
barely move — the same breakdown-point argument as the byzantine
estimators (ops/robust.py). Probes serve the CORRECTED clock, so a late
joiner adopts swarm consensus time in one round even when the whole swarm
has drifted from UTC: the cadence needs internal consistency, not truth.

Reference parity: a coordinator-centric stack gets rendezvous consistency
for free by rendezvousing ON the coordinator; this framework has no
privileged node (SURVEY.md §1 L3), so the correction is peer-to-peer and
byzantine-tolerant like everything else in the tier.

Test hook: ``DVC_CLOCK_SKEW_S`` (read by the volunteer, not here) injects
an artificial skew into a volunteer's local clock, so the e2e suite can
prove rendezvous under multi-second skew (tests/test_interval_cadence.py).
"""

from __future__ import annotations

import asyncio
import random
import statistics
import time
from typing import Callable, Optional

from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

METHOD = "clock.probe"


class ClockSync:
    """Registers ``clock.probe`` and maintains ``offset`` (seconds to ADD
    to the local clock to land on swarm-consensus time).

    ``clock`` is the volunteer's notion of wall time (``time.time`` unless
    a test injects skew). ``now()`` is thread-safe — the trainer thread
    reads it every wall-cadence poll while the asyncio loop re-estimates
    (float attribute assignment is atomic)."""

    def __init__(
        self,
        transport,
        membership,
        *,
        clock: Callable[[], float] = time.time,
        sample_peers: int = 5,
        samples_per_peer: int = 3,
        probe_timeout: float = 3.0,
    ):
        self.transport = transport
        self.membership = membership
        self.clock = clock
        self.sample_peers = int(sample_peers)
        self.samples_per_peer = int(samples_per_peer)
        self.probe_timeout = float(probe_timeout)
        self.offset = 0.0
        self.last_estimate_t: Optional[float] = None
        self._task: Optional[asyncio.Task] = None
        transport.register(METHOD, self._rpc_probe)

    # -- rpc ---------------------------------------------------------------

    async def _rpc_probe(self, args: dict, payload: bytes):
        # Serve the CORRECTED clock (see module docstring): consensus time
        # propagates to probers, raw local time does not.
        return {"t": self.now()}, b""

    # -- estimation --------------------------------------------------------

    def now(self) -> float:
        return self.clock() + self.offset

    async def estimate(self) -> float:
        """One estimation round: probe up to ``sample_peers`` live peers,
        median-combine, accumulate into ``offset``. Returns the new offset.
        Failures (dead peers, timeouts) just shrink the sample — a solo
        volunteer keeps offset unchanged."""
        try:
            peers = await self.membership.alive_peers(include_self=False)
        except Exception as e:  # noqa: BLE001 — estimation must never kill the loop
            log.warning("clock-sync peer listing failed: %s", errstr(e))
            return self.offset
        # Uniform random sample over live peers: deterministic first-N
        # sampling would anchor every volunteer's consensus on the same few
        # (possibly adversarial) early registrants, collapsing the median's
        # breakdown point from "minority of the SWARM" to "minority of a
        # fixed 5-peer panel".
        cands = list(peers.items())
        if len(cands) > self.sample_peers:
            cands = random.sample(cands, self.sample_peers)

        async def probe_peer(pid: str, rec: dict) -> Optional[float]:
            addr = rec.get("addr")
            if not isinstance(addr, (list, tuple)) or len(addr) != 2:
                return None
            addr = (addr[0], int(addr[1]))
            best = None  # (rtt, delta)
            for _ in range(self.samples_per_peer):
                t0 = self.now()
                try:
                    # Pooled transport: only the FIRST probe to a peer pays
                    # the TCP dial; the min-RTT ladder then samples pure
                    # request/response time, so the offset estimate's
                    # RTT/2 error bound tightens to the real network RTT
                    # instead of handshake + slow-start noise.
                    ret, _ = await self.transport.call(
                        addr, METHOD, {}, b"", timeout=self.probe_timeout,
                        connect_timeout=min(2.0, self.probe_timeout),
                    )
                except Exception as e:  # noqa: BLE001
                    log.debug("clock probe to %s failed: %s", pid, errstr(e))
                    break
                t1 = self.now()
                try:
                    ts = float(ret["t"])
                except (KeyError, TypeError, ValueError):
                    break
                rtt = t1 - t0
                delta = ts - 0.5 * (t0 + t1)
                if best is None or rtt < best[0]:
                    best = (rtt, delta)
            return None if best is None else best[1]

        # Concurrent probes: a round costs one probe ladder regardless of
        # dead-peer count (a crashed peer's record lingers for a heartbeat
        # TTL; sequentially its timeouts would stall startup/warmup).
        results = await asyncio.gather(*(probe_peer(p, r) for p, r in cands))
        deltas = [0.0]  # the self-sample: our current corrected clock
        deltas.extend(d for d in results if d is not None)
        if len(deltas) > 1:
            step = float(statistics.median(deltas))
            self.offset += step
            self.last_estimate_t = self.clock()
            if abs(step) > 0.5:
                log.info(
                    "clock-sync: corrected by %+.3fs (total offset %+.3fs, "
                    "%d peers sampled)", step, self.offset, len(deltas) - 1,
                )
        return self.offset

    # -- lifecycle ---------------------------------------------------------

    def start(self, interval_s: float = 30.0, warmup_rounds: int = 5) -> None:
        """Periodic estimation on the running loop.

        The first ``warmup_rounds`` run on a fast (≤3s) cadence: the
        median-with-self rule moves each node at most HALFWAY to its peers
        per round, and nodes join at different times (the very first
        estimate may see an empty swarm), so convergence to a consistent
        swarm clock takes a handful of rounds — which must complete before
        the first averaging boundaries, not one leisurely interval each."""

        async def loop():
            try:
                for _ in range(max(warmup_rounds, 0)):
                    await self.estimate()
                    await asyncio.sleep(min(interval_s, 3.0))
                while True:
                    await self.estimate()
                    await asyncio.sleep(interval_s)
            except asyncio.CancelledError:
                pass

        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
