from distributedvolunteercomputing_tpu.swarm.transport import RPCError, Transport
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode

__all__ = ["Transport", "RPCError", "DHTNode"]
