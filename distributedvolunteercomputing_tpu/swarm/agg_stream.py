"""Streaming chunk-aligned leader aggregation: decode/aggregate overlapped
with arrival, O(N·tile) in-flight memory for the elementwise estimators.

PR 2 made multi-MB contributions CROSS the wire as bounded chunk frames,
but the leader still materialized every peer's full dense f32 buffer before
any aggregation started, and the robust path then paid a second O(N·D) copy
via ``np.stack``. This module is the missing half of that pipeline: the
transport hands each verified contribution chunk to a per-round
``StreamingAggregator`` (via the request-sink plumbing in
``swarm/transport.py``), which decodes it and folds it in immediately —
aggregation overlaps arrival, and the deadline commit reduces to finishing
whatever tiles are still open.

Tiles are aligned 1:1 with the transport's wire chunks (``chunk_bytes``
bytes of f32/bf16 == ``chunk_bytes // esz`` elements), so "one verified
chunk" and "one tile row" are the same event — no re-buffering between the
framing layer and the math.

Aggregation modes, chosen by ``ops.robust.tile_mode(method)``:

- ``mean``     — each arriving chunk is axpy-accumulated straight into one
                 O(D) accumulator (``native.weighted_sum_inplace``) and its
                 bytes released; a per-tile float64 tally records the weight
                 that arrived for that tile, so the deadline commit is one
                 per-tile re-normalization. The leader never holds a
                 per-peer dense vector.
- ``window``   — coordinate-wise estimators (trimmed_mean, median) hold only
                 the in-flight ``[n_slots, tile]`` window per tile: a tile
                 aggregates on a worker thread the moment every armed peer's
                 copy of it has arrived (or at the deadline, over the
                 arrived subset). Peak memory O(N·tile), not O(N·D).
- ``d2_dense`` — krum/bulyan need full vectors for the selected rows, but
                 their O(n²·D) pairwise-distance pass is a sum over
                 coordinates: d² accumulates tile-by-tile as rows fill, so
                 the commit-time selection starts from a finished distance
                 matrix instead of recomputing it.
- ``dense``    — estimators that genuinely couple all coordinates
                 (geometric_median's Weiszfeld iterations, centered_clip's
                 full-vector L2 clipping) keep dense rows; they still gain
                 decode-on-arrival, just not the memory bound.

Partial-contribution semantics (the price of eager commitment): a streamed
contribution that ABORTS mid-payload (corrupt chunk, connection death) has
already folded its sealed tiles into the aggregate — un-doing an axpy needs
the data, which was deliberately released. The committed result is then a
PER-TILE partial-participation aggregate: each tile is a valid weighted
mean / robust estimate over exactly the peers whose copy of that tile
arrived intact. That is the deadline-commit contract applied per tile
rather than per round — every committed coordinate is still a convex
combination (or robust estimate) of honest inputs, and the aborting peer is
reported absent, so its shipped mass is never double-counted by error
feedback (the streaming wires, f32/bf16, carry no EF residual). A slot that
aborts before ANY tile committed is reset cleanly and may retry; one that
aborts after committing tiles is tainted for the round and later pushes
under its key are refused.

Tail-optimal hedged recovery (OptiReduce, PAPERS.md): beside the in-order
original stream, the aggregator accepts **hedged tile-range replies**
(``add_hedged``) — the leader re-requested a straggler's missing tiles over
a second stream (``sync.refetch``) or decoded them from a ring neighbor's
XOR redundancy sidecar. Hedged arrivals are idempotent by (slot, tile): a
per-(slot, tile) arrival bitmap is the single source of truth, so a hedge
and the original can never double-fold one tile, in either order. A slot
whose every tile landed — through any mix of sources — auto-seals; one
sealed with at least one hedged tile is classified ``recovered`` (not
``included``) in ``mass_report``, so the win is auditable per round.
Hedges never resurrect an aborted or tainted slot: replies for those are
counted (``hedge_dropped``) and discarded, and a fenced aggregator counts
hedged chunks with the same ``chunks_after_fence`` bookkeeping the
original stream gets. The per-slot arrival **scoreboard** (tiles present,
missing ranges, last-arrival age) is what the leader's hedge loop ranks
targets from.

Thread model: ``add_chunk``/``add_dense`` run on the event-loop thread (the
transport's frame reader) or an averager worker thread, serialized by one
lock; tile aggregation jobs run via ``asyncio.to_thread`` when a loop is
running (inline otherwise — unit tests stay deterministic); ``finalize``
awaits in-flight jobs, closes open windows over the arrived subsets, and
returns the committed buffer.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from distributedvolunteercomputing_tpu import native
from distributedvolunteercomputing_tpu.ops import mesh_codec as mesh_codec_mod
from distributedvolunteercomputing_tpu.ops import robust
from distributedvolunteercomputing_tpu.swarm import health as health_mod
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

# Sentinel job queued alongside window-closure tuples: "flush the mesh
# mean folder's staged chunks on a worker" (see _spawn_jobs).
_FLUSH = object()


def encode_wire_elems(wire: str, x: np.ndarray) -> bytes:
    """f32 elements -> wire bytes for the elementwise wires (f32/bf16).

    The ONE home of the re-encode rule: the hedge/redundancy paths'
    bit-identical-reencode invariant (refetch serving, tail retention,
    XOR sidecars must all produce the exact bytes the original push
    carried) rests on every encoder agreeing, so there is exactly one."""
    x = np.ascontiguousarray(x, np.float32)
    if wire == "bf16":
        return native.f32_to_bf16(x).tobytes()
    return x.tobytes()


def wire_geometry(wire: str, chunk_bytes: int, n_elems: int) -> Tuple[int, int, int, int]:
    """(element size, chunk bytes, tile elems, n tiles) for an elementwise
    wire — THE tiling rule. The aggregator's bitmap, the refetch range
    RPC, and the redundancy sidecars all address tiles by it, so like
    ``encode_wire_elems`` it has exactly one home: a divergent copy would
    silently shift hedged folds across tile boundaries."""
    esz = 4 if wire == "f32" else 2
    tile_elems = max(int(chunk_bytes) // esz, 1)
    return esz, int(chunk_bytes), tile_elems, max(-(-int(n_elems) // tile_elems), 1)


class TilePool:
    """Reusable float32 scratch buffers, keyed by element count.

    Window buffers and decode staging churn one allocation per tile per
    peer per round without this; the pool caps held bytes so an unusually
    large round can't pin its high-water mark forever."""

    def __init__(self, max_bytes: int = 64 << 20):
        self._lock = threading.Lock()
        self._free: Dict[int, List[np.ndarray]] = {}
        self._held = 0
        self.max_bytes = int(max_bytes)
        self.hits = 0
        self.misses = 0

    def get(self, n_elems: int) -> np.ndarray:
        with self._lock:
            lst = self._free.get(n_elems)
            if lst:
                buf = lst.pop()
                self._held -= buf.nbytes
                self.hits += 1
                return buf
            self.misses += 1
        return np.empty(n_elems, np.float32)

    def put(self, buf: Optional[np.ndarray]) -> None:
        if buf is None or buf.dtype != np.float32:
            return
        with self._lock:
            if self._held + buf.nbytes > self.max_bytes:
                return
            self._free.setdefault(buf.size, []).append(buf)
            self._held += buf.nbytes

    @property
    def held_bytes(self) -> int:
        return self._held


# One process-wide pool: rounds come and go, the buffers stay warm.
_POOL = TilePool()


class _Window:
    """One tile's in-flight [n_slots, tile_elems] row window."""

    __slots__ = ("buf", "mask", "count")

    def __init__(self, buf: np.ndarray, n_slots: int):
        self.buf = buf  # flat pool buffer viewed as [n_slots, tile_elems]
        self.mask = np.zeros(n_slots, bool)
        self.count = 0


class ContributionSink:
    """Transport-facing request sink for ONE streamed contribution.

    The transport calls ``sink(offset, total, data)`` per verified chunk and
    ``sink.close(ok)`` exactly once when the frame completes or dies; both
    are forwarded to the aggregator with this contribution's slot."""

    __slots__ = ("_agg", "slot", "weight", "_on_done", "_closed")

    def __init__(
        self,
        agg: "StreamingAggregator",
        slot: int,
        weight: float,
        on_done: Optional[Callable[[bool], None]] = None,
    ):
        self._agg = agg
        self.slot = slot
        self.weight = float(weight)
        self._on_done = on_done
        self._closed = False

    def __call__(self, off: int, total: int, data: bytes) -> None:
        self._agg.add_chunk(self.slot, self.weight, off, data)

    def close(self, ok: bool) -> None:
        if self._closed:
            return
        self._closed = True
        done = self._agg.seal_slot(self.slot) if ok else False
        if not ok:
            self._agg.abort_slot(self.slot)
        if self._on_done is not None:
            try:
                self._on_done(ok and done)
            except Exception as e:  # noqa: BLE001 — a callback bug must not kill the frame reader
                log.debug("contribution sink callback failed: %s", errstr(e))


class StreamingAggregator:
    """Leader-side streaming aggregation state for one round.

    ``slots`` fixes the armed peer set (the round's members, leader
    included); every contribution is addressed by its slot index. The
    instance is safe for concurrent ``add_chunk``/``add_dense``/``abort``
    from the loop thread and worker threads; ``finalize`` is async and must
    be called exactly once, after which the committed buffer is returned
    and all transient tiles are back in the pool."""

    def __init__(
        self,
        n_elems: int,
        slots: List[str],
        method: str,
        wire: str,
        chunk_bytes: int,
        kw_fn: Optional[Callable[[int], dict]] = None,
        pool: Optional[TilePool] = None,
        codec: Optional[mesh_codec_mod.MeshCodec] = None,
        telemetry=None,
        tail_keep_tiles: int = 0,
    ):
        if wire not in ("f32", "bf16"):
            raise ValueError(f"streaming aggregation needs an elementwise wire, got {wire!r}")
        esz, _, tile_elems, n_tiles = wire_geometry(wire, chunk_bytes, n_elems)
        if chunk_bytes % esz:
            raise ValueError(f"chunk_bytes {chunk_bytes} not {wire} element-aligned")
        self.n_elems = int(n_elems)
        self.wire = wire
        self.esz = esz
        self.chunk_bytes = int(chunk_bytes)
        self.tile_elems = tile_elems
        self.n_tiles = n_tiles
        self.method = method
        self.mode = robust.tile_mode(method)
        self._kw_fn = kw_fn or (lambda n: {})
        self.slots = list(slots)
        self.slot_index = {p: i for i, p in enumerate(self.slots)}
        self.pool = pool or _POOL
        n = len(self.slots)

        self._lock = threading.Lock()
        self.frozen = False
        self._weights: Dict[int, float] = {}  # slot -> folded weight
        self._aborted: Set[int] = set()
        self._tainted: Set[int] = set()
        self._sealed: Set[int] = set()  # slots whose full vector landed
        self._filled = np.zeros(n, np.int64)  # elements received per slot
        self._committed_tiles = np.zeros(n, np.int64)  # tiles folded per slot
        self._tasks: List[asyncio.Task] = []
        # -- tail-optimal hedged recovery state --------------------------
        # The per-(slot, tile) arrival bitmap is the idempotency ledger:
        # one True per tile per slot, set by WHICHEVER source folds it
        # first (original stream, hedged range reply, redundancy decode),
        # checked by every other. _filled stays the ORIGINAL stream's
        # in-order cursor; completeness is _tiles_got == n_tiles.
        self._tile_have = np.zeros((n, self.n_tiles), bool)
        self._tile_hedged = np.zeros((n, self.n_tiles), bool)
        self._tiles_got = np.zeros(n, np.int64)
        self._hedged_tiles = np.zeros(n, np.int64)  # hedge/redund-folded
        # Per-slot arrival timing for the scoreboard (monotonic seconds
        # since t0): first and latest tile arrival from ANY source.
        self._first_at = np.full(n, -1.0)
        self._last_at = np.full(n, -1.0)
        # Seal latency per slot (seconds since arming) — the leader feeds
        # these to the resilience policy's per-peer tail quantiles.
        self._seal_at: Dict[int, float] = {}
        # Summand redundancy: raw wire bytes of the last ``tail_keep_tiles``
        # tiles are retained per (slot, tile) so an XOR sidecar from a ring
        # neighbor can be decoded against the neighbor's own delivered tail
        # at commit time. 0 = retain nothing (redundancy off).
        self.tail_keep_tiles = int(tail_keep_tiles)
        self._tail_bytes: Dict[Tuple[int, int], bytes] = {}

        self._tile_w: Optional[np.ndarray] = None
        self._windows: Dict[int, _Window] = {}
        self._win_done = np.zeros(self.n_tiles, bool)
        # Window mode: complete dense contributions (the leader's own, a
        # parked pre-arming buffer) are held as BORROWED references whose
        # rows copy into a window lazily when a streamed chunk opens it —
        # a dense feed must not materialize every window up front, or the
        # peak regresses to O(N·D) the moment the leader feeds itself.
        self._resident: Dict[int, np.ndarray] = {}
        self._rows: Dict[int, np.ndarray] = {}
        self._d2: Optional[np.ndarray] = None
        self._tile_sealed: Dict[int, List[int]] = {}
        # On-mesh data path: window folds and the mean accumulator run on
        # the volunteer's local device mesh when the codec is active; the
        # host numpy path is both the default (CPU platform) and the
        # degraded-slice fallback (ops.mesh_codec module doc).
        self.codec = codec if codec is not None else mesh_codec_mod.get_default()
        self._folder: Optional[mesh_codec_mod.MeshMeanFolder] = None
        self.folder_flushes = 0
        # Captured from the folder before release() drops it, so the gauges
        # can still say which fold path served a COMMITTED round.
        self.folder_kind = ""
        self.ring_flushes = 0
        # Folder staged-bytes high-water, captured before the folder is
        # dropped (summed into the peak gauge: staged raw chunks are real
        # resident memory beside the accumulator).
        self._folder_staged_peak = 0
        if self.mode == "mean":
            self._tile_w = np.zeros(self.n_tiles, np.float64)
            self._folder = self.codec.mean_folder(
                self.n_elems, self.tile_elems, self.n_tiles, wire
            )
            self.folder_kind = getattr(self._folder, "kind", "")
        elif self.mode == "d2_dense":
            self._d2 = np.zeros((n, n), np.float64)
        # The committed/result buffer is O(D) — except in mean+folder mode,
        # where the DEVICE accumulator plays that role until finalize pulls
        # it: an eager host zeros there would be O(D) counted-but-never-
        # written memory.
        self._out = (
            np.zeros(0, np.float32) if self._folder is not None
            else np.zeros(self.n_elems, np.float32)
        )

        # Telemetry plane (swarm/telemetry.py): per-tile fold latency lands
        # in the unified registry's ``swarm.tile_fold_seconds`` histogram —
        # the in-pipeline evidence behind the leader's ``fold`` span.
        self._tile_hist = (
            telemetry.registry.histogram(
                "swarm.tile_fold_seconds", "window-tile aggregation latency"
            )
            if telemetry is not None and getattr(telemetry, "enabled", False)
            else None
        )
        # Training-health layer (swarm/health.py): per-slot squared
        # distance to the robust aggregate, accumulated tile-by-tile as
        # windows close — the raw material for per-peer contribution-
        # quality attribution. Needs per-peer rows next to the aggregate,
        # so the mean mode (rows released on arrival) can't attribute.
        health = getattr(telemetry, "health", None) if telemetry is not None else None
        self._quality_on = bool(
            health is not None
            and getattr(health, "enabled", False)
            and self.mode != "mean"
        )
        self._q_d2: Dict[int, float] = {}  # slot -> summed d² vs aggregate

        # -- gauges (surfaced via Averager.stats()/volunteer summary) ------
        self.t0 = time.monotonic()
        self.tiles_early = 0  # window tiles aggregated while arrivals were still in flight
        self.tiles_deadline = 0  # window tiles closed over a subset at finalize
        self.busy_s = 0.0  # seconds spent inside aggregation math
        self.streamed_contribs = 0
        self.dense_contribs = 0
        self.aborted_contribs = 0
        # Hedged-recovery gauges: tiles folded from a hedge/redundancy
        # source, hedge replies for tiles that had already landed (wasted
        # wire bytes — the AIMD budget's decrease signal), and replies
        # refused outright (aborted/tainted slot, frozen round).
        self.tiles_recovered = 0
        self.hedge_duplicates = 0
        self.hedge_dropped = 0
        # Leader-failover fencing: True once this aggregator was superseded
        # by a newer round generation (fence()). Chunks that still arrive —
        # a stale sink flushing after its round was deposed — are counted,
        # never folded.
        self.fenced = False
        self.chunks_after_fence = 0
        self._held = self._out.nbytes
        if self._folder is not None:
            # The device-resident accumulator counts against the round's
            # held bytes like any other O(D) state.
            self._held += self._folder.device_bytes
        self.peak_bytes_held = self._held

    # -- memory accounting --------------------------------------------------

    def _note_alloc(self, nbytes: int) -> None:
        self._held += nbytes
        if self._held > self.peak_bytes_held:
            self.peak_bytes_held = self._held

    def _note_free(self, nbytes: int) -> None:
        self._held -= nbytes

    # -- decode ---------------------------------------------------------------

    def _decode(self, data: bytes, out: Optional[np.ndarray] = None) -> np.ndarray:
        if self.wire == "f32":
            x = np.frombuffer(data, np.float32)
            if out is not None:
                out[: x.size] = x
                return out[: x.size]
            return x
        bits = np.frombuffer(data, np.uint16)
        if out is not None:
            return native.bf16_to_f32(bits, out=out[: bits.size])
        return native.bf16_to_f32(bits)

    def _encode_elems(self, x: np.ndarray) -> bytes:
        """f32 elements back to this round's wire form (the inverse of
        _decode; bit-identical for already-roundtripped values)."""
        return encode_wire_elems(self.wire, x)

    # -- sink construction ----------------------------------------------------

    def make_sink(
        self, peer: str, weight: float, total: int,
        on_done: Optional[Callable[[bool], None]] = None,
    ) -> Optional[ContributionSink]:
        """A transport request sink for ``peer``'s streamed contribution, or
        None when this round can't stream it (wrong size, frozen round,
        tainted slot, unknown peer)."""
        slot = self.slot_index.get(peer)
        if slot is None or total != self.n_elems * self.esz:
            return None
        w = float(weight)
        if not np.isfinite(w) or w <= 0:
            return None
        with self._lock:
            if self.frozen or slot in self._tainted or slot in self._sealed:
                return None
            if slot in self._aborted:
                # A cleanly-reset abort (nothing committed) may retry.
                self._aborted.discard(slot)
                self._filled[slot] = 0
            self._weights[slot] = w
        return ContributionSink(self, slot, w, on_done)

    def taints(self, peer: str) -> bool:
        """True when ``peer``'s earlier streamed push committed tiles and
        then died: a later (dense or streamed) contribution under this key
        can no longer enter the round coherently."""
        slot = self.slot_index.get(peer)
        return slot is not None and slot in self._tainted

    # -- ingestion ------------------------------------------------------------

    def add_chunk(self, slot: int, weight: float, off: int, data: bytes) -> None:
        """Fold one verified wire chunk (``off`` in wire-byte space, always
        chunk-aligned by the transport's framing) for ``slot``."""
        total = self.n_elems * self.esz
        if (
            not data
            or off % self.chunk_bytes
            or len(data) != min(self.chunk_bytes, total - off)
        ):
            # Exact-length contract: a sender whose chunk_bytes differs from
            # this aggregator's (version skew, custom embedding) would
            # otherwise fold data across tile boundaries while crediting
            # weight to one tile — silent corruption. Chunk size is a
            # per-Transport constant, never negotiated on the wire, so the
            # only safe response to a mismatch is to poison the slot BEFORE
            # anything folds.
            self.abort_slot(slot)
            return
        tile = off // self.chunk_bytes
        e0 = tile * self.tile_elems
        n = len(data) // self.esz
        fire: List[tuple] = []
        with self._lock:
            if self.fenced:
                self.chunks_after_fence += 1
                return
            if self.frozen or slot in self._aborted or slot in self._tainted:
                return
            if self._filled[slot] != e0:
                # Chunks arrive strictly in order per contribution; a gap
                # means a retry raced an earlier stream — refuse the slot.
                self._aborted.add(slot)
                if self._committed_tiles[slot]:
                    self._tainted.add(slot)
                return
            self._filled[slot] = e0 + n
            if self._tile_have[slot, tile]:
                # A hedged reply folded this tile first: the bitmap wins.
                # The in-order cursor still advances (the stream stays in
                # sync); the redundant copy is the hedge's wasted bytes.
                self.hedge_duplicates += 1
                self._note_arrival_locked(slot)
                return
            t0 = time.perf_counter()
            self._fold_tile_locked(slot, weight, tile, e0, n, data, fire)
            self._mark_tile_locked(slot, tile, hedged=False)
            self.busy_s += time.perf_counter() - t0
        self._spawn_jobs(fire)

    def _fold_tile_locked(
        self, slot: int, weight: float, tile: int, e0: int, n: int,
        data: bytes, fire: List, *, hedged: bool = False,
    ) -> None:
        """Fold one verified tile's wire bytes for ``slot`` — the shared
        body behind the original stream (add_chunk) and hedged replies
        (add_hedged). Caller holds the lock and has already established
        the (slot, tile) is unfolded."""
        if self.mode == "mean":
            if self._folder is not None:
                # On-mesh: stage the RAW wire bytes (no decode on the
                # frame-reader thread); a worker flushes staged batches
                # through one fused device decode+scatter-add.
                if self._folder.add(tile, weight, data):
                    fire.append(_FLUSH)
            else:
                x = self._decode(data)
                native.weighted_sum_inplace(self._out[e0 : e0 + n], x, weight)
            self._tile_w[tile] += weight
            self._committed_tiles[slot] += 1
            if not hedged:
                # "Folded while the push was in flight" — a hedged tile
                # is counted under tiles_recovered instead, never both.
                self.tiles_early += 1
        elif self.mode == "window":
            self._window_row(slot, tile, self._decode(data), n, fire)
        else:  # d2_dense / dense
            row = self._row_buffer(slot)
            self._decode(data, out=row[e0:])
            self._committed_tiles[slot] += 1
            if self.mode == "d2_dense":
                self._accumulate_d2(slot, tile, e0, e0 + n)
        if self.tail_keep_tiles and tile >= self.n_tiles - self.tail_keep_tiles:
            # Summand redundancy: tail tiles double as XOR-decode keys for
            # a ring neighbor's sidecar, so their wire bytes are retained
            # (bounded: tail_keep_tiles x chunk_bytes per slot).
            self._tail_bytes[(slot, tile)] = bytes(data)

    def _note_arrival_locked(self, slot: int) -> None:
        now = time.monotonic() - self.t0
        if self._first_at[slot] < 0:
            self._first_at[slot] = now
        self._last_at[slot] = now

    def _mark_tile_locked(self, slot: int, tile: int, *, hedged: bool) -> None:
        """Record one folded (slot, tile) in the idempotency bitmap and
        auto-seal the slot the moment its last tile lands — completeness
        is tile-count, not the in-order cursor, so a contribution finished
        by hedged replies seals exactly like a purely-streamed one.
        Caller holds the lock."""
        self._tile_have[slot, tile] = True
        self._tiles_got[slot] += 1
        self._note_arrival_locked(slot)
        if hedged:
            self._tile_hedged[slot, tile] = True
            self._hedged_tiles[slot] += 1
            self.tiles_recovered += 1
        if (
            self._tiles_got[slot] == self.n_tiles
            and slot not in self._sealed
            and slot not in self._aborted
            and slot not in self._tainted
        ):
            self._sealed.add(slot)
            self._seal_at[slot] = self._last_at[slot]
            self.streamed_contribs += 1

    def add_hedged(
        self, peer: str, weight: float, off: int, data: bytes,
        *, source: str = "refetch",
    ) -> int:
        """Fold one hedged tile reply (a ``sync.refetch`` range chunk or a
        redundancy-sidecar decode) for ``peer``. Idempotent by (slot,
        tile): a tile the original stream (or an earlier hedge) already
        folded is counted as a duplicate and discarded — a hedge and the
        original can never double-fold. Unlike the original stream, a
        malformed reply only drops itself (the healthy original must not
        be poisoned by a bad hedge), and an aborted/tainted slot is never
        resurrected. Returns 1 when the tile folded, 0 otherwise."""
        slot = self.slot_index.get(peer)
        total = self.n_elems * self.esz
        if (
            slot is None
            or not data
            or off % self.chunk_bytes
            or off >= total
            or len(data) != min(self.chunk_bytes, total - off)
        ):
            with self._lock:
                self.hedge_dropped += 1
            return 0
        tile = off // self.chunk_bytes
        e0 = tile * self.tile_elems
        n = len(data) // self.esz
        fire: List[tuple] = []
        with self._lock:
            if self.fenced:
                self.chunks_after_fence += 1
                return 0
            if self.frozen or slot in self._aborted or slot in self._tainted:
                self.hedge_dropped += 1
                return 0
            if slot in self._sealed or self._tile_have[slot, tile]:
                self.hedge_duplicates += 1
                return 0
            w = self._weights.get(slot)
            if w is None:
                # A silent straggler never declared a weight; the refetch
                # reply carries the one its push would have (first write
                # wins — a started stream's declared weight is kept).
                w = float(weight)
                if not np.isfinite(w) or w <= 0:
                    self.hedge_dropped += 1
                    return 0
                self._weights[slot] = w
            t0 = time.perf_counter()
            self._fold_tile_locked(slot, w, tile, e0, n, data, fire, hedged=True)
            self._mark_tile_locked(slot, tile, hedged=True)
            self.busy_s += time.perf_counter() - t0
        self._spawn_jobs(fire)
        return 1

    def _spawn_jobs(self, fire: List) -> None:
        """Spawn queued aggregation work OUTSIDE the lock: window-closure
        tuples from _fire_locked, or the _FLUSH sentinel for the mesh mean
        folder."""
        folder = self._folder
        for job in fire:
            if job is _FLUSH:
                if folder is not None:  # raced a release(): nothing to flush
                    self._spawn(folder.flush)
            else:
                t, w, r = job
                self._spawn(lambda tt=t, ww=w, rr=r: self._aggregate_window(tt, ww, rr))

    def add_dense(self, peer: str, weight: float, buf: np.ndarray) -> bool:
        """Fold a complete dense contribution (the leader's own, a parked
        pre-arming buffer, or an inline sub-chunk payload). Returns False —
        contribution NOT folded — once the round is frozen."""
        slot = self.slot_index.get(peer)
        if slot is None or buf.size != self.n_elems:
            return False
        w = float(weight)
        fire: List[tuple] = []
        with self._lock:
            if self.frozen or slot in self._aborted or slot in self._tainted or slot in self._sealed:
                return False
            # Tiles a hedged reply (or an aborted-then-retried stream's
            # surviving bitmap) already folded must not fold again: the
            # dense feed covers exactly the MISSING tiles. The common case
            # (no prior tile state) reduces to the whole-vector fast path.
            partial = bool(self._tiles_got[slot])
            w = float(self._weights.get(slot, w))
            t0 = time.perf_counter()
            if self.mode == "mean":
                if self._folder is not None:
                    if partial:
                        # The device folder stages whole vectors only; a
                        # per-tile dense backfill under it would need wire
                        # re-encoding on the loop thread. Rare (auth +
                        # hedge overlap) — refuse, the hedges own the slot.
                        return False
                    self._folder.add_dense(buf, w)
                    self._tile_w += w
                    self._committed_tiles[slot] += self.n_tiles
                elif partial:
                    b32 = np.ascontiguousarray(buf, np.float32)
                    for tile in range(self.n_tiles):
                        if self._tile_have[slot, tile]:
                            continue
                        e0 = tile * self.tile_elems
                        e1 = min(e0 + self.tile_elems, self.n_elems)
                        native.weighted_sum_inplace(self._out[e0:e1], b32[e0:e1], w)
                        self._tile_w[tile] += w
                        self._committed_tiles[slot] += 1
                else:
                    native.weighted_sum_inplace(
                        self._out, np.ascontiguousarray(buf, np.float32), w
                    )
                    self._tile_w += w
                    self._committed_tiles[slot] += self.n_tiles
            elif self.mode == "window":
                # Borrowed reference, not a copy: rows flow into windows
                # lazily (open ones now, future ones at creation, the rest
                # at finalize). A tile that already aggregated EARLY before
                # this feed excludes it — the same per-tile participation
                # contract streamed stragglers get.
                ref = np.ascontiguousarray(buf, np.float32)
                self._resident[slot] = ref
                for tile, win in list(self._windows.items()):
                    if win.mask[slot]:
                        continue
                    e0 = tile * self.tile_elems
                    n = min(self.tile_elems, self.n_elems - e0)
                    row0 = slot * self.tile_elems
                    win.buf[row0 : row0 + n] = ref[e0 : e0 + n]
                    win.mask[slot] = True
                    win.count += 1
                    if win.count >= self._active_slots():
                        fire.append(self._fire_locked(tile, win, early=True))
            else:
                row = self._row_buffer(slot)
                row[:] = buf
                for tile in range(self.n_tiles):
                    if self._tile_have[slot, tile]:
                        continue  # hedge-folded: d2/commit already counted
                    self._committed_tiles[slot] += 1
                    if self.mode == "d2_dense":
                        e0 = tile * self.tile_elems
                        self._accumulate_d2(
                            slot, tile, e0, min(e0 + self.tile_elems, self.n_elems)
                        )
            if self.tail_keep_tiles:
                # Retain the tail tiles' WIRE form (re-encoded from the
                # dense feed — bit-identical for f32/bf16 roundtrips) so
                # this slot can serve as a ring neighbor's XOR-decode key.
                b32 = np.ascontiguousarray(buf, np.float32)
                for tile in range(self.n_tiles - self.tail_keep_tiles, self.n_tiles):
                    if tile < 0 or (slot, tile) in self._tail_bytes:
                        continue
                    e0 = tile * self.tile_elems
                    e1 = min(e0 + self.tile_elems, self.n_elems)
                    self._tail_bytes[(slot, tile)] = self._encode_elems(b32[e0:e1])
            self.busy_s += time.perf_counter() - t0
            self._filled[slot] = self.n_elems
            self._tile_have[slot, :] = True
            self._tiles_got[slot] = self.n_tiles
            self._note_arrival_locked(slot)
            self._sealed.add(slot)
            self._seal_at.setdefault(slot, self._last_at[slot])
            self._weights[slot] = w
            self.dense_contribs += 1
        self._spawn_jobs(fire)
        return True

    def seal_slot(self, slot: int) -> bool:
        """Mark a streamed contribution complete; False when it didn't
        actually deliver every element (short stream). Completeness is
        TILE count, not the in-order cursor: a contribution whose tail a
        hedge delivered seals (auto-sealed by the last fold already; this
        just confirms it to the sink lifecycle)."""
        with self._lock:
            if slot in self._aborted or slot in self._tainted:
                return False
            if slot in self._sealed:
                return True
            if self._tiles_got[slot] != self.n_tiles:
                return False
            # Unreachable in practice (_mark_tile_locked auto-seals at the
            # last fold) — kept as the sink lifecycle's backstop.
            self._sealed.add(slot)
            self._seal_at.setdefault(slot, time.monotonic() - self.t0)
            self.streamed_contribs += 1
            return True

    def abort_slot(self, slot: int) -> None:
        """A streamed contribution died mid-payload. Tiles it already
        committed stand (per-tile participation, module doc); open window
        rows are withdrawn; a slot with committed tiles is tainted."""
        fire: List[tuple] = []
        with self._lock:
            if slot in self._aborted or slot in self._sealed or self.frozen:
                self._aborted.add(slot)
                return
            self._aborted.add(slot)
            self.aborted_contribs += 1
            if self.mode in ("mean", "window") and self._committed_tiles[slot]:
                # Irreversibly folded tiles (axpy'd / aggregated): the slot
                # can't coherently re-enter this round.
                self._tainted.add(slot)
            if self.mode in ("d2_dense", "dense"):
                # Nothing irreversible happened (rows are retained until
                # finalize): a retry starts clean — including the tile
                # bitmap, or the retry's chunks would read as duplicates.
                self._committed_tiles[slot] = 0
                self._tile_have[slot, :] = False
                self._tile_hedged[slot, :] = False
                self._tiles_got[slot] = 0
                self._hedged_tiles[slot] = 0
            if self.mode == "window":
                for tile, win in self._windows.items():
                    if win.mask[slot]:
                        win.mask[slot] = False
                        win.count -= 1
                        # Withdrawn rows leave the idempotency bitmap too:
                        # only CLOSED tiles stand, and a clean retry's
                        # chunks must not read as duplicates.
                        if self._tile_have[slot, tile]:
                            self._tile_have[slot, tile] = False
                            self._tiles_got[slot] -= 1
                            if self._tile_hedged[slot, tile]:
                                self._tile_hedged[slot, tile] = False
                                self._hedged_tiles[slot] -= 1
                # Its absence may be exactly what held the remaining
                # windows open — re-check the early-fire condition.
                active = self._active_slots()
                for tile, win in list(self._windows.items()):
                    if win.count and win.count >= active:
                        fire.append(self._fire_locked(tile, win, early=True))
            elif self.mode in ("d2_dense", "dense"):
                row = self._rows.pop(slot, None)
                if row is not None:
                    self._note_free(row.nbytes)
                    self.pool.put(row)
                # Withdraw its pairwise-d² participation so a clean retry
                # can't double-accumulate pairs it already contributed.
                for peers in self._tile_sealed.values():
                    if slot in peers:
                        peers.remove(slot)
                if self._d2 is not None:
                    self._d2[slot, :] = 0.0
                    self._d2[:, slot] = 0.0
        self._spawn_jobs(fire)

    # -- internals ------------------------------------------------------------

    def _active_slots(self) -> int:
        return len(self.slots) - len(self._aborted)

    def _row_buffer(self, slot: int) -> np.ndarray:
        row = self._rows.get(slot)
        if row is None:
            row = self.pool.get(self.n_elems)
            self._note_alloc(row.nbytes)
            self._rows[slot] = row
        return row

    def _fire_locked(self, tile: int, win: _Window, early: bool):
        """Commit one window's CLOSURE atomically (caller holds the lock):
        the tile is done, its rows are committed, and the window leaves the
        in-flight dict — all before the aggregation math runs, so neither
        an abort nor a clean-retry re-stream can reopen or double-count the
        tile while the worker job is still in flight. Returns the job args
        for the caller to spawn OUTSIDE the lock."""
        self._windows.pop(tile, None)
        self._win_done[tile] = True
        rows = np.flatnonzero(win.mask)
        self._committed_tiles[rows] += 1
        if early:
            self.tiles_early += 1
        else:
            self.tiles_deadline += 1
        return (tile, win, rows)

    def _window_row(
        self, slot: int, tile: int, x: np.ndarray, n: int,
        fire: List[tuple],
    ) -> None:
        """Place one decoded tile row; when every active slot has
        contributed it, close the window (atomically, via _fire_locked) and
        queue its aggregation job on ``fire`` for the caller to spawn
        OUTSIDE the lock. Caller holds the lock."""
        if self._win_done[tile]:
            return  # tile already closed (late row after an early fire)
        win = self._windows.get(tile)
        if win is None:
            flat = self.pool.get(len(self.slots) * self.tile_elems)
            self._note_alloc(flat.nbytes)
            win = self._windows[tile] = _Window(flat, len(self.slots))
            # Seed the new window with every resident dense contribution.
            e0 = tile * self.tile_elems
            for rslot, ref in self._resident.items():
                if rslot == slot or rslot in self._aborted:
                    continue
                rn = min(self.tile_elems, self.n_elems - e0)
                win.buf[rslot * self.tile_elems : rslot * self.tile_elems + rn] = (
                    ref[e0 : e0 + rn]
                )
                win.mask[rslot] = True
                win.count += 1
        win.buf[slot * self.tile_elems : slot * self.tile_elems + n] = x[:n]
        if not win.mask[slot]:
            win.mask[slot] = True
            win.count += 1
        if win.count >= self._active_slots():
            fire.append(self._fire_locked(tile, win, early=True))

    def _aggregate_window(self, tile: int, win: _Window, rows: np.ndarray) -> None:
        """The aggregation math for one ALREADY-CLOSED tile (closure —
        done flag, committed rows — happened in _fire_locked): robust-
        aggregate the arrived rows into the output slice, return the window
        buffer to the pool. Runs on a worker thread when a loop is
        available; an exception here propagates out of finalize() and fails
        the round rather than committing a silently-zeroed tile."""
        t0 = time.perf_counter()
        e0 = tile * self.tile_elems
        n = min(self.tile_elems, self.n_elems - e0)
        q: Optional[np.ndarray] = None
        try:
            if rows.size:
                stack = win.buf[: len(self.slots) * self.tile_elems].reshape(
                    len(self.slots), self.tile_elems
                )[rows, :n]
                kw = self._kw_fn(rows.size)
                # On-mesh window fold when the codec is active (sorting
                # network over the peer axis); ops.robust numpy otherwise.
                agg = self.codec.aggregate(
                    np.ascontiguousarray(stack), self.method, **kw
                )
                self._out[e0 : e0 + n] = agg
                if self._quality_on and rows.size >= 3:
                    # Quality attribution: each arrived row's squared
                    # distance to the tile's robust aggregate — one extra
                    # O(rows·tile) pass next to the fold's sort, gated off
                    # with the health probe.
                    q = health_mod.row_d2(stack, agg)
        finally:
            dt = time.perf_counter() - t0
            if self._tile_hist is not None:
                self._tile_hist.observe(dt, method=self.method)
            with self._lock:
                self.busy_s += dt
                if q is not None:
                    for slot, d2 in zip(rows, q):
                        self._q_d2[int(slot)] = self._q_d2.get(int(slot), 0.0) + float(d2)
                self._note_free(win.buf.nbytes)
                self.pool.put(win.buf)

    def _accumulate_d2(self, slot: int, tile: int, e0: int, e1: int) -> None:
        """Tile-wise pairwise squared-distance accumulation (krum/bulyan):
        d² is a plain sum over coordinates, so each sealed tile adds its
        partial distances against every slot that already sealed the same
        tile. Caller holds the lock.

        Streamed chunks run this inline on the event loop — ms-scale per
        chunk (one tile × already-sealed peers), amortized across arrival,
        and a deferred job could race abort's row-withdrawal/pool-reuse.
        The O(n·D) dense feeds land via asyncio.to_thread at the call
        sites, so the loop never eats a whole contribution's d² at once."""
        peers = self._tile_sealed.setdefault(tile, [])
        a64 = self._rows[slot][e0:e1].astype(np.float64)
        for other in peers:
            if other == slot:
                continue
            b_row = self._rows.get(other)
            if b_row is None:
                continue
            d = a64 - b_row[e0:e1]
            v = float(np.dot(d, d))
            self._d2[slot, other] += v
            self._d2[other, slot] += v
        peers.append(slot)

    def _spawn(self, fn: Callable[[], None]) -> None:
        """Run an aggregation job off the event loop when one is running,
        inline otherwise (synchronous tests, worker-thread callers)."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            fn()
            return
        task = loop.create_task(asyncio.to_thread(fn))
        self._tasks.append(task)

    # -- commit ---------------------------------------------------------------

    def freeze(self) -> None:
        """Stop accepting contributions (the deadline hit): later chunks and
        dense feeds become no-ops. ``finalize`` then closes what's open —
        split from it so the caller can fix round membership between the
        two without racing in-flight feeds.

        Fully-delivered streams whose close() hasn't run yet (the commit
        can interleave with a frame's trailing-MAC read) are auto-sealed:
        every chunk CRC-verified and folded, so the mass IS in the
        aggregate — the peer must be reported included, not absent."""
        with self._lock:
            self.frozen = True
            for slot in range(len(self.slots)):
                if (
                    slot not in self._sealed
                    and slot not in self._aborted
                    and slot not in self._tainted
                    and self._tiles_got[slot] == self.n_tiles
                ):
                    self._sealed.add(slot)
                    self._seal_at.setdefault(slot, time.monotonic() - self.t0)
                    self.streamed_contribs += 1

    def fence(self) -> None:
        """Supersede this aggregator under a newer round generation (leader
        failover re-arm over the same epoch): freeze, return every
        transient buffer to the pool, and from here on COUNT — never fold —
        any chunk a stale sink still delivers. The partially-committed
        tiles this aggregator holds are abandoned with it; the recovery
        round re-collects the same contributions into a fresh aggregator,
        so no half-folded mass from the deposed generation can leak into
        the recovered result."""
        with self._lock:
            self.fenced = True
        self.release()

    def progress(self) -> Dict[str, int]:
        """Per-peer elements received so far (streamed or dense) — the
        mid-round visibility probe failover phase instrumentation and the
        chaos campaign read to tell 'pre-arm' from 'mid-stream'."""
        with self._lock:
            return {p: int(self._filled[i]) for i, p in enumerate(self.slots)}

    def weight_of(self, peer: str) -> float:
        """The weight a peer's contribution was folded with (0.0 if it
        never fed this round)."""
        slot = self.slot_index.get(peer)
        with self._lock:
            return float(self._weights.get(slot, 0.0)) if slot is not None else 0.0

    def included_peers(self) -> List[str]:
        """Peers whose COMPLETE contribution entered the aggregate."""
        with self._lock:
            return [self.slots[s] for s in sorted(self._sealed)]

    def mass_report(self, shard_of: Optional[Dict[str, int]] = None) -> dict:
        """Balanced gradient-mass classification for this round (training-
        health layer, swarm/health.py): every armed slot lands in exactly
        one of included (sealed purely by its own stream) / recovered
        (sealed with at least one hedge/redundancy-folded tile — the
        tail-optimal pipeline's auditable win) / aborted (died mid-payload
        or tainted) / excluded (never sealed by the freeze — late,
        partial, or silent), with the weight it DECLARED (0 for a slot
        that never spoke — its undelivered mass is unknowable to the
        leader, so it balances as one excluded slot at weight 0).
        included + recovered + excluded + aborted weight sums to the total
        armed weight by construction; the property test exercises the
        classification across the deadline / abort / hedge / fence
        matrix.

        ``shard_of`` (zone-sharded training) tags each peer's entry with
        its shard domain so ``health.mass_by_shard`` can roll the buckets
        up per shard — a shard-holder death then shows as mass moving to
        recovered/excluded in ONE shard's bucket, not as a fleet-wide
        dip. Peers absent from the map are left untagged."""
        with self._lock:
            per_peer: Dict[str, dict] = {}
            for slot, pid in enumerate(self.slots):
                w = float(self._weights.get(slot, 0.0))
                if slot in self._sealed:
                    oc = "recovered" if self._hedged_tiles[slot] else "included"
                elif slot in self._aborted or slot in self._tainted:
                    oc = "aborted"
                else:
                    oc = "excluded"
                per_peer[pid] = {"outcome": oc, "weight": w}
                if shard_of is not None and pid in shard_of:
                    per_peer[pid]["shard"] = int(shard_of[pid])
        return health_mod.mass_report_from_per_peer(per_peer)

    # -- tail-optimal hedged recovery surface --------------------------------

    def scoreboard(self) -> Dict[str, dict]:
        """Per-peer tile-arrival scoreboard — what the leader's hedge loop
        ranks re-request targets from. ``missing`` is the contiguous
        [t0, t1) tile ranges not yet folded from any source;
        ``last_arrival_age_s`` is None until the slot's first tile."""
        now = time.monotonic() - self.t0
        with self._lock:
            out: Dict[str, dict] = {}
            for slot, pid in enumerate(self.slots):
                last = self._last_at[slot]
                out[pid] = {
                    "tiles_got": int(self._tiles_got[slot]),
                    "n_tiles": self.n_tiles,
                    "hedged_tiles": int(self._hedged_tiles[slot]),
                    "sealed": slot in self._sealed,
                    "aborted": slot in self._aborted or slot in self._tainted,
                    "started": bool(self._first_at[slot] >= 0.0),
                    "last_arrival_age_s": (
                        round(now - last, 6) if last >= 0.0 else None
                    ),
                    "missing": self._missing_ranges_locked(slot),
                }
            return out

    def _missing_ranges_locked(self, slot: int) -> List[Tuple[int, int]]:
        # Fast paths first: the hedge loop polls this under the ingest
        # lock every ~200 ms, and most slots are either COMPLETE (sealed/
        # dense) or UNTOUCHED (silent) — neither needs the bitmap scan
        # (at 1e6 tiles the flatnonzero temp alone is MBs per slot).
        got = int(self._tiles_got[slot])
        if got == self.n_tiles:
            return []
        if got == 0:
            return [(0, self.n_tiles)]
        missing = np.flatnonzero(~self._tile_have[slot])
        if missing.size == 0:
            return []
        ranges: List[Tuple[int, int]] = []
        start = prev = int(missing[0])
        for t in missing[1:]:
            t = int(t)
            if t == prev + 1:
                prev = t
                continue
            ranges.append((start, prev + 1))
            start = prev = t
        ranges.append((start, prev + 1))
        return ranges

    def tail_bytes(self, peer: str, tile: int) -> Optional[bytes]:
        """The retained wire bytes of one of ``peer``'s tail tiles (None
        unless redundancy retention covered it and the tile arrived) —
        the XOR-decode key for a ring neighbor's sidecar."""
        slot = self.slot_index.get(peer)
        if slot is None:
            return None
        with self._lock:
            return self._tail_bytes.get((slot, tile))

    def seal_latencies(self) -> Dict[str, float]:
        """Seconds from arming to each sealed contribution's completion —
        the leader feeds these into the resilience policy's per-peer tail
        quantiles (the hedge-target ranking evidence)."""
        with self._lock:
            return {
                self.slots[s]: round(dt, 6) for s, dt in self._seal_at.items()
            }

    def hedge_stats(self) -> Dict[str, int]:
        """Hedge-outcome counters for this round (AIMD feedback + gauges)."""
        with self._lock:
            return {
                "tiles_recovered": int(self.tiles_recovered),
                "hedge_duplicates": int(self.hedge_duplicates),
                "hedge_dropped": int(self.hedge_dropped),
                "slots_recovered": sum(
                    1 for s in self._sealed if self._hedged_tiles[s]
                ),
            }

    def quality_d2(self) -> Dict[str, float]:
        """Per-peer summed squared distance to the committed aggregate
        (accumulated across window tiles / the dense fold); empty when the
        health probe is off or the method is ``mean``."""
        with self._lock:
            return {self.slots[s]: d2 for s, d2 in self._q_d2.items()}

    async def finalize(self, included: Optional[List[str]] = None) -> np.ndarray:
        """Freeze arrivals, close open windows over the arrived subsets,
        await in-flight tile jobs, and return the committed buffer; every
        transient tile goes back to the pool. A failed tile job raises —
        the round must FAIL loudly, never commit a silently-zeroed tile."""
        self.freeze()
        leftovers: List[tuple] = []
        with self._lock:
            for tile, win in list(self._windows.items()):
                if win.count:
                    leftovers.append(self._fire_locked(tile, win, early=False))
                else:
                    # Empty window (every row withdrawn): nothing to close.
                    self._windows.pop(tile, None)
                    self._note_free(win.buf.nbytes)
                    self.pool.put(win.buf)
        self._spawn_jobs(leftovers)
        if self._tasks:
            results = await asyncio.gather(*self._tasks, return_exceptions=True)
            self._tasks.clear()
            for r in results:
                if isinstance(r, BaseException):
                    raise RuntimeError(f"tile aggregation failed: {r!r}") from r
        out = await asyncio.to_thread(self._finalize_blocking, included)
        self.release()  # transient rows/windows back to the pool NOW
        return out

    def _finalize_blocking(self, included: Optional[List[str]]) -> np.ndarray:
        t0 = time.perf_counter()
        try:
            if self.mode == "mean":
                if self._folder is not None:
                    # Pull the device accumulator (tail chunks flushed);
                    # re-normalization below is shared with the host path.
                    self._out = np.ascontiguousarray(
                        self._folder.result(), np.float32
                    )
                    self.folder_flushes = self._folder.flushes
                    self.ring_flushes = int(
                        getattr(self._folder, "ring_flushes", 0)
                    )
                # Per-tile re-normalization by the weight that ARRIVED: the
                # deadline-commit re-weighting, applied at tile granularity.
                for tile in range(self.n_tiles):
                    e0 = tile * self.tile_elems
                    w = self._tile_w[tile]
                    if w > 0:
                        self._out[e0 : e0 + self.tile_elems] *= np.float32(1.0 / w)
                return self._out
            if self.mode == "window":
                # Tiles no streamed chunk ever opened (e.g. every push
                # landed dense/pre-arming) close here over the residents.
                if self._resident:
                    rows = [
                        s for s in sorted(self._resident) if s not in self._aborted
                    ]
                    for tile in range(self.n_tiles):
                        if self._win_done[tile] or tile in self._windows or not rows:
                            continue
                        e0 = tile * self.tile_elems
                        n = min(self.tile_elems, self.n_elems - e0)
                        stack = np.stack(
                            [self._resident[s][e0 : e0 + n] for s in rows]
                        )
                        agg = self.codec.aggregate(
                            stack, self.method, **self._kw_fn(len(rows))
                        )
                        self._out[e0 : e0 + n] = agg
                        if self._quality_on and len(rows) >= 3:
                            for s, d2 in zip(rows, health_mod.row_d2(stack, agg)):
                                self._q_d2[s] = self._q_d2.get(s, 0.0) + float(d2)
                        self._win_done[tile] = True
                        self.tiles_deadline += 1
                return self._out
            # d2_dense / dense: stack the complete rows and run the dense
            # estimator (selection from the PRE-ACCUMULATED d² for krum/
            # bulyan). Completeness is TILE count, not the in-order cursor:
            # a hedge-completed row (out-of-order tiles, cursor never
            # advanced) is complete and must aggregate — it was REPORTED
            # recovered, so dropping it here would commit the accounting
            # without the mass.
            slots = sorted(
                self.slot_index[p]
                for p in (included if included is not None else self.included_peers())
                if self.slot_index.get(p) in self._rows
                and self._tiles_got[self.slot_index[p]] == self.n_tiles
            )
            if not slots:
                return self._out
            stack = np.stack([self._rows[s] for s in slots])
            kw = self._kw_fn(len(slots))
            if self.mode == "d2_dense" and self._d2 is not None:
                kw = dict(kw, d2=self._d2[np.ix_(slots, slots)].astype(np.float32))
            self._out = self.codec.aggregate(stack, self.method, **kw)
            if self._quality_on and len(slots) >= 3:
                # Dense-path quality attribution (krum/bulyan/geomedian/
                # centered_clip): one O(n·D) distance pass against the
                # aggregate the estimator just selected.
                for s, d2 in zip(slots, health_mod.row_d2(stack, self._out)):
                    self._q_d2[s] = self._q_d2.get(s, 0.0) + float(d2)
            return self._out
        finally:
            self.busy_s += time.perf_counter() - t0

    def release(self) -> None:
        """Return every transient buffer to the pool (skipped/failed round)."""
        with self._lock:
            self.frozen = True
            for win in self._windows.values():
                self._note_free(win.buf.nbytes)
                self.pool.put(win.buf)
            self._windows.clear()
            for row in self._rows.values():
                self._note_free(row.nbytes)
                self.pool.put(row)
            self._rows.clear()
            self._resident.clear()  # borrowed references: just drop them
            self._tail_bytes.clear()  # redundancy retention dies with the round
            if self._folder is not None:
                # Device accumulator freed with the round (committed rounds
                # already pulled result(); failed/fenced ones abandon it).
                self._folder_staged_peak = max(
                    self._folder_staged_peak, self._folder.peak_staged_bytes
                )
                self._note_free(self._folder.device_bytes)
                self._folder = None

    def gauges(self) -> dict:
        wall = max(time.monotonic() - self.t0, 1e-9)
        folder = self._folder
        staged_peak = max(
            self._folder_staged_peak,
            folder.peak_staged_bytes if folder is not None else 0,
        )
        return {
            "mode": self.mode,
            # Accumulator/window/row high-water PLUS the mesh folder's
            # staged raw-chunk high-water (summed peaks: a slight
            # over-count of the true concurrent peak, never an under-count).
            "peak_bytes_held": int(self.peak_bytes_held + staged_peak),
            "tiles_early": int(self.tiles_early),
            "tiles_deadline": int(self.tiles_deadline),
            "agg_busy_s": round(self.busy_s, 6),
            "agg_busy_frac": round(min(self.busy_s / wall, 1.0), 4),
            "streamed_contribs": int(self.streamed_contribs),
            "dense_contribs": int(self.dense_contribs),
            "aborted_contribs": int(self.aborted_contribs),
            # Tail-optimal hedged recovery (per-round view; the averager
            # rolls these into cumulative stats).
            "tiles_recovered": int(self.tiles_recovered),
            "hedge_duplicates": int(self.hedge_duplicates),
            "hedge_dropped": int(self.hedge_dropped),
            "fenced": bool(self.fenced),
            "chunks_after_fence": int(self.chunks_after_fence),
            # On-mesh data path: which backend folded this round (may read
            # "host" after a mid-round degrade — that IS the signal).
            "codec_backend": self.codec.backend,
            "folder_flushes": int(self.folder_flushes),
            # "ring" when the fused reduce pipeline (ops.mesh_collective)
            # carries the mean folds, "staged" for the PR 5 staged path,
            # "" when the round has no folder (non-mean modes / host codec).
            # Captured at construction so it survives release().
            "folder_kind": self.folder_kind,
            "ring_flushes": int(
                getattr(folder, "ring_flushes", None) or self.ring_flushes
            ),
        }
