"""Coordinator: swarm bootstrap node + liveness registry + metrics sink.

Reference parity: the ``coordinator.py`` entrypoint "bootstraps the swarm:
initial DHT node, rendezvous address, liveness registry" (SURVEY.md §2,
BASELINE.json:5). It does NO device work (SURVEY.md §3-A) — one asyncio
process serving DHT RPCs, collecting per-volunteer metrics, and evicting the
dead (by TTL expiry, which the DHT does for free).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, Optional, Tuple

from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.membership import PEERS_KEY
from distributedvolunteercomputing_tpu.swarm.transport import Transport
from distributedvolunteercomputing_tpu.utils.logging import get_logger

log = get_logger(__name__)


class Coordinator:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_path: Optional[str] = None,
        advertise_host: Optional[str] = None,
        secret: Optional[bytes] = None,
    ):
        self.transport = Transport(host, port, advertise_host=advertise_host, secret=secret)
        self.dht = DHTNode(self.transport)
        self.metrics_path = metrics_path
        self.latest_metrics: Dict[str, dict] = {}
        self._t0 = time.time()
        self.transport.register("coord.report", self._rpc_report)
        self.transport.register("coord.status", self._rpc_status)

    async def start(self) -> Tuple[str, int]:
        from distributedvolunteercomputing_tpu.utils.asyncio_debug import maybe_enable_from_env

        # DVC_ASYNC_DEBUG=1: loop stall/race detectors (stopped in close())
        self._loop_monitor = maybe_enable_from_env()
        addr = await self.transport.start()
        await self.dht.start(bootstrap=None)
        log.info("coordinator listening on %s:%d", *addr)
        return addr

    async def close(self) -> None:
        await self.dht.stop()
        if getattr(self, "_loop_monitor", None) is not None:
            await self._loop_monitor.stop()
        await self.transport.close()

    # -- RPCs --------------------------------------------------------------

    async def _rpc_report(self, args: dict, payload: bytes):
        """Volunteers push per-step metrics; coordinator aggregates swarm-level."""
        peer = args.get("peer", "?")
        self.latest_metrics[peer] = {**args, "recv_t": time.time()}
        if self.metrics_path:
            with open(self.metrics_path, "a") as fh:
                fh.write(json.dumps(self.latest_metrics[peer]) + "\n")
        return {"ok": True}, b""

    async def _rpc_status(self, args: dict, payload: bytes):
        """Swarm-level view: alive peers + aggregate samples/sec."""
        peers = await self.dht.get(PEERS_KEY)
        alive = {pid: rec for pid, rec in peers.items() if rec is not None}
        fresh = [
            m for m in self.latest_metrics.values() if time.time() - m["recv_t"] < 60.0
        ]
        agg_sps = sum(float(m.get("samples_per_sec", 0.0)) for m in fresh)
        return {
            "alive": alive,
            "n_alive": len(alive),
            "swarm_samples_per_sec": agg_sps,
            "uptime_s": time.time() - self._t0,
            # Transport-level counters (per-peer bytes/RPCs/connects/latency
            # EWMA): the coordinator's own WAN vantage, one `coord.status`
            # away for operators.
            "transport": self.transport.stats(),
            # Per-volunteer leader-aggregation pipeline gauges (peak bytes
            # held, tiles aggregated early vs at-deadline, aggregate-thread
            # busy fraction) from the freshest reports — empty until some
            # volunteer has led a streaming round.
            "aggregation": {
                m.get("peer", "?"): m["aggregation"]
                for m in fresh
                if m.get("aggregation")
            },
            # Per-volunteer leader-failover gauges (leaders deposed, rounds
            # recovered by a successor, recovery latency) — empty until a
            # volunteer has lived through a leader death.
            "failover": {
                m.get("peer", "?"): m["failover"]
                for m in fresh
                if m.get("failover")
            },
        }, b""


async def run_coordinator_forever(
    host: str,
    port: int,
    metrics_path: Optional[str] = None,
    advertise_host: Optional[str] = None,
    secret: Optional[bytes] = None,
) -> None:
    coord = Coordinator(host, port, metrics_path, advertise_host=advertise_host, secret=secret)
    addr = await coord.start()
    print(f"COORDINATOR_READY {addr[0]}:{addr[1]}", flush=True)
    try:
        while True:
            await asyncio.sleep(10.0)
            status, _ = await coord._rpc_status({}, b"")
            log.info("swarm status: %s", status)
    except asyncio.CancelledError:
        await coord.close()
