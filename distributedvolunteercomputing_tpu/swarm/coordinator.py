"""Coordinator: a stateless front for the replicated control plane.

Reference parity: the ``coordinator.py`` entrypoint "bootstraps the swarm:
initial DHT node, rendezvous address, liveness registry" (SURVEY.md §2,
BASELINE.json:5). Since the control-plane PR it holds NO authoritative
state: it is one DHT node plus one ``ControlPlaneReplica``
(swarm/control_plane.py) — membership records, metrics rollups, and the
replica set itself are TTL'd DHT soft state, sharded by key range across
every elected replica (any volunteer run with ``--host-replica`` is a
candidate too). Kill this process mid-training and a surviving replica
serves ``coord.status`` within one heartbeat interval; volunteers' batched
heartbeat/report traffic fails over on conn failure, exactly like the PR-4
leader-deposal path.

SIGTERM (the TPU-VM preemption notice) retires gracefully: a "retiring"
tombstone under ``cp/replicas`` makes volunteers and peer replicas
re-resolve the active set immediately instead of waiting for the record's
TTL.
"""

from __future__ import annotations

import asyncio
import signal
import time
from typing import Optional, Tuple

from distributedvolunteercomputing_tpu.swarm.control_plane import ControlPlaneReplica
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.transport import Transport
from distributedvolunteercomputing_tpu.utils.logging import get_logger

log = get_logger(__name__)


class Coordinator:
    """Swarm bootstrap node hosting one control-plane replica. The public
    surface (``coord.report``/``coord.status`` RPCs, ``_rpc_status`` for
    in-process callers) is unchanged from the single-host coordinator; the
    state behind it moved into the DHT."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_path: Optional[str] = None,
        advertise_host: Optional[str] = None,
        secret: Optional[bytes] = None,
        rid: Optional[str] = None,
    ):
        self.transport = Transport(host, port, advertise_host=advertise_host, secret=secret)
        self.dht = DHTNode(self.transport)
        self.replica = ControlPlaneReplica(
            self.transport, self.dht, rid=rid, metrics_path=metrics_path,
        )

    async def start(self) -> Tuple[str, int]:
        from distributedvolunteercomputing_tpu.utils.asyncio_debug import maybe_enable_from_env

        # DVC_ASYNC_DEBUG=1: loop stall/race detectors (stopped in close())
        self._loop_monitor = maybe_enable_from_env()
        addr = await self.transport.start()
        await self.dht.start(bootstrap=None)
        await self.replica.start()
        log.info("coordinator listening on %s:%d", *addr)
        return addr

    async def close(self) -> None:
        await self.replica.stop()
        await self.dht.stop()
        if getattr(self, "_loop_monitor", None) is not None:
            await self._loop_monitor.stop()
        await self.transport.close()

    async def retire(self, grace: float = 0.5) -> None:
        """Graceful SIGTERM path: publish the retiring tombstone, drain,
        then close."""
        await self.replica.retire(grace=grace)
        await self.close()

    # Back-compat passthroughs: in-process callers (tests, the forever
    # loop) talk to the coordinator, the replica does the work. The window
    # views flatten the replica's per-shard windows back into the flat
    # lists the single-host coordinator kept.

    @property
    def latest_metrics(self):
        return self.replica.latest_metrics

    @property
    def _commit_window(self):
        return sorted(
            (td for w in self.replica._commit_window.values() for td in w),
            key=lambda td: td[0],
        )

    @property
    def _xz_window(self):
        return sorted(
            (td for w in self.replica._xz_window.values() for td in w),
            key=lambda td: td[0],
        )

    def _multigroup_rollup(self, fresh: list):
        return self.replica._multigroup_rollup(
            fresh, self._commit_window, self._xz_window
        )

    async def _rpc_report(self, args: dict, payload: bytes):
        return await self.replica._rpc_report(args, payload)

    async def _rpc_status(self, args: dict, payload: bytes):
        return await self.replica._rpc_status(args, payload)


async def run_coordinator_forever(
    host: str,
    port: int,
    metrics_path: Optional[str] = None,
    advertise_host: Optional[str] = None,
    secret: Optional[bytes] = None,
) -> None:
    coord = Coordinator(host, port, metrics_path, advertise_host=advertise_host, secret=secret)
    # SIGTERM = preemption notice: retire gracefully (publish the retiring
    # tombstone so volunteers re-resolve replicas IMMEDIATELY) instead of
    # vanishing and leaving them to discover the corpse by conn failure.
    # Installed BEFORE the ready line: a supervisor that kills the moment
    # the coordinator reports ready must still get the graceful path.
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(signal.SIGTERM, stop.set)
    except (NotImplementedError, RuntimeError):  # non-main thread / Windows
        pass
    addr = await coord.start()
    print(f"COORDINATOR_READY {addr[0]}:{addr[1]}", flush=True)
    try:
        last_log = time.monotonic()
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(), timeout=1.0)
            except asyncio.TimeoutError:
                pass
            if time.monotonic() - last_log >= 10.0:
                last_log = time.monotonic()
                status, _ = await coord._rpc_status({}, b"")
                log.info("swarm status: %s", status)
        log.info("SIGTERM: retiring coordinator replica")
        await coord.retire()
    except asyncio.CancelledError:
        await coord.close()
