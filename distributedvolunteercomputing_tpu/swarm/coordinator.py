"""Coordinator: swarm bootstrap node + liveness registry + metrics sink.

Reference parity: the ``coordinator.py`` entrypoint "bootstraps the swarm:
initial DHT node, rendezvous address, liveness registry" (SURVEY.md §2,
BASELINE.json:5). It does NO device work (SURVEY.md §3-A) — one asyncio
process serving DHT RPCs, collecting per-volunteer metrics, and evicting the
dead (by TTL expiry, which the DHT does for free).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, Optional, Tuple

from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.membership import PEERS_KEY
from distributedvolunteercomputing_tpu.swarm.transport import Transport
from distributedvolunteercomputing_tpu.utils.logging import get_logger

log = get_logger(__name__)


class Coordinator:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_path: Optional[str] = None,
        advertise_host: Optional[str] = None,
        secret: Optional[bytes] = None,
    ):
        self.transport = Transport(host, port, advertise_host=advertise_host, secret=secret)
        self.dht = DHTNode(self.transport)
        self.metrics_path = metrics_path
        self.latest_metrics: Dict[str, dict] = {}
        self._t0 = time.time()
        # Swarm-wide committed-round rate (multi-group rollup): per-peer
        # last-seen cumulative rounds_ok, and a sliding window of
        # (recv_t, delta) increments the status RPC sums over the last
        # minute — a rate no single volunteer's flat counter can show.
        self._commit_seen: Dict[str, int] = {}
        self._commit_window: list = []
        # Cross-zone byte rate (hierarchical-schedule rollup), tracked the
        # same way: per-peer last-seen cumulative cross-zone bytes SENT
        # (sent-side only, so each wire byte is counted once across the
        # swarm — the same definition hierarchy_bench.json uses) and a
        # sliding window of increments, so status can report
        # cross_zone_bytes_per_commit — the hierarchical schedule's
        # headline metric — live.
        self._xz_seen: Dict[str, int] = {}
        self._xz_window: list = []
        self.transport.register("coord.report", self._rpc_report)
        self.transport.register("coord.status", self._rpc_status)

    COMMIT_WINDOW_S = 60.0
    # Volunteer ids are fresh uuids per process, so churn would grow the
    # per-peer maps without bound on a long-running coordinator; a peer
    # silent this long is dropped (a late reappearance re-seeds its commit
    # baseline at delta 0, identical to first sight).
    STALE_PEER_TTL_S = 600.0

    async def start(self) -> Tuple[str, int]:
        from distributedvolunteercomputing_tpu.utils.asyncio_debug import maybe_enable_from_env

        # DVC_ASYNC_DEBUG=1: loop stall/race detectors (stopped in close())
        self._loop_monitor = maybe_enable_from_env()
        addr = await self.transport.start()
        await self.dht.start(bootstrap=None)
        log.info("coordinator listening on %s:%d", *addr)
        return addr

    async def close(self) -> None:
        await self.dht.stop()
        if getattr(self, "_loop_monitor", None) is not None:
            await self._loop_monitor.stop()
        await self.transport.close()

    # -- RPCs --------------------------------------------------------------

    async def _rpc_report(self, args: dict, payload: bytes):
        """Volunteers push per-step metrics; coordinator aggregates swarm-level."""
        peer = args.get("peer", "?")
        now = time.time()
        self.latest_metrics[peer] = {**args, "recv_t": now}
        groups = args.get("groups")
        if isinstance(groups, dict):
            total = groups.get("rounds_ok")
            if isinstance(total, int):
                prev = self._commit_seen.get(peer)
                self._commit_seen[peer] = total
                if prev is None:
                    # First sight of this peer (fresh coordinator joining a
                    # long-running swarm, or a new volunteer): seed the
                    # baseline only — injecting the lifetime total would
                    # report a bogus commit burst for the next window.
                    delta = 0
                elif total >= prev:
                    delta = total - prev
                else:
                    # Counter went backwards = the volunteer restarted;
                    # count from zero, don't subtract history.
                    delta = total
                if delta > 0:
                    self._commit_window.append((now, delta))
            xz = groups.get("cross_zone_bytes_sent")
            if isinstance(xz, int):
                prev = self._xz_seen.get(peer)
                self._xz_seen[peer] = xz
                # Unlike the commit counter, a DECREASE here re-baselines
                # at delta 0 rather than counting from zero: the byte sum
                # is cumulative-but-not-strictly-monotone (peer-stats LRU
                # eviction or a zone re-attribution can dip it), and
                # "count from zero" would re-inject a volunteer's entire
                # lifetime cross-zone bytes as one phantom burst. A real
                # volunteer restart just loses the first window's bytes.
                xdelta = xz - prev if prev is not None and xz >= prev else 0
                if xdelta > 0:
                    self._xz_window.append((now, xdelta))
            cutoff = now - self.COMMIT_WINDOW_S
            self._commit_window = [
                (t, d) for t, d in self._commit_window if t >= cutoff
            ]
            self._xz_window = [
                (t, d) for t, d in self._xz_window if t >= cutoff
            ]
        for p in [
            p for p, m in self.latest_metrics.items()
            if now - m["recv_t"] > self.STALE_PEER_TTL_S
        ]:
            self.latest_metrics.pop(p, None)
            self._commit_seen.pop(p, None)
            self._xz_seen.pop(p, None)
        if self.metrics_path:
            with open(self.metrics_path, "a") as fh:
                fh.write(json.dumps(self.latest_metrics[peer]) + "\n")
        return {"ok": True}, b""

    def _multigroup_rollup(self, fresh: list) -> Optional[dict]:
        """Swarm-level view of the rotating group schedule, from the fresh
        reports that carry ``groups`` gauges. Namespaced PER GROUP — the
        flat per-peer maps elsewhere in status would silently average
        across groups — plus the rollups a dashboard needs: groups active
        this rotation, committed-round rate, and the slowest group's lag
        behind its last commit."""
        gstats = {
            m.get("peer", "?"): m["groups"]
            for m in fresh
            if isinstance(m.get("groups"), dict) and m["groups"].get("enabled")
        }
        if not gstats:
            return None
        now = time.time()
        rot = max(
            (gs.get("rot") for gs in gstats.values() if gs.get("rot") is not None),
            default=None,
        )
        active = {
            gs["group_id"] for gs in gstats.values() if gs.get("group_id")
        }
        # Per-group breakdown, merged across reporters. Counters are
        # volunteer-rounds (a committed group round counts once per member
        # that saw it commit) — a participation measure, not a round count.
        per_group: Dict[str, dict] = {}
        for peer, gs in gstats.items():
            for gid, rec in (gs.get("recent") or {}).items():
                g = per_group.setdefault(
                    gid,
                    {"volunteers": 0, "rounds_ok": 0, "rounds_skipped": 0,
                     "rounds_degraded": 0, "last_commit_t": None},
                )
                g["volunteers"] += 1
                for k in ("rounds_ok", "rounds_skipped", "rounds_degraded"):
                    g[k] += int(rec.get(k) or 0)
                t = rec.get("last_commit_t")
                if t is not None and (
                    g["last_commit_t"] is None or t > g["last_commit_t"]
                ):
                    g["last_commit_t"] = t
        # Slowest ACTIVE group's lag behind its last commit (volunteer
        # clocks, so skew-accurate only to ClockSync quality): the
        # "is any group silently stuck" gauge.
        lags = [
            now - per_group[gid]["last_commit_t"]
            for gid in active
            if gid in per_group and per_group[gid]["last_commit_t"] is not None
        ]
        # Per-zone breakdown (hierarchical schedule): volunteers, commit
        # totals, and each zone's cross-zone byte footprint — so an
        # operator sees WHICH zone is burning WAN bytes or lagging, not
        # one flat number averaging a DC slice against a home DSL line.
        per_zone: Dict[str, dict] = {}
        per_level: Dict[str, dict] = {}
        for gs in gstats.values():
            z = per_zone.setdefault(
                str(gs.get("zone") or ""),
                {"volunteers": 0, "rounds_ok": 0,
                 "cross_zone_bytes_sent": 0, "cross_zone_bytes_received": 0},
            )
            z["volunteers"] += 1
            z["rounds_ok"] += int(gs.get("rounds_ok") or 0)
            for k in ("cross_zone_bytes_sent", "cross_zone_bytes_received"):
                z[k] += int(gs.get(k) or 0)
            for lv, rec in (gs.get("levels") or {}).items():
                agg = per_level.setdefault(
                    str(lv),
                    {"rounds_ok": 0, "rounds_skipped": 0, "rounds_degraded": 0},
                )
                for k in agg:
                    agg[k] += int(rec.get(k) or 0)
        cutoff = now - self.COMMIT_WINDOW_S
        commits = sum(d for t, d in self._commit_window if t >= cutoff)
        xz_bytes = sum(d for t, d in self._xz_window if t >= cutoff)
        return {
            "volunteers": len(gstats),
            "rot": rot,
            "groups_active": len(active),
            "rounds_ok_total": sum(
                int(gs.get("rounds_ok") or 0) for gs in gstats.values()
            ),
            "commits_per_min": round(
                commits * 60.0 / self.COMMIT_WINDOW_S, 2
            ),
            "slowest_group_lag_s": round(max(lags), 3) if lags else None,
            "per_group": per_group,
            "per_zone": per_zone,
            "per_level": per_level or None,
            # The hierarchical schedule's headline metric, live: WAN bytes
            # that crossed a zone boundary (sent-side counters, each wire
            # byte counted once — the hierarchy_bench definition) per
            # committed volunteer-round, over the sliding window (None
            # until a commit lands in it).
            "cross_zone_bytes_per_commit": (
                round(xz_bytes / commits, 1) if commits else None
            ),
        }

    async def _rpc_status(self, args: dict, payload: bytes):
        """Swarm-level view: alive peers + aggregate samples/sec."""
        peers = await self.dht.get(PEERS_KEY)
        alive = {pid: rec for pid, rec in peers.items() if rec is not None}
        fresh = [
            m for m in self.latest_metrics.values() if time.time() - m["recv_t"] < 60.0
        ]
        agg_sps = sum(float(m.get("samples_per_sec", 0.0)) for m in fresh)
        multigroup = self._multigroup_rollup(fresh)
        return {
            # Rotating group-schedule rollup (None until some volunteer
            # reports multi-group gauges): per-group commit health plus
            # the swarm-wide rate/lag numbers.
            "multigroup": multigroup,
            "alive": alive,
            "n_alive": len(alive),
            "swarm_samples_per_sec": agg_sps,
            "uptime_s": time.time() - self._t0,
            # Transport-level counters (per-peer bytes/RPCs/connects/latency
            # EWMA): the coordinator's own WAN vantage, one `coord.status`
            # away for operators.
            "transport": self.transport.stats(),
            # Per-volunteer leader-aggregation pipeline gauges (peak bytes
            # held, tiles aggregated early vs at-deadline, aggregate-thread
            # busy fraction) from the freshest reports — empty until some
            # volunteer has led a streaming round.
            "aggregation": {
                m.get("peer", "?"): m["aggregation"]
                for m in fresh
                if m.get("aggregation")
            },
            # Per-volunteer leader-failover gauges (leaders deposed, rounds
            # recovered by a successor, recovery latency) — empty until a
            # volunteer has lived through a leader death.
            "failover": {
                m.get("peer", "?"): m["failover"]
                for m in fresh
                if m.get("failover")
            },
        }, b""


async def run_coordinator_forever(
    host: str,
    port: int,
    metrics_path: Optional[str] = None,
    advertise_host: Optional[str] = None,
    secret: Optional[bytes] = None,
) -> None:
    coord = Coordinator(host, port, metrics_path, advertise_host=advertise_host, secret=secret)
    addr = await coord.start()
    print(f"COORDINATOR_READY {addr[0]}:{addr[1]}", flush=True)
    try:
        while True:
            await asyncio.sleep(10.0)
            status, _ = await coord._rpc_status({}, b"")
            log.info("swarm status: %s", status)
    except asyncio.CancelledError:
        await coord.close()
