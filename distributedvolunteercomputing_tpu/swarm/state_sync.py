"""Peer-pull state synchronisation: join the swarm at the swarm's step.

The capability that makes churn recovery real (SURVEY.md §5
checkpoint/resume): a volunteer that (re)joins — fresh process, restored
preemption, long absence — pulls the freshest params straight from a live
peer instead of training from its cold init and poisoning the next averaging
round with stale weights (the hivemind ``load_state_from_peers`` role, done
the swarm's way: DHT announcement + transport RPCs).

Protocol:
- every provider periodically announces ``state/<namespace>`` in the DHT
  with its current step (subkey = peer_id, TTL'd like heartbeats);
- a puller reads the key, targets the highest announced step above its own,
  and fetches the flattened f32 payload in CHUNKS (``state.fetch`` with
  offset/length). The first chunk opens a session: the provider serializes
  its tree ONCE and pins the buffer for the session, so a multi-chunk pull
  is a consistent snapshot even while the provider keeps training. Every
  chunk rides the transport's CRC-checked framing, so a flipped byte in any
  chunk fails that chunk, not the whole transfer;
- the puller validates the total length against ITS OWN schema before
  adopting (a wrong-model payload can't be loaded) and runs a sanity guard
  (finite, magnitude-bounded) so a garbage provider can't hand a rejoiner
  NaN params; it walks down the candidate list on failure.

What the payload is: the SYNC SUBTREE, not necessarily the full params. The
volunteer wires the model bundle's ``avg_select``/``avg_merge`` through this
service, so a LoRA model ships only its adapters (~1000x less than the
frozen base, which every volunteer reconstructs bit-identically from the
task-constant ``init_seed``).

Trust model (byzantine mode): a pulled state comes from ONE provider; the
sanity guard rejects gross poison (NaN/Inf/absurd magnitudes) but a
malicious provider could serve subtly-wrong values. This is accepted under
the HONEST-MAJORITY assumption the byzantine averager itself rests on: the
rejoiner's very next averaging round contracts it toward the robust
aggregate of the group, so a poisoned pull survives at most one averaging
interval and the poisoner's own round contributions are trimmed by the
estimator. (Cross-checking a second provider cannot distinguish malice from
normal between-round drift — two honest peers at the same step legitimately
differ by their local steps — so it would reject honest providers.)

Optimizer moments are NOT transferred: a pulled state resumes with a cold
optimizer at the correct step (the standard trade — moments are 2x params of
extra WAN bytes for marginal benefit after averaging rounds resync anyway).
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from distributedvolunteercomputing_tpu import native
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.transport import Addr, RPCError, Transport
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger
from distributedvolunteercomputing_tpu.utils.pytree import (
    flatten_to_buffer,
    tree_specs,
    unflatten_from_buffer,
)

log = get_logger(__name__)

# (step, sync_subtree) supplier — reads the live trainer state.
StateProvider = Callable[[], Tuple[int, Any]]

# Per-chunk payload bytes. Well under the transport's frame guard; big
# enough that a GPT-2-small full tree (~500 MB f32) is ~8 chunks.
DEFAULT_CHUNK_BYTES = 64 * 1024 * 1024


class _Session:
    __slots__ = ("buf", "step", "t0", "pending")

    def __init__(self):
        self.buf = b""
        self.step = 0
        self.t0 = time.monotonic()
        self.pending = True  # reserved (counts toward the cap) but not filled


class StateSyncService:
    # Concurrent pinned serializations; each holds one payload-sized buffer.
    MAX_SESSIONS = 2
    SESSION_TTL = 180.0
    # Sanity bound for adopted values: trained params live in O(1); 1e4
    # already means something is deeply wrong (guards garbage providers).
    MAX_ABS_VALUE = 1e4

    def __init__(
        self,
        transport: Transport,
        dht: DHTNode,
        peer_id: str,
        namespace: str,
        announce_ttl: float = 30.0,
        fetch_timeout: float = 60.0,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        wire: str = "f32",
    ):
        # Wire codec for SERVED state (this side's provider role): bf16
        # halves and q8 quarters the rejoin transfer, at the same tolerance
        # the averaging wire already accepts. The puller decodes whatever
        # the provider's fetch meta declares, so mixed-wire swarms still
        # sync. (topk is grads-only and meaningless for a params snapshot.)
        if wire not in ("f32", "bf16", "q8"):
            raise ValueError(f"unknown state-sync wire {wire!r}")
        self.transport = transport
        self.dht = dht
        self.peer_id = peer_id
        self.namespace = namespace
        self.announce_ttl = announce_ttl
        self.fetch_timeout = fetch_timeout
        self.chunk_bytes = int(chunk_bytes)
        self.wire = wire
        self._provider: Optional[StateProvider] = None
        self._sessions: Dict[str, _Session] = {}
        # Extra fields merged into every announce record (zone-sharded
        # volunteers ride their shard assignment here, so a rejoiner can
        # tell full-tree providers from shard-holders before dialing one
        # that only serves 1/K of what it needs).
        self.extra_announce: Optional[Callable[[], dict]] = None
        transport.register("state.fetch", self._rpc_fetch)

    @property
    def key(self) -> str:
        return f"state/{self.namespace}"

    def set_provider(self, provider: StateProvider) -> None:
        self._provider = provider

    # -- provider side -----------------------------------------------------

    async def announce(self) -> None:
        """Publish (addr, step) under the state key; call periodically."""
        if self._provider is None:
            return
        step, _ = self._provider()
        rec = {"addr": list(self.transport.addr), "step": int(step)}
        if self.extra_announce is not None:
            try:
                rec.update(self.extra_announce() or {})
            except Exception as e:  # noqa: BLE001 — announce must not die on a gauge
                log.debug("extra_announce failed: %s", errstr(e))
        await self.dht.store(
            self.key,
            rec,
            subkey=self.peer_id,
            ttl=self.announce_ttl,
        )

    def _sweep_sessions(self) -> None:
        now = time.monotonic()
        for sid in [s for s, st in self._sessions.items() if now - st.t0 > self.SESSION_TTL]:
            del self._sessions[sid]

    async def _rpc_fetch(self, args: dict, payload: bytes):
        """Chunked fetch. args: {session, offset, length}. offset 0 (or a
        new session id) serializes and PINS the provider's current tree, so
        later chunks come from the same snapshot; the final chunk (or an
        unconditional expiry timer) releases it."""
        if self._provider is None:
            raise RPCError("no state to serve yet")
        self._sweep_sessions()
        session = str(args.get("session", "")) or uuid.uuid4().hex
        offset = int(args.get("offset", 0))
        length = int(args.get("length", 0)) or self.chunk_bytes
        st = self._sessions.get(session)
        if st is not None and st.pending:
            # Another connection's open is mid-serialization; this session id
            # is not usable by anyone else.
            raise RPCError("state session still opening")
        if st is None:
            if offset != 0:
                raise RPCError("unknown state session (expired or never opened)")
            if len(self._sessions) >= self.MAX_SESSIONS:
                raise RPCError("state session cap reached; retry shortly")
            # Reserve BEFORE the await: concurrent opens each hold a slot, so
            # N simultaneous rejoiners can never pin more than MAX_SESSIONS
            # payload-sized buffers (the cap-check-then-insert race).
            st = self._sessions[session] = _Session()
            try:
                step, tree = self._provider()

                def _serialize() -> bytes:
                    buf, _, _ = flatten_to_buffer(tree)
                    if self.wire == "bf16":
                        return native.f32_to_bf16(buf).tobytes()
                    if self.wire == "q8":
                        return native.q8_encode(buf)
                    return buf.tobytes()

                # Param-sized flatten+copy off the event loop: serving state
                # must not stall heartbeats/averaging RPCs for a big memcpy.
                st.buf = await asyncio.to_thread(_serialize)
                st.step = int(step)
                st.pending = False
            except BaseException:
                self._sessions.pop(session, None)
                raise
            # Unconditional expiry: a puller that dies after chunk 0 must not
            # pin this buffer until the NEXT fetch RPC happens to sweep — two
            # such aborts would block all state serving for SESSION_TTL.
            asyncio.get_running_loop().call_later(
                self.SESSION_TTL, self._sessions.pop, session, None
            )
        chunk = st.buf[offset : offset + length]
        done = offset + len(chunk) >= len(st.buf)
        if done:
            self._sessions.pop(session, None)
        return (
            {
                "step": st.step,
                "session": session,
                "total": len(st.buf),
                "offset": offset,
                "done": done,
                "wire": self.wire,
            },
            chunk,
        )

    # -- puller side -------------------------------------------------------

    async def _candidates(self, min_step: int) -> List[Tuple[int, str, Addr]]:
        records = await self.dht.get(self.key)
        out = []
        for pid, rec in records.items():
            if pid == self.peer_id or not isinstance(rec, dict):
                continue
            try:
                step = int(rec["step"])
                host, port = rec["addr"]
                addr = (str(host), int(port))
            except (KeyError, TypeError, ValueError):
                continue
            if step > min_step:
                out.append((step, pid, addr))
        out.sort(reverse=True)  # freshest first
        return out

    @staticmethod
    def _expected_bytes(wire: str, n_elems: int) -> int:
        """Exact coded size of an n_elems f32 tree under each wire. Raises
        on unknown wires — silently treating a foreign codec as raw f32
        would let same-sized garbage through the size check."""
        if wire == "bf16":
            return 2 * n_elems
        if wire == "q8":
            return native.q8_coded_size(n_elems)
        if wire == "f32":
            return 4 * n_elems
        raise RPCError(f"provider declared unknown wire {wire!r}")

    async def _fetch_all(self, addr: Addr, n_elems: int) -> Tuple[int, str, bytearray]:
        """Pull the full buffer from one provider in chunks; returns
        (provider_step, wire, payload). Raises on any failure — caller
        moves on. The provider's first response declares its wire codec;
        the total must match that codec's exact size for our schema.
        Chunks write straight into one preallocated buffer: collecting
        parts and joining would hold ~2x the payload at the join."""
        out: Optional[bytearray] = None
        wire = "f32"
        session = ""
        offset = 0
        while True:
            # Every session chunk rides ONE pooled connection (the transport
            # chunk-frames each 64 MB RPC payload into wire chunks with
            # per-chunk CRCs); the dial is bounded separately so a dead
            # provider costs seconds, not the 60 s transfer budget.
            ret, chunk = await self.transport.call(
                addr,
                "state.fetch",
                {"peer": self.peer_id, "session": session, "offset": offset,
                 "length": self.chunk_bytes},
                timeout=self.fetch_timeout,
                connect_timeout=5.0,
                # Bulk transfer: must not poison the control-plane latency
                # EWMA the failure detector suspects on.
                record_latency=False,
            )
            total = int(ret["total"])
            if out is None:  # first response: wire + size validation
                wire = str(ret.get("wire", "f32"))
                expect_bytes = self._expected_bytes(wire, n_elems)
                if total != expect_bytes:
                    raise RPCError(
                        f"provider buffer {total}B != local schema "
                        f"{expect_bytes}B (wire={wire})"
                    )
                out = bytearray(total)
            elif total != len(out):
                raise RPCError("provider total changed mid-session")
            if int(ret["offset"]) != offset or not chunk or offset + len(chunk) > total:
                raise RPCError("chunk sequencing error")
            out[offset : offset + len(chunk)] = chunk
            offset += len(chunk)
            session = str(ret["session"])
            if ret.get("done"):
                if offset != total:
                    raise RPCError("provider finished short of its own total")
                break
        return int(ret["step"]), wire, out

    def _sane(self, buf: np.ndarray) -> bool:
        """Finite and magnitude-bounded, allocation-free: NaN propagates
        through min/max and fails both comparisons; +/-Inf fails the bound.
        (np.isfinite().all() + np.abs() would allocate ~1.25x the payload on
        the memory-tight rejoin path.)"""
        if buf.size == 0:
            return True
        lo = float(np.min(buf))
        hi = float(np.max(buf))
        return -self.MAX_ABS_VALUE < lo <= hi < self.MAX_ABS_VALUE

    async def pull(
        self, local_tree: Any, local_step: int, min_lead: int = 1
    ) -> Optional[Tuple[int, Any]]:
        """Fetch the sync subtree from the freshest peer at least
        ``min_lead`` steps ahead; returns (step, tree) or None (nobody
        ahead / all fetches failed — both normal, the caller trains on)."""
        # Schema only — no param-sized buffer materialized on the pull side.
        specs, treedef = tree_specs(local_tree)
        expect = int(sum(s.size for s in specs))
        for step, pid, addr in await self._candidates(local_step + min_lead - 1):
            try:
                got_step, wire, payload = await self._fetch_all(addr, expect)
                if wire == "bf16":
                    buf = native.bf16_to_f32(np.frombuffer(payload, np.uint16))
                elif wire == "q8":
                    buf = native.q8_decode(payload)  # accepts the bytearray; no copy
                else:
                    buf = np.frombuffer(payload, np.float32)
                if buf.size != expect:
                    raise RPCError(f"decoded {buf.size} elems != schema {expect}")
                if not self._sane(buf):
                    log.warning(
                        "state pull from %s failed the sanity guard "
                        "(non-finite or absurd values); trying next", pid,
                    )
                    continue
                log.info(
                    "pulled state at step %d from %s (%d bytes, %d-byte chunks)",
                    got_step, pid, len(payload), self.chunk_bytes,
                )
                # No defensive copy: unflatten's astype copies each chunk out
                # of the read-only frombuffer view.
                return got_step, unflatten_from_buffer(buf, specs, treedef)
            except (RPCError, OSError, asyncio.TimeoutError, ValueError) as e:
                log.info("state pull from %s failed (%s); trying next", pid, errstr(e))
        return None
